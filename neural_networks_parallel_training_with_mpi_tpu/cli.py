"""CLI entrypoint.

Usage (the TPU-native analogue of the reference's
``mpiexec -n numprocs python dataParallelTraining_NN_MPI.py --lr --momentum
--batch_size --nepochs``, README.md:12):

    python -m neural_networks_parallel_training_with_mpi_tpu \
        --lr 0.001 --momentum 0.9 --batch_size 4 --nepochs 3

No external launcher is needed on a single host: parallelism comes from the
device mesh, not from process replication.  On multi-host pods, run the same
command on every host (the TPU runtime provides world configuration).
"""

from __future__ import annotations

import sys

from .config import build_argparser, config_from_args
from .utils.logging import log
from .utils import platform as plat


def _pin_platform(args) -> int:
    """Bind the process to a JAX platform before any backend init.

    Hang-proof by construction: ``cpu`` never touches an accelerator;
    ``auto``/``tpu`` probe from a subprocess with a timeout (an exclusive
    TPU tunnel that is already claimed *blocks* inside backend init rather
    than erroring), and ``auto`` falls back to cpu while ``tpu`` exits with
    a clear error.  Returns 0, or a nonzero exit code.
    """
    if args.platform == "cpu":
        plat.pin("cpu", num_devices=args.num_devices)
        return 0
    info = plat.probe(timeout_s=args.probe_timeout, attempts=1, log=log)
    if info and info["platform"] != "cpu":
        log(f"accelerator: {info['n_devices']}x {info['device_kind']}")
        plat.unpin_cpu()  # a stray JAX_PLATFORMS=cpu must not override the probe
        return 0
    if args.platform == "tpu":
        log("ERROR: --platform tpu but no accelerator answered the probe "
            f"within {args.probe_timeout:.0f}s (tunnel busy or absent); "
            "rerun with --platform cpu [--num_devices N]")
        return 2
    log("no accelerator; using cpu")
    plat.pin("cpu", num_devices=args.num_devices)
    return 0


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    rc = _pin_platform(args)
    if rc:
        return rc
    from .train.trainer import Trainer  # import after the platform pin

    cfg = config_from_args(args)
    trainer = Trainer(cfg)
    result = trainer.fit()
    log(f"done: final loss {result['final_loss']:.6f}, "
        f"{result['samples_per_sec']:.1f} samples/sec")
    val = {k: v for k, v in result.items() if k.startswith("val_")}
    if val:
        log("validation: " + ", ".join(f"{k[4:]} {v:.6f}"
                                       for k, v in sorted(val.items())))
    return 0


if __name__ == "__main__":
    sys.exit(main())
