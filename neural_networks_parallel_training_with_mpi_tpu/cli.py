"""CLI entrypoint.

Usage (the TPU-native analogue of the reference's
``mpiexec -n numprocs python dataParallelTraining_NN_MPI.py --lr --momentum
--batch_size --nepochs``, README.md:12):

    python -m neural_networks_parallel_training_with_mpi_tpu \
        --lr 0.001 --momentum 0.9 --batch_size 4 --nepochs 3

No external launcher is needed on a single host: parallelism comes from the
device mesh, not from process replication.  On multi-host pods, run the same
command on every host (the TPU runtime provides world configuration).
"""

from __future__ import annotations

import sys

from .config import build_argparser, config_from_args
from .utils.logging import log
from .utils import platform as plat


def _pin_platform(args) -> int:
    """Bind the process to a JAX platform before any backend init.

    Hang-proof by construction: ``cpu`` never touches an accelerator;
    ``auto``/``tpu`` probe from a subprocess with a timeout (an exclusive
    TPU tunnel that is already claimed *blocks* inside backend init rather
    than erroring), and ``auto`` falls back to cpu while ``tpu`` exits with
    a clear error.  Returns 0, or a nonzero exit code.
    """
    if args.platform == "cpu":
        plat.pin("cpu", num_devices=args.num_devices)
        return 0
    info = plat.probe(timeout_s=args.probe_timeout, attempts=1, log=log)
    if info and info["platform"] != "cpu":
        log(f"accelerator: {info['n_devices']}x {info['device_kind']}")
        plat.unpin_cpu()  # a stray JAX_PLATFORMS=cpu must not override the probe
        return 0
    if args.platform == "tpu":
        log("ERROR: --platform tpu but no accelerator answered the probe "
            f"within {args.probe_timeout:.0f}s (tunnel busy or absent); "
            "rerun with --platform cpu [--num_devices N]")
        return 2
    log("no accelerator; using cpu")
    plat.pin("cpu", num_devices=args.num_devices)
    return 0


def _reinterpret_void_leaves(params, model):
    """npz stores extension dtypes (ml_dtypes bfloat16 — the
    --param_dtype bfloat16 training path) as raw void bytes; a
    template-less decode restore gets them back as ``|V2`` arrays.
    Reinterpret against the model's param dtype via the same helper the
    templated restore path uses (utils.checkpoint.reinterpret_void)."""
    import jax
    import numpy as np

    from .utils.checkpoint import reinterpret_void

    dt = np.dtype(getattr(getattr(model, "cfg", None), "param_dtype", None)
                  or np.float32)
    return jax.tree_util.tree_map(
        lambda x: reinterpret_void(x, dt), params)


def _dense_decode_params(params, model, meta):
    """Normalize a restored checkpoint into the dense per-layer layout the
    KV-cache decoder expects.  Checkpoints from the explicit-TP layouts
    (pipeline, seq x tensor) carry the head-aligned qkv column permutation
    (recorded as ``qkv_tp`` in meta.json — shape-preserving, hence
    undetectable from the pytree; same reconciliation the Trainer does on
    resume) and pipeline checkpoints carry stage-stacked blocks (the stack
    depth is inferable: a stacked qkv weight has 1 [(S, per)] or 2
    [(v, S, per) interleaved] extra leading dims vs the dense 2-D leaf)."""
    if not (isinstance(params, dict) and "blocks" in params):
        return params
    from .parallel.pipeline import dense_layer_blocks

    params = dict(params)
    params["blocks"] = dense_layer_blocks(
        params["blocks"], model.cfg,
        saved_tp=int((meta or {}).get("qkv_tp", 1)))
    return params


def _generate(args) -> int:
    """Decode from a trained LM checkpoint: the inference entrypoint
    (the reference has no inference path at all — its closest artifact is
    the dead test block at dataParallelTraining_NN_MPI.py:227-236).

    ``--generate "1,2,3"`` takes a comma-separated token-id prompt (this
    framework ships no tokenizer — datasets are synthetic/byte-level) and
    prints the continuation ids from models.generate's jitted KV-cache
    decode."""
    import jax
    import jax.numpy as jnp

    from .models.registry import build_model
    from .models.generate import generate
    from .train.state import TrainState
    from .ops import optim as optim_lib
    from .utils import checkpoint as ckpt, prng

    cfg = config_from_args(args)
    if cfg.model.arch != "transformer":
        log("ERROR: --generate needs a transformer model (--dataset lm "
            "or --arch transformer)")
        return 2
    # cheap input validation FIRST — before any model init or restore
    try:
        ids = [int(t) for t in args.generate.replace(" ", "").split(",") if t]
    except ValueError:
        log(f"ERROR: --generate expects comma-separated token ids, got "
            f"{args.generate!r}")
        return 2
    if not ids or any(t < 0 or t >= cfg.model.vocab_size for t in ids):
        log(f"ERROR: prompt ids must be in [0, {cfg.model.vocab_size}), "
            f"got {args.generate!r}")
        return 2
    if len(ids) + args.max_new_tokens > cfg.model.max_seq_len:
        log(f"ERROR: prompt ({len(ids)}) + max_new_tokens "
            f"({args.max_new_tokens}) exceeds max_seq_len "
            f"{cfg.model.max_seq_len} (raise --seq_len)")
        return 2
    if args.top_k > cfg.model.vocab_size:
        log(f"ERROR: --top_k {args.top_k} > vocab_size "
            f"{cfg.model.vocab_size}")
        return 2

    model = build_model(cfg.model)
    if cfg.checkpoint_dir:
        # only params matter for decoding; restore without a template so
        # the training-time optimizer flags need not be repeated (the npz
        # treedef is stored).  Orbax (multi-host sharded) snapshots DO need
        # a template for target shardings — build one on demand.
        try:
            restored = ckpt.restore(cfg.checkpoint_dir, template=None)
        except ValueError as e:
            if "template" not in str(e):
                log(f"ERROR: cannot restore {cfg.checkpoint_dir}: {e}")
                return 2
            opt = optim_lib.make(cfg.optimizer, cfg.lr, cfg.momentum,
                                 cfg.weight_decay)
            template = TrainState.create(model, opt, prng.init_key(cfg.seed))
            try:
                restored = ckpt.restore(cfg.checkpoint_dir, template)
            except ValueError as e2:
                log(f"ERROR: cannot restore {cfg.checkpoint_dir}: {e2} "
                    "(orbax restore needs the training-time --optimizer)")
                return 2
        if restored is None:
            log(f"ERROR: no checkpoint under {cfg.checkpoint_dir}")
            return 2
        # meta of the generation actually restored (the fallback chain can
        # land below an unquarantinable corrupt newest) — an unpinned read
        # could return a different generation's qkv_tp and silently
        # garble the decode weights
        params = _dense_decode_params(
            _reinterpret_void_leaves(restored.params, model), model,
            ckpt.read_meta(cfg.checkpoint_dir,
                           step=int(jax.device_get(restored.step))))
        log(f"restored step {int(jax.device_get(restored.step))} from "
            f"{cfg.checkpoint_dir}")
    else:
        log("note: no --checkpoint_dir; generating from a fresh init")
        params = model.init(prng.init_key(cfg.seed))
    if (getattr(args, "quantize", "none") == "int8"
            and cfg.model.matmul_dtype == "fp8"):
        # refuse loudly instead of silently falling through to the
        # dequant path: Linear's fp8 branch requires float kernels, so
        # over PTQ int8 weights the flag would do nothing (DESIGN §14)
        log("ERROR: --matmul_dtype fp8 cannot run over --quantize int8 "
            "PTQ kernels; use --matmul_dtype int8 (true int8 compute) "
            "or bf16 (dequant) with PTQ weights")
        return 2
    if getattr(args, "quantize", "none") == "int8":
        from .ops.quant import quantize_params, quantized_bytes

        skip = tuple(s for s in (args.quantize_skip or "").split(",") if s)
        full_b = quantized_bytes(params)
        params = quantize_params(params, skip=skip)
        log(f"int8 weights-only PTQ: param bytes {full_b/2**20:.1f} -> "
            f"{quantized_bytes(params)/2**20:.1f} MiB"
            + (f" (kept {','.join(skip)} full-precision)" if skip else ""))
        if cfg.model.matmul_dtype == "int8":
            # ops.qmm int8_serve_dot: the decode matmuls run int8 x int8
            # -> int32 with dynamic per-token activation scales instead
            # of dequantizing into the compute dtype (DESIGN.md §14)
            log("int8 COMPUTE decode: true int8 activation x weight dot "
                "(ops.qmm) over the PTQ kernels")
    prompt = jnp.asarray([ids], jnp.int32)
    out = generate(model, params, prompt, args.max_new_tokens,
                   temperature=args.temperature, top_k=args.top_k,
                   top_p=args.top_p,
                   key=jax.random.PRNGKey(cfg.seed),
                   kv_quant=getattr(args, "kv_quant", "none") == "int8",
                   prefill_chunk=getattr(args, "prefill_chunk", 0))
    toks = [int(t) for t in jax.device_get(out)[0]]
    print(",".join(str(t) for t in toks))
    return 0


def _supervise(args, argv) -> int:
    """--supervise N: run this same command under the crash-restart
    supervisor (train.resilience.supervise; exit-code contract in that
    module and DESIGN.md §6).  The child argv is this argv minus the
    supervisor flags, plus --resume when a checkpoint dir is configured so
    every relaunch continues from the newest snapshot.

    With --telemetry_dir the supervisor additionally (a) watches the
    child's OWN role-qualified heartbeat (heartbeat-<role>-p<P>.json,
    per the world env channel; leader-written, so only the rank-0
    supervisor's monitor ever arms) when --hang_timeout is set — an
    external hang detector that works even when the child process is
    frozen whole, armed at 4x the in-process timeout so the child's own
    watchdog fires first — (b) points the relaunch log at the child's
    postmortem.json flight-recorder dump after an abnormal exit, and
    (c) summarizes the kind="alert" records the child emitted during
    its lifetime next to each exit (observe-only).

    With --elastic the supervisor reacts to repeated peer-loss exits
    (43/42) by probing the surviving topology — the coordinator-aware
    ``parallel.mesh.probe_world``, driven by the same env channel the
    child's world_setup reads — and relaunching at the shrunken world;
    a probe below --min_devices parks/polls, then exits 46
    (DESIGN.md §10)."""
    import os

    from .train.resilience import strip_supervisor_flags, supervise

    child = strip_supervisor_flags(argv)
    if args.checkpoint_dir and "--resume" not in child:
        child.append("--resume")
    heartbeat = postmortem = alerts = events = None
    heartbeat_timeout = 0.0
    if getattr(args, "telemetry_dir", None):
        # watch exactly THIS child's heartbeat: the role-qualified file
        # its telemetry will write (workload decides the role; the
        # process id rides the world env channel) — never the freshest
        # sibling, which a co-resident process could keep beating while
        # our child hangs
        from .train.resilience import heartbeat_filename

        role = "rl" if getattr(args, "workload", "lm") == "rl" else "train"
        heartbeat = os.path.join(args.telemetry_dir,
                                 heartbeat_filename(role))
        postmortem = os.path.join(args.telemetry_dir, "postmortem.json")
        alerts = os.path.join(args.telemetry_dir, "metrics.jsonl")
        # supervisor lifecycle JSONL next to the trace files so one dir
        # holds the whole goodput join (utils/goodput.py prices the
        # relaunch gaps from these events); lands in the trace/ subdir
        # when tracing is on, else directly under the telemetry dir
        from .train import trace as _trace_lib

        events_dir = (_trace_lib.dir_from_config(args)
                      if (getattr(args, "trace", False)
                          or getattr(args, "trace_dir", None))
                      else args.telemetry_dir)
        os.makedirs(events_dir, exist_ok=True)
        events = os.path.join(events_dir, "supervisor-events.jsonl")
        if getattr(args, "hang_timeout", 0.0) > 0:
            heartbeat_timeout = max(4.0 * args.hang_timeout, 60.0)
    probe = None
    if getattr(args, "elastic", False):
        def probe():
            # imported lazily: pulls jax (module only — the probe itself
            # runs in a subprocess, so the supervisor process never
            # initializes a backend)
            from .parallel.mesh import probe_world

            return probe_world(log=lambda m: print(m, file=sys.stderr,
                                                   flush=True))
    pkg = __name__.rsplit(".", 1)[0]
    return supervise([sys.executable, "-m", pkg, *child],
                     max_restarts=args.supervise,
                     backoff=args.supervise_backoff,
                     backoff_cap=args.supervise_backoff_max,
                     heartbeat_path=heartbeat,
                     heartbeat_timeout=heartbeat_timeout,
                     postmortem_path=postmortem,
                     alerts_path=alerts,
                     ckpt_dir=args.checkpoint_dir,
                     elastic=getattr(args, "elastic", False),
                     min_devices=getattr(args, "min_devices", 0),
                     probe=probe,
                     events_path=events,
                     # a platform's advance notice (SIGUSR1) lands on
                     # this top-level pid; the child is the process that
                     # must checkpoint — forward it (train.resilience
                     # preemption-notice channel)
                     forward_preempt=True)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    args = build_argparser().parse_args(argv)
    if getattr(args, "supervise", 0) > 0:
        return _supervise(args, argv)  # before any backend init
    rc = _pin_platform(args)
    if rc:
        return rc
    if getattr(args, "generate", None) is not None:
        return _generate(args)
    from .train.resilience import (EXIT_ANOMALY, EXIT_CAPACITY, EXIT_PEER,
                                   EXIT_SDC, AnomalyAbort, CapacityAbort,
                                   SDCAbort, is_peer_error)
    from .train.trainer import Trainer  # import after the platform pin

    cfg = config_from_args(args)
    try:
        if cfg.workload == "rl":
            # Anakin actor-learner RL (rl/, DESIGN.md §13) — same
            # exception->exit-code contract, so the supervisor and the
            # elastic policy treat an RL child like any training child
            from .rl.runner import RLRunner

            trainer = RLRunner(cfg)
        else:
            trainer = Trainer(cfg)
        result = trainer.fit()
    except AnomalyAbort as e:
        # deterministic divergence: the last good checkpoint is preserved
        # (no final save) and the supervisor must NOT relaunch
        log(f"ERROR: anomaly abort: {e} (exit {EXIT_ANOMALY})")
        return EXIT_ANOMALY
    except SDCAbort as e:
        # silent data corruption the run must not survive: a replay-
        # reproducible (software) divergence, or a device past its strike
        # budget — no final save (it would snapshot corrupt state), and
        # the supervisor must NOT relaunch (it would replay the bug)
        log(f"ERROR: SDC abort: {e} (exit {EXIT_SDC})")
        return EXIT_SDC
    except CapacityAbort as e:
        # the healthy world is below --min_devices: no-retry exit 46 —
        # relaunching cannot create chips (DESIGN.md §10)
        log(f"ERROR: capacity abort: {e} (exit {EXIT_CAPACITY})")
        return EXIT_CAPACITY
    except Exception as e:
        # peer/transport loss (a collective raised, world formation timed
        # out): exit 43 so the supervisor retries — and, under --elastic,
        # counts the loss toward its probe-and-shrink streak.  Anything
        # else stays a crash (traceback, rc 1): also retried, but never
        # misread as a topology signal.
        if not is_peer_error(e):
            raise
        # full traceback first: the classifier is heuristic, and a
        # misread software crash must stay diagnosable from the log
        import traceback

        traceback.print_exc()
        log(f"ERROR: peer loss: {type(e).__name__}: {e} "
            f"(exit {EXIT_PEER})")
        # hard exit: after a lost peer the distributed client's background
        # threads LOG(FATAL) during interpreter teardown, overriding a
        # normal return with SIGABRT — which the supervisor would count as
        # an anonymous crash instead of the peer-loss streak the elastic
        # policy needs
        import os

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(EXIT_PEER)
    unit = ("env frames/sec" if cfg.workload == "rl" else "samples/sec")
    log(f"done: final loss {result['final_loss']:.6f}, "
        f"{result['samples_per_sec']:.1f} {unit}")
    val = {k: v for k, v in result.items() if k.startswith("val_")}
    if val:
        log("validation: " + ", ".join(f"{k[4:]} {v:.6f}"
                                       for k, v in sorted(val.items())))
    if result.get("preempt_notice"):
        # advance-notice preemption (SIGUSR1): the final checkpoint is
        # on disk, but the node is going away — exit 47 (decommission)
        # so the supervisor stops WITHOUT calling the job finished, and
        # the goodput ledger prices the tail as drain, not rollback
        from .train.resilience import EXIT_DECOMMISSION

        return EXIT_DECOMMISSION
    return 0


if __name__ == "__main__":
    sys.exit(main())
