"""CLI entrypoint.

Usage (the TPU-native analogue of the reference's
``mpiexec -n numprocs python dataParallelTraining_NN_MPI.py --lr --momentum
--batch_size --nepochs``, README.md:12):

    python -m neural_networks_parallel_training_with_mpi_tpu \
        --lr 0.001 --momentum 0.9 --batch_size 4 --nepochs 3

No external launcher is needed on a single host: parallelism comes from the
device mesh, not from process replication.  On multi-host pods, run the same
command on every host (the TPU runtime provides world configuration).
"""

from __future__ import annotations

import sys

from .config import build_argparser, config_from_args
from .train.trainer import Trainer
from .utils.logging import log


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    cfg = config_from_args(args)
    trainer = Trainer(cfg)
    result = trainer.fit()
    log(f"done: final loss {result['final_loss']:.6f}, "
        f"{result['samples_per_sec']:.1f} samples/sec")
    val = {k: v for k, v in result.items() if k.startswith("val_")}
    if val:
        log("validation: " + ", ".join(f"{k[4:]} {v:.6f}"
                                       for k, v in sorted(val.items())))
    return 0


if __name__ == "__main__":
    sys.exit(main())
