"""The Anakin actor–learner step: rollout + GAE + PPO in ONE program.

Podracer's Anakin layout (arXiv 2104.06272 §3) co-locates acting and
learning on the same devices: a T-step environment rollout under the
CURRENT policy (``lax.scan`` over time, ``vmap`` over the per-device env
batch — the DrJAX-style mapped fan-out, arXiv 2403.07128), Generalized
Advantage Estimation, and the PPO clipped-surrogate update are all
compiled into one ``shard_map``-mapped, jitted step on the data mesh:

* envs (state, obs, running returns, per-env PRNG keys) are dim-0-sharded
  over the DATA axes — each device owns ``n_envs / dp`` environments;
* params / optimizer state / step counter are replicated;
* gradients are psum'd over the data axes exactly like the DP LM step
  (``parallel.data_parallel``), so the update — and hence the skip guard
  predicate and the telemetry metrics vector — is identical on every
  replica.

One Anakin step = one rollout of ``T * n_envs`` env frames + ``ppo_epochs``
full-batch clipped-surrogate optimizer updates on them.  There is no host
round-trip anywhere inside: the policy the envs step under is the one
being updated, on the same chips, which is the entire point of the
architecture.

Determinism/resume contract: the step is a pure function of
:class:`RLState`; all randomness derives from the carried per-env base
keys via ``fold_in(key_i, 1 + step*T + t)``, so checkpointing RLState
(step, params, opt state, env state, obs, running returns, env keys) and
restoring it reproduces the uninterrupted run bitwise
(tests/test_rl.py pins this).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.optim import Optimizer
from ..parallel.data_parallel import DATA_AXES, data_axis_size
from ..utils import prng
from .gae import gae_advantages

Pytree = Any


class RLState(NamedTuple):
    """The Anakin analogue of ``train.state.TrainState`` — everything a
    trajectory-exact resume needs, in one checkpointable pytree.  The
    first three fields mirror TrainState; the trailing four are the
    per-env actor state, dim-0-sharded over the data axes.
    ``utils.checkpoint``'s elastic reshard derives the opt-state leaf
    range from the NamedTuple field order, so the env leaves here are
    never mistaken for repaddable optimizer padding — a resume with a
    different ``--rl_envs`` refuses loudly instead of silently
    zero-extending env state (tests/test_rl.py pins it)."""

    step: jax.Array       # int32 scalar — Anakin steps (rollout+update)
    params: Pytree        # policy/value net, replicated
    opt_state: Pytree     # replicated (GuardedState-wrapped when guarded)
    env_state: Pytree     # per-env environment state, (n_envs, ...)
    obs: jax.Array        # (n_envs, obs_dim) current observations
    ep_return: jax.Array  # (n_envs,) running (uncompleted) episode returns
    env_keys: jax.Array   # (n_envs, 2) per-env PRNG base keys


def rl_state_spec() -> RLState:
    """shard_map in/out spec-prefix tree: params replicated, envs sharded."""
    return RLState(step=P(), params=P(), opt_state=P(),
                   env_state=P(DATA_AXES), obs=P(DATA_AXES),
                   ep_return=P(DATA_AXES), env_keys=P(DATA_AXES))


def policy_heads(model, params: Pytree, obs: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
    """(logits, value) from the shared-torso net: the registry MLP with
    ``out_features = n_actions + 1`` — columns [:-1] are action logits,
    column [-1] the state value.  One matmul stack serves both heads."""
    out = model.apply(params, obs)
    return out[..., :-1], out[..., -1]


def init_rl_state(env, model, optimizer: Optimizer, n_envs: int,
                  seed: int) -> RLState:
    """Deterministic host-side init (every process derives the identical
    state from the job seed, like ``TrainState.create``): policy params
    from the INIT stream, per-env base keys from the ENV stream, each
    env reset with ``fold_in(key_i, 0)`` (step keys use ``1 + ...``, so
    the reset draw can never collide with a rollout draw)."""
    params = model.init(prng.init_key(seed))
    base = prng.stream(seed, prng.ENV)
    env_keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
        jnp.arange(n_envs))
    reset_keys = jax.vmap(lambda k: jax.random.fold_in(k, 0))(env_keys)
    env_state, obs = jax.vmap(env.reset)(reset_keys)
    return RLState(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=optimizer.init(params),
                   env_state=env_state, obs=obs,
                   ep_return=jnp.zeros((n_envs,), jnp.float32),
                   env_keys=env_keys)


def place_rl_state(state: RLState, mesh: Mesh) -> RLState:
    """Place an RLState on the mesh: params/opt replicated, env leaves
    dim-0-sharded over the data axes (used at init AND on restore)."""
    rep = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(DATA_AXES))
    put = lambda tree, s: jax.tree_util.tree_map(
        lambda x: jax.device_put(x, s), tree)
    return RLState(step=jax.device_put(state.step, rep),
                   params=put(state.params, rep),
                   opt_state=put(state.opt_state, rep),
                   env_state=put(state.env_state, shard),
                   obs=jax.device_put(state.obs, shard),
                   ep_return=jax.device_put(state.ep_return, shard),
                   env_keys=jax.device_put(state.env_keys, shard))


def anakin_step_flops(model, obs_dim: int, rollout_steps: int,
                      ppo_epochs: int) -> Optional[float]:
    """Analytic matmul FLOPs of one Anakin step PER ENV FRAME — the honest
    accounting the MFU stream divides by (``train.telemetry``): every
    frame pays 1 actor forward, the bootstrap value adds 1/T of a
    forward, and the learner pays ``ppo_epochs`` fwd+bwd passes (the
    standard 3x-forward convention) over the full rollout batch.  None
    for unaccounted architectures."""
    fwd = model.fwd_flops((1, obs_dim))
    if fwd is None:
        return None
    return float(fwd) * (1.0 + 1.0 / max(1, rollout_steps)
                         + 3.0 * max(1, ppo_epochs))


def make_anakin_step(env, model, optimizer: Optimizer, mesh: Mesh, *,
                     rollout_steps: int, gamma: float = 0.99,
                     gae_lambda: float = 0.95, clip_eps: float = 0.2,
                     entropy_coef: float = 0.01, value_coef: float = 0.5,
                     ppo_epochs: int = 4, normalize_advantages: bool = True,
                     with_metrics: bool = False, donate: bool = True):
    """Build the jitted Anakin step: ``state -> (state, out)``.

    ``out`` is the scalar PPO loss, or with ``with_metrics`` the
    on-device telemetry dict — ``telemetry.METRIC_KEYS`` assembled by the
    same ``telemetry.update_with_metrics`` seam the DP LM step uses (so
    a guarded optimizer pays ONE norm reduction, and the update math is
    byte-identical to the metrics-off step: params stay bitwise-equal
    with telemetry on vs off) — extended with the RL scalars
    ``return_mean`` (completed episodes this rollout; NaN when none
    completed), ``episodes`` (completed count), ``entropy``,
    ``approx_kl`` and ``value_loss`` from the final PPO epoch.

    The PPO update is ``ppo_epochs`` FULL-batch clipped-surrogate steps
    on the rollout (advantages frozen after GAE; no minibatch shuffling
    — at Anakin scale the rollout IS the minibatch), each an ordinary
    ``Optimizer.update`` on psum'd global-mean gradients, so
    ``with_skip_guard``/``with_clipping`` wrappers apply unchanged.
    """
    if rollout_steps < 1:
        raise ValueError(f"rollout_steps must be >= 1, got {rollout_steps}")
    if ppo_epochs < 1:
        raise ValueError(f"ppo_epochs must be >= 1, got {ppo_epochs}")
    T = int(rollout_steps)

    def shard_step(state: RLState):
        n_local = state.obs.shape[0]

        # ---- actor: T-step rollout under the current policy ----------
        def rollout_body(carry, t):
            env_state, obs, ep_ret = carry
            # one fresh key per (env, t), derived from the carried base
            # keys — nothing about the draw depends on how the rollout
            # is batched or sharded
            keys = jax.vmap(
                lambda k: jax.random.fold_in(k, 1 + state.step * T + t)
            )(state.env_keys)
            akeys = jax.vmap(lambda k: jax.random.fold_in(k, 0))(keys)
            ekeys = jax.vmap(lambda k: jax.random.fold_in(k, 1))(keys)
            logits, value = policy_heads(model, state.params, obs)
            action = jax.vmap(jax.random.categorical)(akeys, logits)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), action[:, None], axis=1)[:, 0]
            env_state, next_obs, reward, done = jax.vmap(env.step)(
                env_state, action, ekeys)
            ep_ret = ep_ret + reward
            completed_sum = jnp.sum(ep_ret * done)
            completed_n = jnp.sum(done)
            ep_ret = ep_ret * (1.0 - done)
            traj = (obs, action, logp, value, reward, done)
            return ((env_state, next_obs, ep_ret),
                    (traj, completed_sum, completed_n))

        carry0 = (state.env_state, state.obs, state.ep_return)
        (env_state, final_obs, ep_return), (traj, csum, cnum) = lax.scan(
            rollout_body, carry0, jnp.arange(T))
        obs_t, action_t, logp_t, value_t, reward_t, done_t = traj

        # completed-episode return, GLOBAL mean over the data axes (NaN
        # when no episode completed this rollout — the host stream skips
        # non-finite points)
        total_completed = lax.psum(jnp.sum(cnum), DATA_AXES)
        return_mean = jnp.where(
            total_completed > 0,
            lax.psum(jnp.sum(csum), DATA_AXES)
            / jnp.maximum(total_completed, 1.0),
            jnp.float32(jnp.nan))

        # ---- advantages (GAE) ----------------------------------------
        _, last_value = policy_heads(model, state.params, final_obs)
        adv_t, ret_t = gae_advantages(reward_t, value_t, done_t,
                                      last_value, gamma, gae_lambda)
        n_total = jnp.float32(T * n_local) * data_axis_size(mesh)
        if normalize_advantages:
            # global-batch normalization: psum'd moments, so every
            # replica standardizes by the identical statistics
            mean = lax.psum(jnp.sum(adv_t), DATA_AXES) / n_total
            var = lax.psum(jnp.sum(jnp.square(adv_t - mean)),
                           DATA_AXES) / n_total
            adv_t = (adv_t - mean) / jnp.sqrt(var + 1e-8)

        flat = lambda x: x.reshape((T * n_local,) + x.shape[2:])
        b_obs, b_act = flat(obs_t), flat(action_t)
        b_logp, b_adv, b_ret = flat(logp_t), flat(adv_t), flat(ret_t)

        # ---- learner: PPO clipped surrogate, global-mean gradients ----
        def loss_sums(params):
            logits, value = policy_heads(model, params, b_obs)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, b_act[:, None],
                                       axis=1)[:, 0]
            ratio = jnp.exp(logp - b_logp)
            clipped = jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps)
            pg_sum = -jnp.sum(jnp.minimum(ratio * b_adv, clipped * b_adv))
            v_sum = 0.5 * jnp.sum(jnp.square(value - b_ret))
            ent_sum = -jnp.sum(jnp.sum(jnp.exp(logp_all) * logp_all,
                                       axis=-1))
            kl_sum = jnp.sum(b_logp - logp)
            total = pg_sum + value_coef * v_sum - entropy_coef * ent_sum
            return total, (v_sum, ent_sum, kl_sum)

        params, opt_state = state.params, state.opt_state
        loss = v_loss = entropy = approx_kl = jnp.float32(0.0)
        metrics = None
        for e in range(ppo_epochs):
            (total, (v_sum, ent_sum, kl_sum)), grads = jax.value_and_grad(
                loss_sums, has_aux=True)(params)
            grads = jax.tree_util.tree_map(
                lambda g: lax.psum(g, DATA_AXES) / n_total, grads)
            loss = lax.psum(total, DATA_AXES) / n_total
            v_loss = lax.psum(v_sum, DATA_AXES) / n_total
            entropy = lax.psum(ent_sum, DATA_AXES) / n_total
            approx_kl = lax.psum(kl_sum, DATA_AXES) / n_total
            if with_metrics and e == ppo_epochs - 1:
                from ..train import telemetry

                params, opt_state, metrics = telemetry.update_with_metrics(
                    optimizer, grads, opt_state, params, loss)
            else:
                params, opt_state = optimizer.update(grads, opt_state,
                                                     params)

        new_state = RLState(step=state.step + 1, params=params,
                            opt_state=opt_state, env_state=env_state,
                            obs=final_obs, ep_return=ep_return,
                            env_keys=state.env_keys)
        # the RL scalars are byproducts of work the step does anyway, so
        # both modes carry them; with_metrics ADDS the telemetry vector
        # (grad/param norms etc.) — the only change to the program — and
        # the update math stays byte-identical either way
        out = dict(metrics) if with_metrics else {"loss": loss}
        out.update(return_mean=return_mean,
                   episodes=total_completed,
                   entropy=entropy, approx_kl=approx_kl,
                   value_loss=v_loss)
        return new_state, out

    spec = rl_state_spec()
    mapped = jax.shard_map(
        shard_step, mesh=mesh,
        in_specs=(spec,),
        out_specs=(spec, P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())
