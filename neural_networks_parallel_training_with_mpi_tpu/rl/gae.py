"""Generalized Advantage Estimation (Schulman et al., arXiv 1506.02438).

One ``lax.scan`` backward over the rollout::

    delta_t = r_t + gamma * V_{t+1} * (1 - done_t) - V_t
    A_t     = delta_t + gamma * lam * (1 - done_t) * A_{t+1}

``done_t`` masks BOTH the bootstrap and the recursion: an episode that
terminates mid-rollout contributes no value (or advantage) leakage from
the auto-reset successor state — the boundary every hand-rolled GAE gets
wrong, pinned against a plain-numpy reference in tests/test_rl.py.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def gae_advantages(rewards: jax.Array, values: jax.Array,
                   dones: jax.Array, last_value: jax.Array,
                   gamma: float, lam: float
                   ) -> Tuple[jax.Array, jax.Array]:
    """(advantages, returns), each shaped like ``rewards``.

    ``rewards``/``values``/``dones`` are time-major ``(T, ...)`` —
    ``values[t] = V(s_t)`` for the state the t-th action was taken in,
    ``dones[t]`` flags that transition t ended its episode —
    and ``last_value`` is ``V(s_T)`` of the post-rollout state (the
    bootstrap for episodes still running at the boundary).
    ``returns = advantages + values`` are the value-function regression
    targets (the lambda-returns)."""
    values_next = jnp.concatenate([values[1:], last_value[None]], axis=0)
    not_done = 1.0 - dones

    def body(carry, xs):
        r, v, v_next, nd = xs
        delta = r + gamma * v_next * nd - v
        adv = delta + gamma * lam * nd * carry
        return adv, adv

    _, advantages = lax.scan(body, jnp.zeros_like(last_value),
                             (rewards, values, values_next, not_done),
                             reverse=True)
    return advantages, advantages + values
