"""Reinforcement learning on the training mesh (ROADMAP item 5).

Podracer/Anakin-style co-located actor–learner training ("Podracer
architectures for scalable Reinforcement Learning", arXiv 2104.06272):
environment transitions, the rollout loop, advantage estimation and the
PPO update are ALL jitted into one shard_mapped program on the same data
mesh the LM steps use — environments sharded along the data axes, params
replicated, gradients psum'd, exactly like the DP train step.  DrJAX
(arXiv 2403.07128) names the mechanism: the actor fan-out is a mapped
primitive (``vmap`` over envs inside ``shard_map`` over devices), not a
fleet of actor processes.

Modules:

* :mod:`.envs` — stateless pure-JAX vectorized environments (gridworld,
  CartPole) with auto-reset transitions.
* :mod:`.gae` — Generalized Advantage Estimation via ``lax.scan``.
* :mod:`.anakin` — the fused rollout + GAE + PPO step and its
  :class:`~.anakin.RLState`.
* :mod:`.runner` — the learner loop riding the existing ``train/``
  machinery (telemetry, manifest checkpoints, supervisor, faults).
"""

from .envs import CartPole, GridWorld, make_env  # noqa: F401
from .gae import gae_advantages  # noqa: F401
from .anakin import (  # noqa: F401
    RLState,
    anakin_step_flops,
    init_rl_state,
    make_anakin_step,
    place_rl_state,
)
