"""The RL learner loop: Anakin steps riding the existing train/ machinery.

Deliberately NOT a fork of ``train.trainer.Trainer``: everything around
the step — optimizer construction (``ops.optim.make`` + the
``with_skip_guard`` guarded update), telemetry (metrics.jsonl, heartbeat,
flight recorder, MFU accounting), manifest-committed checkpoints with
verified restore, deterministic fault injection, the hang watchdog,
graceful SIGTERM preemption, and the crash-restart supervisor — is the
same machinery, consumed through the same seams.  The point of ROADMAP
item 5 is precisely that the reliability stack needs NO RL-specific code:
an injected crash mid-RL-run relaunches, restores the newest verified
snapshot, and continues trajectory-exact (tests/test_rl.py pins it).

What IS different from supervised training: there is no data loader (the
environments generate the data on device), one "dispatch" is one Anakin
step (T * n_envs env frames + ppo_epochs PPO updates), and the
checkpoint state is :class:`~.anakin.RLState` — params + optimizer state
PLUS env state, observations, running returns and the per-env PRNG keys,
so a resume reproduces the uninterrupted run bitwise.
"""

from __future__ import annotations

import hashlib
import sys
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..config import ModelConfig, TrainConfig
from ..models.registry import build_model
from ..ops import optim as optim_lib
from ..ops import schedules
from ..parallel import data_parallel as dp
from ..parallel.mesh import describe, make_mesh, world_setup
from ..train import telemetry as telemetry_lib
from ..train import trace as trace_lib
from ..utils import compile_ledger as ledger_lib
from ..utils.logging import MetricsLogger, Throughput, log
from . import anakin
from .envs import make_env


def params_digest(params: Any) -> str:
    """sha256 over the host copy of every param leaf, in tree order — the
    cross-process bitwise-trajectory witness examples/21 diffs."""
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(jax.device_get(params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()


class RLRunner:
    """Drives :func:`rl.anakin.make_anakin_step` under the full
    reliability stack.  Mirrors the Trainer's surface where it matters
    (``fit() -> result dict`` with ``final_loss``/``samples_per_sec``,
    the same abort exceptions propagating to the CLI's exit-code
    mapping) so ``cli.main`` treats both workloads identically."""

    def __init__(self, cfg: TrainConfig, mesh=None):
        self.cfg = cfg
        world_setup()
        if cfg.min_devices and jax.device_count() < cfg.min_devices:
            from ..train.resilience import CapacityAbort

            raise CapacityAbort(
                f"{jax.device_count()} healthy device(s) < --min_devices "
                f"{cfg.min_devices}: refusing to train below the capacity "
                "floor (exit 46; raise capacity or lower --min_devices)")
        if cfg.collective_timeout > 0:
            from ..parallel import distributed

            distributed.set_collective_timeout(cfg.collective_timeout)
        self.mesh = mesh if mesh is not None else make_mesh(cfg.mesh)
        for axis in ("tensor", "pipe", "seq", "expert"):
            if self.mesh.shape.get(axis, 1) > 1:
                raise NotImplementedError(
                    f"--workload rl shards ENVIRONMENTS over the data "
                    f"axes; the {axis} axis has no meaning for the "
                    "policy MLP — use --dp/--fsdp only")
        if cfg.update_sharding != "replicated" or cfg.master_weights:
            raise NotImplementedError(
                "--workload rl runs the replicated weight update (the "
                "policy net is a few thousand params; sharding its "
                "update would be pure overhead) — drop "
                "--update_sharding/--master_weights")
        self.dp_size = dp.data_axis_size(self.mesh)
        rl = cfg.rl
        if rl.n_envs < 1 or rl.n_envs % self.dp_size != 0:
            raise ValueError(
                f"--rl_envs {rl.n_envs} must be a positive multiple of "
                f"the data-axis size {self.dp_size} (each device owns "
                "n_envs/dp environments)")
        self.env = make_env(rl.env)
        # the policy/value net comes from models/registry like every
        # other workload's model: an MLP torso with n_actions+1 outputs
        # (logits ++ value — rl.anakin.policy_heads splits them)
        self.model = build_model(ModelConfig(
            arch="mlp", in_features=self.env.obs_dim,
            hidden=tuple(rl.hidden),
            out_features=self.env.n_actions + 1,
            dtype=cfg.param_dtype or cfg.model.dtype,
            compute_dtype=cfg.model.compute_dtype))
        # lr schedule domain = optimizer steps = updates * ppo_epochs
        lr = schedules.make(
            cfg.lr_schedule, cfg.lr,
            total_steps=max(1, rl.total_updates * rl.ppo_epochs),
            warmup_steps=cfg.warmup_steps, min_lr=cfg.min_lr)
        # replicated path: with_clipping's whole-tree norm is already the
        # global norm (gradients are psum'd before the update) — same
        # seam as the DP trainer
        self.optimizer = optim_lib.make(
            cfg.optimizer, lr, cfg.momentum, cfg.weight_decay,
            grad_clip=cfg.grad_clip)
        self.guarded = cfg.skip_nonfinite or cfg.skip_threshold > 0
        if self.guarded:
            self.optimizer = optim_lib.with_skip_guard(
                self.optimizer, cfg.skip_threshold)
        from ..utils.faults import FaultPlan

        self.fault_plan = FaultPlan.from_config(cfg.faults)
        if self.fault_plan and self.fault_plan.det_desync() is not None:
            raise NotImplementedError(
                "desync?det wraps the supervised train step's TrainState; "
                "the RL step is not wired for it (bitflip/desync without "
                "det target RLState.params/opt_state and work unchanged)")
        if self.fault_plan and any(f.kind == "nan"
                                   for f in self.fault_plan.faults):
            # reject rather than vacuously pass: nan poisons a HOST-FED
            # batch, and the RL step's frames are generated on device —
            # a chaos run asking for it would inject nothing and exit 0
            raise NotImplementedError(
                "the 'nan' fault poisons the host-fed batch; RL frames "
                "are generated on device, so there is nothing to poison "
                "— exercise the skip guard with the state kinds "
                "(bitflip/desync) instead")
        self.telemetry_metrics = bool(cfg.telemetry_dir
                                      and cfg.metrics_every > 0)
        # compile-ledger seam + span tracer: same observability channel
        # as the supervised Trainer (train/trace.py, DESIGN.md §7)
        self.step_fn = ledger_lib.instrument(
            anakin.make_anakin_step(
                self.env, self.model, self.optimizer, self.mesh,
                rollout_steps=rl.rollout_steps, gamma=rl.gamma,
                gae_lambda=rl.gae_lambda, clip_eps=rl.clip_eps,
                entropy_coef=rl.entropy_coef, value_coef=rl.value_coef,
                ppo_epochs=rl.ppo_epochs,
                with_metrics=self.telemetry_metrics),
            "rl_anakin_step")
        self.tracer = None
        trace_dir = trace_lib.dir_from_config(cfg)
        if trace_dir:
            self.tracer = trace_lib.start_run(trace_dir)
        self.frames_per_update = rl.rollout_steps * rl.n_envs
        self.metrics = MetricsLogger(cfg.metrics_jsonl)
        dev = self.mesh.devices.flat[0]
        self.telemetry = telemetry_lib.Telemetry(
            cfg, self.model, (self.env.obs_dim,),
            n_devices=int(self.mesh.devices.size),
            device_kind=dev.device_kind, platform=dev.platform,
            kind="rl",
            flops_per_row=anakin.anakin_step_flops(
                self.model, self.env.obs_dim, rl.rollout_steps,
                rl.ppo_epochs))
        self.state: Optional[anakin.RLState] = None

    # ---- state lifecycle -------------------------------------------------
    def init_state(self) -> anakin.RLState:
        host = anakin.init_rl_state(self.env, self.model, self.optimizer,
                                    self.cfg.rl.n_envs, self.cfg.seed)
        self.state = anakin.place_rl_state(host, self.mesh)
        return self.state

    def maybe_resume(self) -> int:
        """Restore the newest VERIFIED snapshot (manifest-checked,
        quarantine-and-fall-back — utils.checkpoint unchanged) and return
        the Anakin step to resume from.  The snapshot carries env state,
        observations, running returns and the per-env keys, so the
        resumed trajectory is bitwise the uninterrupted one."""
        if not (self.cfg.resume and self.cfg.checkpoint_dir):
            return 0
        from ..utils import checkpoint as ckpt

        restored = ckpt.restore(self.cfg.checkpoint_dir, self.state,
                                elastic=self.cfg.elastic)
        if restored is None:
            return 0
        self.state = anakin.place_rl_state(restored, self.mesh)
        return int(jax.device_get(self.state.step))

    def save(self, final: bool = False) -> None:
        if not self.cfg.checkpoint_dir:
            return
        from ..utils import checkpoint as ckpt

        self.telemetry.alive()
        step_now = int(jax.device_get(self.state.step))
        # a run ending exactly on a checkpoint boundary already committed
        # this step (same guard as Trainer.save: the orbax layout refuses
        # to rewrite an existing generation)
        if final and getattr(self, "_last_saved_step", None) == step_now:
            ckpt.wait_pending()
            return
        self._last_saved_step = step_now
        extra = {"workload": "rl",
                 "saved_world": {"dp": int(self.dp_size)}}
        with trace_lib.span("ckpt", final=final):
            if self.cfg.async_checkpoint and not final:
                ckpt.save_async(self.cfg.checkpoint_dir, self.state,
                                keep=self.cfg.checkpoint_keep,
                                extra_meta=extra)
            else:
                if final:
                    ckpt.wait_pending()
                ckpt.save(self.cfg.checkpoint_dir, self.state,
                          keep=self.cfg.checkpoint_keep, extra_meta=extra)

    # ---- the loop --------------------------------------------------------
    def fit(self) -> Dict[str, Any]:
        cfg, rl = self.cfg, self.cfg.rl
        if self.state is None:
            self.init_state()
        start = self.maybe_resume()
        log(f"mesh: {describe(self.mesh)} | workload: rl ({rl.env}) | "
            f"policy: mlp {self.env.obs_dim}->"
            f"{'x'.join(str(h) for h in rl.hidden)}->"
            f"{self.env.n_actions}+1 ({self.model.n_params():,} params) | "
            f"{rl.n_envs} envs x T={rl.rollout_steps} "
            f"({self.frames_per_update} frames/update), "
            f"ppo_epochs={rl.ppo_epochs}"
            + (f" | resumed at update {start}" if start else ""))
        from ..utils.watchdog import HangWatchdog
        from ..train.resilience import GracefulShutdown

        watchdog = HangWatchdog(
            cfg.hang_timeout or None,
            on_timeout=lambda: telemetry_lib.emergency_dump("hang"))
        shutdown = GracefulShutdown()
        thr = Throughput()
        first_return = None
        ema_return = None
        last_loss = float("nan")
        last_fetched: Optional[dict] = None
        prev: Optional[tuple] = None  # (update, out future)
        step = start

        def observe(update: int, out) -> None:
            """Fetch one dispatch's out dict (the step always returns at
            least loss + the RL scalars), fold the return stream into the
            host-side trackers, and emit the log/metrics lines at the
            log_every cadence."""
            nonlocal first_return, ema_return, last_loss, last_fetched
            with trace_lib.span("fetch", step=update):
                fetched = last_fetched = jax.device_get(out)
            last_loss = float(fetched["loss"])
            ret = float(fetched.get("return_mean", float("nan")))
            if np.isfinite(ret):
                if first_return is None:
                    first_return = ret
                ema_return = (ret if ema_return is None
                              else 0.9 * ema_return + 0.1 * ret)
            if cfg.log_every and update % cfg.log_every == 0:
                extra = (f", return {ret:.3f} (EMA {ema_return:.3f})"
                         if np.isfinite(ret) and ema_return is not None
                         else "")
                log(f"update {update}: loss {last_loss:.6f}{extra}")
                self.metrics.write({"step": update, "loss": last_loss,
                                    **({"return_mean": ret}
                                       if np.isfinite(ret) else {}),
                                    "frames_per_sec":
                                        thr.samples_per_sec})

        try:
            with watchdog, shutdown:
                while step < rl.total_updates and not shutdown.requested:
                    if self.fault_plan is not None:
                        # crash/sigterm/ckpt-I/O kinds (no batch leaves
                        # to poison — env frames are generated on device)
                        self.fault_plan.apply(step, {},
                                              ckpt_dir=cfg.checkpoint_dir)
                        # SDC kinds corrupt RLState.params/opt_state
                        # shards exactly like the trainer's state
                        self.state = self.fault_plan.apply_state(
                            step, self.state, what="rl state")
                    with trace_lib.span("dispatch", step=step):
                        self.state, out = self.step_fn(self.state)
                    watchdog.pat()
                    thr.add(self.frames_per_update)
                    before, step = step, step + 1
                    self.telemetry.on_dispatch(step, 0, before, out, 1,
                                               self.frames_per_update)
                    # lag-1 fetch: by now `out`'s successor is submitted,
                    # so this device_get keeps one dispatch in flight —
                    # and it is the blocking point the watchdog needs
                    if prev is not None:
                        observe(*prev)
                    prev = (step, out)
                    if (cfg.checkpoint_every
                            and step % cfg.checkpoint_every == 0):
                        with watchdog.suspended():
                            self.save()
        finally:
            exc = sys.exc_info()[1]
            if exc is not None:
                self.telemetry.on_abnormal_exit(exc)
                self.metrics.close()
                self.telemetry.close()
                if self.tracer is not None:
                    trace_lib.stop_run(self.tracer)
        if prev is not None:
            observe(*prev)
        self.telemetry.flush(step=step)
        if shutdown.requested:
            self.telemetry.on_preempted(shutdown.signum, step)
        self.save(final=True)
        digest = params_digest(self.state.params)
        final_return = (ema_return if ema_return is not None
                        else float("nan"))
        log(f"rl: return {first_return if first_return is not None else float('nan'):.3f}"
            f" -> EMA {final_return:.3f} over {step - start} update(s); "
            f"params sha256 {digest}")
        result = {"final_loss": last_loss,
                  "steps": step,
                  "updates": step - start,
                  "samples_per_sec": thr.samples_per_sec,
                  "env_frames_per_sec": thr.samples_per_sec,
                  "first_return": first_return,
                  "final_return": final_return,
                  "params_sha256": digest}
        if shutdown.requested:
            log(f"preempted (signal {shutdown.signum}): final checkpoint "
                f"at update {step}, exiting 0")
            result["preempted"] = True
        if self.guarded:
            result["skipped_updates"] = int(
                jax.device_get(self.state.opt_state.skipped))
        if last_fetched is not None:
            for k in ("entropy", "approx_kl", "value_loss"):
                if k in last_fetched:
                    result[k] = float(last_fetched[k])
        self.metrics.close()
        self.telemetry.close()
        if self.tracer is not None:
            trace_lib.stop_run(self.tracer)
        return result
