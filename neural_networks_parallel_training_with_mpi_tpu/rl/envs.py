"""Pure-JAX vectorized environments.

Each environment is a frozen dataclass of STATIC configuration whose
``reset``/``step`` methods are pure functions over explicit state::

    state, obs          = env.reset(key)
    state, obs, r, done = env.step(state, action, key)

so thousands of envs batch with one ``vmap`` and run entirely on device —
zero host round-trips per transition, which is the whole point of the
Anakin layout (arXiv 2104.06272 §2: "the environment itself is compiled
into the TPU program").  ``step`` AUTO-RESETS: when the transition ends
the episode (``done``), the returned state/obs already belong to a fresh
episode (seeded from the same per-step key), so a fixed-length
``lax.scan`` rollout never stalls on episode boundaries.  ``done`` flags
the boundary for GAE masking; the reward returned is the terminal
transition's.

Time-limit truncation is treated as termination (``done=1``, no
bootstrap) — the standard small-scale simplification; DESIGN.md §13
discusses the bias.

Determinism: every method consumes an explicit PRNG key and carries no
hidden state, so a rollout is a pure function of (params, env state,
keys) — the property the trajectory-exact checkpoint resume contract
rests on.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Pytree = Any
EnvState = Dict[str, jax.Array]


@dataclass(frozen=True)
class GridWorld:
    """N x N gridworld: start anywhere, walk to the fixed goal at the
    bottom-right corner.  Actions: 0=up 1=right 2=down 3=left (moves off
    the edge are no-ops).  Reward: ``goal_reward`` on reaching the goal,
    ``step_penalty`` per non-terminal step.  Episodes end at the goal or
    after ``max_steps`` transitions.  Observation: one-hot row ++ one-hot
    col (``2 * size`` floats) — small enough that the policy MLP is a few
    thousand params, rich enough that the optimal policy is non-trivial
    from every start cell."""

    size: int = 5
    max_steps: int = 30
    goal_reward: float = 1.0
    step_penalty: float = 0.01

    @property
    def obs_dim(self) -> int:
        return 2 * self.size

    @property
    def n_actions(self) -> int:
        return 4

    def _obs(self, state: EnvState) -> jax.Array:
        r = jax.nn.one_hot(state["pos"][0], self.size, dtype=jnp.float32)
        c = jax.nn.one_hot(state["pos"][1], self.size, dtype=jnp.float32)
        return jnp.concatenate([r, c])

    def reset(self, key: jax.Array) -> Tuple[EnvState, jax.Array]:
        # uniform over all cells EXCEPT the goal (a spawn on the goal
        # would be a zero-length episode)
        cell = jax.random.randint(key, (), 0, self.size * self.size - 1)
        state = {"pos": jnp.stack([cell // self.size, cell % self.size]
                                  ).astype(jnp.int32),
                 "t": jnp.zeros((), jnp.int32)}
        return state, self._obs(state)

    def step(self, state: EnvState, action: jax.Array, key: jax.Array
             ) -> Tuple[EnvState, jax.Array, jax.Array, jax.Array]:
        moves = jnp.asarray([[-1, 0], [0, 1], [1, 0], [0, -1]], jnp.int32)
        pos = jnp.clip(state["pos"] + moves[action], 0, self.size - 1)
        t = state["t"] + 1
        at_goal = jnp.all(pos == self.size - 1)
        done = (at_goal | (t >= self.max_steps)).astype(jnp.float32)
        reward = jnp.where(at_goal, jnp.float32(self.goal_reward),
                           jnp.float32(-self.step_penalty))
        nxt = {"pos": pos, "t": t}
        reset_state, reset_obs = self.reset(key)
        # auto-reset: where done, the carried state/obs are already the
        # next episode's (done itself still marks THIS transition)
        boolean = done > 0
        state_out = jax.tree_util.tree_map(
            lambda a, b: jnp.where(boolean, a, b), reset_state, nxt)
        obs_out = jnp.where(boolean, reset_obs, self._obs(nxt))
        return state_out, obs_out, reward, done


@dataclass(frozen=True)
class CartPole:
    """Classic CartPole-v1 dynamics (Barto-Sutton-Anderson), the control
    benchmark Anakin's paper itself uses for the toy scale: Euler
    integration at ``tau``, +1 reward per transition, episode ends when
    the pole falls (|theta| > ~12 deg), the cart leaves the track
    (|x| > 2.4), or after ``max_steps`` transitions."""

    gravity: float = 9.8
    masscart: float = 1.0
    masspole: float = 0.1
    length: float = 0.5          # half the pole length
    force_mag: float = 10.0
    tau: float = 0.02
    theta_threshold: float = 12 * 2 * jnp.pi / 360
    x_threshold: float = 2.4
    max_steps: int = 200

    @property
    def obs_dim(self) -> int:
        return 4

    @property
    def n_actions(self) -> int:
        return 2

    def reset(self, key: jax.Array) -> Tuple[EnvState, jax.Array]:
        x = jax.random.uniform(key, (4,), jnp.float32, -0.05, 0.05)
        state = {"x": x, "t": jnp.zeros((), jnp.int32)}
        return state, x

    def step(self, state: EnvState, action: jax.Array, key: jax.Array
             ) -> Tuple[EnvState, jax.Array, jax.Array, jax.Array]:
        x, x_dot, theta, theta_dot = (state["x"][0], state["x"][1],
                                      state["x"][2], state["x"][3])
        force = jnp.where(action == 1, self.force_mag, -self.force_mag)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        cos, sin = jnp.cos(theta), jnp.sin(theta)
        temp = (force + polemass_length * theta_dot**2 * sin) / total_mass
        theta_acc = ((self.gravity * sin - cos * temp)
                     / (self.length * (4.0 / 3.0
                                       - self.masspole * cos**2
                                       / total_mass)))
        x_acc = temp - polemass_length * theta_acc * cos / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * x_acc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * theta_acc
        vec = jnp.stack([x, x_dot, theta, theta_dot])
        t = state["t"] + 1
        fell = ((jnp.abs(x) > self.x_threshold)
                | (jnp.abs(theta) > self.theta_threshold))
        done = (fell | (t >= self.max_steps)).astype(jnp.float32)
        reward = jnp.ones((), jnp.float32)
        nxt = {"x": vec, "t": t}
        reset_state, reset_obs = self.reset(key)
        boolean = done > 0
        state_out = jax.tree_util.tree_map(
            lambda a, b: jnp.where(boolean, a, b), reset_state, nxt)
        obs_out = jnp.where(boolean, reset_obs, vec)
        return state_out, obs_out, reward, done


ENVS = {"gridworld": GridWorld, "cartpole": CartPole}


def make_env(name: str):
    """Build an environment from its config name (``config.RLConfig.env``)."""
    if name not in ENVS:
        raise ValueError(f"unknown env {name!r} (choices: "
                         f"{', '.join(sorted(ENVS))})")
    return ENVS[name]()
