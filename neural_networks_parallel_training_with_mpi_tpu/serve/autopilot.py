"""Fleet autopilot: the control loop that ACTS on the obs plane.

Everything before this module observes and annotates: the telemetry
plane rolls up SLO sketches (PR 14), the router places against live
load reports, the supervisor relaunches crashes — but replica count is
fixed at launch, new weights need a full restart, and a burn-rate alert
changes nothing.  :class:`Autopilot` closes the loop with three
decision kinds, each guarded so a noisy signal cannot flap the fleet:

* **Autoscaling** — scale out when mean replica occupancy or the
  router's fleet-queue depth crosses its high-water mark and HOLDS
  there (``scale_out_hold_s`` hysteresis); scale in when occupancy sits
  under the low-water mark with an empty queue for ``scale_in_hold_s``.
  Scale-in never drops work: the victim is retired at the supervisor
  (``GroupSupervisor.retire`` — its exit is terminal, no restart-budget
  burn), asked to drain (``Scheduler.drain`` inside the worker, the
  ``decommission`` op) and exits ``EXIT_DECOMMISSION`` (47); its
  in-flight requests requeue exactly once through the router's ledger
  and complete on siblings.  A drain that stalls past
  ``drain_timeout_s`` escalates to SIGKILL — safe, because the child is
  already retired.
* **Zero-downtime weight rollout** — :meth:`start_rollout` verifies a
  weight snapshot's manifest (utils/ckpt_manifest: size + sha256 per
  payload file) BEFORE spawning anything; a bad snapshot is refused
  with the serving generation untouched.  Verified, it spawns canary
  replicas of the next generation (strided replica ids:
  ``gen * GEN_STRIDE + k``, so flow traces and telemetry attribute
  every token to its generation), shifts a deterministic rid-modulo
  traffic slice onto them, and judges.
* **Canary judge with automatic rollback** — over a fixed observation
  window the judge reads the same per-writer breakdown rows
  ``tools/obs_agg.py`` renders (built from each replica's latest raw
  ``kind="rollup"`` load report — one record shape everywhere, the
  judge and the dashboard cannot disagree) plus the router's
  per-replica completion/deadline-miss ledger deltas.  Canary p50 TTFT
  beyond ``canary_max_p50_ratio`` x the stable generation's, a miss
  fraction over ``canary_max_miss_frac``, or a canary child that dies
  terminally (e.g. a corrupted-after-verify checkpoint exiting
  EXIT_ANOMALY) rolls the canary back — traffic restored, canaries
  decommissioned, the old generation never disturbed.  A healthy
  window promotes: the new generation grows to the old serving width,
  traffic shifts, and the old generation drains out through the same
  no-drop decommission path.

Every action consumed by a failure arms a bounded exponential backoff
(``action_backoff_s`` doubling to ``action_backoff_cap_s``), and
successful scaling actions arm a ``cooldown_s`` — the two guards that
keep a flapping signal from thrashing replicas.

No extra thread: :meth:`tick` rides the owner's service loop
(``Fleet.pump`` calls it when the autopilot is attached), so the
control loop's steady-state cost shows up — and is priced, bench.py
``--autopilot`` — in the same tokens/s the fleet reports.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from ..utils.sketches import QuantileSketch
from .fleet import GEN_STRIDE  # noqa: F401  (re-exported: the id<->
#   generation stride is part of this module's attribution contract)


# ---------------------------------------------------------------------------
# weight snapshots (the rollout artifact)
# ---------------------------------------------------------------------------

def save_weight_snapshot(ckpt_dir, params, step: int = 0,
                         meta: Optional[dict] = None) -> str:
    """Write a weight-only snapshot a rollout can verify and a worker
    can load: ``ckpt-<step>/weights.npz`` (flattened keystr -> array)
    committed through ``utils.ckpt_manifest`` — payload fsync'd,
    manifest written LAST with a size + sha256 per file — so
    :func:`load_weight_snapshot` (and the autopilot, before it spawns a
    generation) can prove integrity without unpickling anything.
    Returns the snapshot directory path."""
    import os

    import jax
    import numpy as np

    from ..utils import ckpt_manifest

    snap = Path(ckpt_dir) / f"{ckpt_manifest.CKPT_PREFIX}{int(step)}"
    snap.mkdir(parents=True, exist_ok=True)
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    arrs = {jax.tree_util.keystr(path): np.asarray(leaf)
            for path, leaf in flat}
    with open(snap / "weights.npz", "wb") as f:
        np.savez(f, **arrs)
        f.flush()
        os.fsync(f.fileno())
    ckpt_manifest.commit(
        snap, {"step": int(step), "kind": "weights", **(meta or {})})
    return str(snap)


def load_weight_snapshot(snap_dir, template):
    """Verify then load a :func:`save_weight_snapshot` directory into
    the structure of ``template`` (the worker's seed-initialized params,
    which fixes the expected tree).  Raises ``ValueError`` on ANY
    integrity, missing/extra-leaf, shape or dtype mismatch — the fleet
    worker maps that to ``EXIT_ANOMALY`` (44, deterministic no-retry),
    the signal a canary rollback keys on."""
    import jax
    import numpy as np

    snap_dir = Path(snap_dir)
    from ..utils import ckpt_manifest

    problems = ckpt_manifest.verify(snap_dir)
    if problems:
        raise ValueError(
            f"weight snapshot {snap_dir} failed verification: "
            f"{'; '.join(problems[:3])}")
    with np.load(snap_dir / "weights.npz") as z:
        arrs = {k: z[k] for k in z.files}
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if key not in arrs:
            raise ValueError(f"snapshot missing leaf {key}")
        a = arrs.pop(key)
        if tuple(a.shape) != tuple(leaf.shape):
            raise ValueError(f"snapshot leaf {key}: shape {a.shape}, "
                             f"model expects {tuple(leaf.shape)}")
        if a.dtype != leaf.dtype:
            raise ValueError(f"snapshot leaf {key}: dtype {a.dtype}, "
                             f"model expects {leaf.dtype}")
        leaves.append(a)
    if arrs:
        raise ValueError(f"snapshot has leaves the model does not: "
                         f"{sorted(arrs)[:5]}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# the control loop
# ---------------------------------------------------------------------------

@dataclass
class AutopilotConfig:
    """Guard rails for the three decision kinds (module docstring).
    Defaults suit the tiny CPU-emulated fleets of the examples/bench;
    real deployments scale the holds and windows with their traffic's
    noise floor."""
    # fleet width
    min_replicas: int = 1
    max_replicas: int = 4
    # decision cadence: tick() is called every Fleet.pump but only
    # evaluates this often (the steady-state overhead knob)
    interval_s: float = 0.2
    # autoscaling signal + hysteresis
    high_occupancy: float = 1.25   # mean (in_flight+queued)/slots
    high_queue: int = 8            # router fleet-queue high water
    low_occupancy: float = 0.25
    scale_out_hold_s: float = 0.75
    scale_in_hold_s: float = 3.0
    cooldown_s: float = 5.0        # between successful scaling actions
    # bounded backoff after a FAILED/rolled-back action
    action_backoff_s: float = 1.0
    action_backoff_cap_s: float = 30.0
    # decommission / spawn liveness bounds
    drain_timeout_s: float = 10.0
    ready_timeout_s: float = 120.0
    # canary policy
    canary_replicas: int = 1
    canary_fraction: float = 0.25
    canary_window_s: float = 5.0
    canary_min_completed: int = 5
    canary_max_extensions: int = 3
    canary_max_p50_ratio: float = 3.0
    canary_max_miss_frac: float = 0.25
    # decision-ledger persistence: when set, every decision is appended
    # as one ``kind="autopilot"`` JSON line (the control loop's flight
    # recorder — rendered by ``metrics_summary --autopilot`` and drawn
    # as instant events by ``trace_report``, joined into the goodput
    # ledger by ``goodput_report``)
    events_path: Optional[str] = None


class Autopilot:
    """The supervisor-side control loop over a running fleet.  The
    ``fleet`` object provides the actuation surface (``Fleet`` has all
    of it; tests drive an in-process stand-in): ``router``,
    ``add_replica``, ``decommission``, ``force_kill``, ``replica_done``,
    ``remove_replica``.  All state is host-side bookkeeping;
    :meth:`tick` is cheap enough to ride every service-loop pass."""

    def __init__(self, fleet, cfg: Optional[AutopilotConfig] = None,
                 log: Optional[Callable[[str], None]] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        self.fleet = fleet
        self.cfg = cfg or AutopilotConfig()
        self.log = log or (lambda m: None)
        self._now = now_fn
        self._t0 = now_fn()
        self.decisions: List[Dict[str, Any]] = []
        self._last_eval = -math.inf
        # hysteresis + flap guards
        self._high_since: Optional[float] = None
        self._low_since: Optional[float] = None
        self._cooldown_until = -math.inf
        self._backoff_until = -math.inf
        self._failures = 0
        # in-flight actions
        self._pending_out: Optional[Dict[str, Any]] = None
        self._draining: Dict[str, Dict[str, Any]] = {}
        self._rollout: Optional[Dict[str, Any]] = None

    # ---- bookkeeping ---------------------------------------------------
    def _decide(self, action: str, **extra) -> Dict[str, Any]:
        d = {"t": round(self._now() - self._t0, 3), "action": action,
             **extra}
        self.decisions.append(d)
        self.log(f"[autopilot] {action}: "
                 + ", ".join(f"{k}={v}" for k, v in extra.items()))
        if self.cfg.events_path:
            # append-only flight recorder; t_unix puts decisions on the
            # same wall-clock axis as the trace spans, so trace_report
            # can draw them as instants over the tick timeline
            import json
            import os

            try:
                rec = {"kind": "autopilot",
                       "t_unix": round(time.time(), 3),
                       "run": os.environ.get("NNPT_RUN_ID", ""),
                       "p": int(os.environ.get("NNPT_PROCESS_ID", "0")
                                or 0),
                       "inc": int(os.environ.get("NNPT_INCARNATION",
                                                 "0") or 0),
                       **d}
                with open(self.cfg.events_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
            except (OSError, TypeError, ValueError):
                pass  # the ledger must never take the control loop down
        return d

    def _action_failed(self, now: float, action: str,
                       why: str) -> None:
        self._failures += 1
        delay = min(self.cfg.action_backoff_s
                    * (2.0 ** (self._failures - 1)),
                    self.cfg.action_backoff_cap_s)
        self._backoff_until = now + delay
        self._decide("action_backoff", failed=action, why=why,
                     backoff_s=round(delay, 2))

    def _primary_gen(self) -> int:
        return self.fleet.router._primary_gen

    def _active(self) -> List[Any]:
        """Replicas the autopilot counts as serving capacity: registered
        at the router and not already being drained out."""
        return [h for h in self.fleet.router.replicas
                if h.name not in self._draining]

    def summary(self) -> Dict[str, Any]:
        """Decision counts per action (bench/test assertion surface)."""
        by: Dict[str, int] = {}
        for d in self.decisions:
            by[d["action"]] = by.get(d["action"], 0) + 1
        return {"decisions": len(self.decisions), "by_action": by,
                "draining": sorted(self._draining),
                "rollout": (self._rollout or {}).get("phase")}

    # ---- the judge's input ---------------------------------------------
    def breakdown(self) -> List[Dict[str, Any]]:
        """One row per replica in ``tools/obs_agg.py``'s per-writer
        breakdown shape, built from each replica's latest RAW
        ``kind="rollup"`` load report (the identical document obs_agg
        merges from the telemetry dirs — same sketches, same ``now``
        gauges), plus the generation tag the judge slices on."""
        rows = []
        for h in self.fleet.router.replicas:
            rec = getattr(h, "report", None)
            if rec is None and hasattr(h, "sched"):
                rec = h.sched.load_report()     # InprocReplica
            if not rec:
                continue
            row: Dict[str, Any] = {
                "name": h.name, "role": rec.get("role", "serve"),
                "replica": rec.get("replica"),
                "generation": getattr(h, "generation", 0),
                "step": rec.get("step"),
            }
            for metric in ("ttft_ms", "itl_ms"):
                doc = (rec.get("sketches") or {}).get(metric)
                if doc:
                    sk = QuantileSketch.from_dict(doc)
                    row[f"{metric}_p50"] = sk.quantile(0.5)
                    row[f"{metric}_p99"] = sk.quantile(0.99)
            now_d = rec.get("now") or {}
            for k in ("queue_depth", "in_flight", "block_utilization"):
                if k in now_d:
                    row[k] = now_d[k]
            rows.append(row)
        return rows

    # ---- the loop ------------------------------------------------------
    def tick(self) -> List[Dict[str, Any]]:
        """One control evaluation (rate-limited to ``interval_s``);
        returns the decisions made during this call."""
        now = self._now()
        if now - self._last_eval < self.cfg.interval_s:
            return []
        self._last_eval = now
        before = len(self.decisions)
        self._watch_pending_out(now)
        self._watch_draining(now)
        if self._rollout is not None:
            self._advance_rollout(now)
        else:
            self._autoscale(now)
        return self.decisions[before:]

    # ---- autoscaling ---------------------------------------------------
    def _observe(self):
        router = self.fleet.router
        occs = []
        for h in self._active():
            if not h.accepting():
                continue
            sig = h.load()
            occs.append(sig.occupancy if sig is not None else 0.0)
        queue = len(router.queue)
        mean_occ = (sum(occs) / len(occs)) if occs else math.inf
        return mean_occ, queue

    def _autoscale(self, now: float) -> None:
        cfg = self.cfg
        mean_occ, queue = self._observe()
        high = (mean_occ >= cfg.high_occupancy
                or queue >= cfg.high_queue)
        low = mean_occ <= cfg.low_occupancy and queue == 0
        # hysteresis: the signal must HOLD before anything moves
        if high:
            self._low_since = None
            if self._high_since is None:
                self._high_since = now
        elif low:
            self._high_since = None
            if self._low_since is None:
                self._low_since = now
        else:
            self._high_since = self._low_since = None
        if (now < self._cooldown_until or now < self._backoff_until
                or self._pending_out is not None or self._draining):
            return                      # one action in flight at a time
        n = len(self._active())
        if (self._high_since is not None
                and now - self._high_since >= cfg.scale_out_hold_s
                and n < cfg.max_replicas):
            self._scale_out(now, reason={
                "mean_occupancy": round(mean_occ, 3)
                if math.isfinite(mean_occ) else None,
                "queue_depth": queue})
        elif (self._low_since is not None
                and now - self._low_since >= cfg.scale_in_hold_s
                and n > cfg.min_replicas):
            self._scale_in(now, reason={
                "mean_occupancy": round(mean_occ, 3)
                if math.isfinite(mean_occ) else None})

    def _scale_out(self, now: float, reason) -> None:
        try:
            h = self.fleet.add_replica(generation=self._primary_gen())
        except Exception as exc:          # spawn refusal = failed action
            self._action_failed(now, "scale_out", str(exc)[:200])
            return
        self._pending_out = {"name": h.name, "t": now,
                             "deadline": now + self.cfg.ready_timeout_s}
        self._high_since = None
        self._cooldown_until = now + self.cfg.cooldown_s
        self._decide("scale_out", replica=h.name, **reason)

    def _watch_pending_out(self, now: float) -> None:
        p = self._pending_out
        if p is None:
            return
        h = next((r for r in self.fleet.router.replicas
                  if r.name == p["name"]), None)
        if h is not None and h.accepting():
            self._pending_out = None
            self._failures = 0
            self._decide("scale_out_ready", replica=p["name"],
                         reaction_s=round(now - p["t"], 3))
            return
        rc = self.fleet.replica_done(p["name"])
        if rc is not None:
            # the supervisor gave up on (or terminally stopped) the new
            # child before it ever served — undo the registration
            self._pending_out = None
            self.fleet.remove_replica(p["name"])
            self._action_failed(now, "scale_out",
                                f"{p['name']} never ready (rc {rc})")
            return
        if now >= p["deadline"]:
            self._pending_out = None
            try:
                self.fleet.supervisor.retire(p["name"])
            except (KeyError, AttributeError):
                pass
            self.fleet.force_kill(p["name"])
            self.fleet.remove_replica(p["name"])
            self._action_failed(now, "scale_out",
                                f"{p['name']} ready timeout")

    def _scale_in(self, now: float, reason) -> None:
        gen = self._primary_gen()
        victims = [h for h in self._active()
                   if getattr(h, "generation", 0) == gen]
        if len(victims) <= self.cfg.min_replicas:
            return
        victim = max(victims, key=lambda h: h.name)  # newest out first
        self._begin_decommission(now, victim.name, kind="scale_in")
        self._low_since = None
        self._cooldown_until = now + self.cfg.cooldown_s
        self._decide("scale_in", replica=victim.name, **reason)

    # ---- decommission (the no-drop removal primitive) ------------------
    def _begin_decommission(self, now: float, name: str,
                            kind: str) -> None:
        sent = self.fleet.decommission(name)
        self._draining[name] = {
            "t": now, "deadline": now + self.cfg.drain_timeout_s,
            "forced": False, "kind": kind, "op_sent": sent,
            "base_requeued": self.fleet.router.requeued}

    def _watch_draining(self, now: float) -> None:
        for name, st in list(self._draining.items()):
            rc = self.fleet.replica_done(name)
            if rc is not None:
                self.fleet.remove_replica(name)
                del self._draining[name]
                self._decide(
                    "drained", replica=name, rc=rc, kind=st["kind"],
                    forced=st["forced"],
                    wall_s=round(now - st["t"], 3),
                    requeued=self.fleet.router.requeued
                    - st["base_requeued"])
                if self._rollout is not None:
                    self._check_promote_done(now)
                continue
            if now >= st["deadline"] and not st["forced"]:
                # stalled drain: the child is already retired, so the
                # kill is terminal — no relaunch, ledger requeues once
                st["forced"] = True
                self.fleet.force_kill(name)
                self._decide("drain_stalled_kill", replica=name,
                             kind=st["kind"],
                             after_s=round(now - st["t"], 3))

    # ---- rollout / canary ----------------------------------------------
    def start_rollout(self, snapshot_dir,
                      canary_replicas: Optional[int] = None,
                      canary_fraction: Optional[float] = None,
                      step_sleep_ms: Optional[float] = None) -> bool:
        """Begin a zero-downtime weight rollout from a snapshot dir
        (:func:`save_weight_snapshot` layout).  Verification happens
        HERE, before any process spawns: a bad snapshot returns False
        with the serving generation untouched (decision
        ``rollout_rejected``).  ``step_sleep_ms`` overrides the canary
        workers' emulated device latency (chaos/testing knob: a slow
        canary must roll back on its SLO judgment)."""
        if self._rollout is not None:
            raise RuntimeError("a rollout is already in progress")
        now = self._now()
        from ..utils import ckpt_manifest

        problems = ckpt_manifest.verify(Path(snapshot_dir))
        if problems:
            self._decide("rollout_rejected",
                         snapshot=str(snapshot_dir),
                         problems=problems[:3])
            self._action_failed(now, "rollout", "snapshot unverified")
            return False
        gen = self._primary_gen() + 1
        k = canary_replicas or self.cfg.canary_replicas
        names = []
        try:
            for _ in range(k):
                h = self.fleet.add_replica(
                    generation=gen, ckpt=str(snapshot_dir),
                    step_sleep_ms=step_sleep_ms)
                names.append(h.name)
        except Exception as exc:
            for n in names:
                self.fleet.force_kill(n)
                self.fleet.remove_replica(n)
            self._action_failed(now, "rollout", str(exc)[:200])
            return False
        self._rollout = {
            "phase": "wait_ready", "gen": gen,
            "snapshot": str(snapshot_dir), "canary": names,
            "step_sleep_ms": step_sleep_ms, "t0": now,
            "fraction": (canary_fraction
                         if canary_fraction is not None
                         else self.cfg.canary_fraction),
            "deadline": now + self.cfg.ready_timeout_s,
            "extensions": 0,
        }
        self._decide("canary_spawn", generation=gen,
                     replicas=list(names),  # copy: _promote grows it
                     snapshot=str(snapshot_dir))
        return True

    def _canary_handles(self) -> List[Any]:
        names = set(self._rollout["canary"])
        return [h for h in self.fleet.router.replicas
                if h.name in names]

    def _advance_rollout(self, now: float) -> None:
        ro = self._rollout
        phase = ro["phase"]
        if phase == "promote_drain":
            self._check_promote_done(now)
            return
        # a canary child that terminally died (bad checkpoint -> exit
        # 44; supervisor gave up) fails the rollout in ANY phase
        for name in list(ro["canary"]):
            rc = self.fleet.replica_done(name)
            if rc is not None and name not in self._draining:
                self._rollback(now, f"canary {name} died (rc {rc})")
                return
        if phase == "wait_ready":
            if all(h.accepting() for h in self._canary_handles()) \
                    and self._canary_handles():
                router = self.fleet.router
                router.set_traffic(self._primary_gen(),
                                   canary_generation=ro["gen"],
                                   canary_fraction=ro["fraction"])
                ro["phase"] = "judge"
                ro["window_end"] = now + self.cfg.canary_window_s
                ro["base_completed"] = router.per_replica_completed()
                ro["base_missed"] = router.per_replica_missed()
                self._decide("canary_traffic",
                             fraction=ro["fraction"],
                             generation=ro["gen"])
            elif now >= ro["deadline"]:
                self._rollback(now, "canary never became ready")
            return
        if phase == "judge" and now >= ro["window_end"]:
            self._judge(now)

    def _judge(self, now: float) -> None:
        ro = self._rollout
        cfg = self.cfg
        router = self.fleet.router
        canary = set(ro["canary"])
        comp = router.per_replica_completed()
        miss = router.per_replica_missed()
        done = sum(comp.get(n, 0) - ro["base_completed"].get(n, 0)
                   for n in canary)
        missed = sum(miss.get(n, 0) - ro["base_missed"].get(n, 0)
                     for n in canary)
        if done < cfg.canary_min_completed:
            if ro["extensions"] < cfg.canary_max_extensions:
                ro["extensions"] += 1
                ro["window_end"] = now + cfg.canary_window_s
                self._decide("canary_window_extended",
                             completed=done,
                             extension=ro["extensions"])
                return
            self._rollback(now, f"insufficient canary traffic "
                                f"({done} completed)")
            return
        miss_frac = missed / done
        # latency verdict from the router's WINDOWED completion samples
        # (FleetRouter.recent), not the replicas' lifetime sketches: a
        # fresh canary's first-compile TTFTs would dominate a lifetime
        # p50 forever and roll back every healthy push.  Samples before
        # the traffic shift (minus the judge window, for the stable
        # side's sample size) are out of scope.
        t_cut = now - self.cfg.canary_window_s * (
            1 + ro["extensions"] + 1)
        canary_ts, stable_ts = [], []
        for s in router.recent:
            if s["t"] < t_cut or s["ttft_ms"] is None:
                continue
            if s["generation"] == ro["gen"]:
                canary_ts.append(s["ttft_ms"])
            elif s["generation"] == self._primary_gen():
                stable_ts.append(s["ttft_ms"])
        ratio = None
        if canary_ts and stable_ts:
            c_p50 = sorted(canary_ts)[len(canary_ts) // 2]
            s_p50 = sorted(stable_ts)[len(stable_ts) // 2]
            if s_p50 > 0:
                ratio = c_p50 / s_p50
        verdict = {"completed": done, "missed": missed,
                   "miss_frac": round(miss_frac, 3),
                   "p50_ratio": (round(ratio, 2)
                                 if ratio is not None else None)}
        if miss_frac > cfg.canary_max_miss_frac:
            self._rollback(now, f"canary SLO burn {miss_frac:.0%}",
                           **verdict)
            return
        if ratio is not None and ratio > cfg.canary_max_p50_ratio:
            self._rollback(now, f"canary p50 {ratio:.1f}x stable",
                           **verdict)
            return
        self._promote(now, verdict)

    def _promote(self, now: float, verdict: Dict[str, Any]) -> None:
        ro = self._rollout
        router = self.fleet.router
        old_gen = self._primary_gen()
        old = [h for h in self._active()
               if getattr(h, "generation", 0) == old_gen]
        # grow the new generation to the old serving width, then shift
        # all traffic; old-gen replicas stay accepting until their drain
        # lands (generation preference, not partition — zero downtime
        # while the extras compile)
        grow = max(0, len(old) - len(ro["canary"]))
        try:
            for _ in range(grow):
                h = self.fleet.add_replica(
                    generation=ro["gen"], ckpt=ro["snapshot"],
                    step_sleep_ms=ro["step_sleep_ms"])
                ro["canary"].append(h.name)
        except Exception as exc:
            self._rollback(now, f"promote spawn failed: {exc}")
            return
        router.set_traffic(ro["gen"])
        for h in old:
            self._begin_decommission(now, h.name, kind="rollout_old")
        ro["phase"] = "promote_drain"
        ro["old"] = [h.name for h in old]
        self._decide("canary_promote", generation=ro["gen"],
                     draining=[h.name for h in old], **verdict)

    def _check_promote_done(self, now: float) -> None:
        ro = self._rollout
        if ro is None or ro["phase"] != "promote_drain":
            return
        if any(n in self._draining for n in ro["old"]):
            return
        self._failures = 0
        self._decide("rollout_complete", generation=ro["gen"],
                     wall_s=round(now - ro["t0"], 3))
        self._rollout = None

    def _rollback(self, now: float, reason: str, **extra) -> None:
        ro = self._rollout
        router = self.fleet.router
        # restore traffic FIRST: the old generation takes everything
        # again before the canaries disappear
        router.set_traffic(self._primary_gen())
        for name in ro["canary"]:
            if name in self._draining:
                continue
            if self.fleet.replica_done(name) is not None:
                self.fleet.remove_replica(name)
            else:
                self._begin_decommission(now, name, kind="rollback")
        self._decide("canary_rollback", generation=ro["gen"],
                     reason=reason, **extra)
        self._rollout = None
        self._action_failed(now, "rollout", reason)
