"""Fleet autopilot: the control loop that ACTS on the obs plane.

Everything before this module observes and annotates: the telemetry
plane rolls up SLO sketches (PR 14), the router places against live
load reports, the supervisor relaunches crashes — but replica count is
fixed at launch, new weights need a full restart, and a burn-rate alert
changes nothing.  :class:`Autopilot` closes the loop with three
decision kinds, each guarded so a noisy signal cannot flap the fleet:

* **Autoscaling** — scale out when mean replica occupancy or the
  router's fleet-queue depth crosses its high-water mark and HOLDS
  there (``scale_out_hold_s`` hysteresis); scale in when occupancy sits
  under the low-water mark with an empty queue for ``scale_in_hold_s``.
  Scale-in never drops work: the victim is retired at the supervisor
  (``GroupSupervisor.retire`` — its exit is terminal, no restart-budget
  burn), asked to drain (``Scheduler.drain`` inside the worker, the
  ``decommission`` op) and exits ``EXIT_DECOMMISSION`` (47); its
  in-flight requests requeue exactly once through the router's ledger
  and complete on siblings.  A drain that stalls past
  ``drain_timeout_s`` escalates to SIGKILL — safe, because the child is
  already retired.
* **Zero-downtime weight rollout** — :meth:`start_rollout` verifies a
  weight snapshot's manifest (utils/ckpt_manifest: size + sha256 per
  payload file) BEFORE spawning anything; a bad snapshot is refused
  with the serving generation untouched.  Verified, it spawns canary
  replicas of the next generation (strided replica ids:
  ``gen * GEN_STRIDE + k``, so flow traces and telemetry attribute
  every token to its generation), shifts a deterministic rid-modulo
  traffic slice onto them, and judges.
* **Canary judge with automatic rollback** — over a fixed observation
  window the judge reads the same per-writer breakdown rows
  ``tools/obs_agg.py`` renders (built from each replica's latest raw
  ``kind="rollup"`` load report — one record shape everywhere, the
  judge and the dashboard cannot disagree) plus the router's
  per-replica completion/deadline-miss ledger deltas.  Canary p50 TTFT
  beyond ``canary_max_p50_ratio`` x the stable generation's, a miss
  fraction over ``canary_max_miss_frac``, or a canary child that dies
  terminally (e.g. a corrupted-after-verify checkpoint exiting
  EXIT_ANOMALY) rolls the canary back — traffic restored, canaries
  decommissioned, the old generation never disturbed.  A healthy
  window promotes: the new generation grows to the old serving width,
  traffic shifts, and the old generation drains out through the same
  no-drop decommission path.

Two robustness decision kinds ride the same guards: **preemption
backfill** (a replica announcing an advance notice — ``preempt_notice``
on the wire — is priced as lost capacity immediately; a replacement
spawns while the victim finishes its in-flight work and exits 47) and
**degraded-replica eviction** (``health_eviction``: a slow-but-alive
replica whose windowed TTFT median or rollup ITL p50 sits
``evict_*_ratio`` x beyond its peers' median for ``evict_hold_s`` is
replaced-then-drained — the replacement accepts before the victim
decommissions, so the fleet never dips below ``min_replicas``).

Every action consumed by a failure arms a bounded exponential backoff
(``action_backoff_s`` doubling to ``action_backoff_cap_s``), and
successful scaling actions arm a ``cooldown_s`` — the two guards that
keep a flapping signal from thrashing replicas.

No extra thread: :meth:`tick` rides the owner's service loop
(``Fleet.pump`` calls it when the autopilot is attached), so the
control loop's steady-state cost shows up — and is priced, bench.py
``--autopilot`` — in the same tokens/s the fleet reports.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.sketches import QuantileSketch
from .fleet import GEN_STRIDE  # noqa: F401  (re-exported: the id<->
#   generation stride is part of this module's attribution contract)
from .fleet import role_kind


# ---------------------------------------------------------------------------
# weight snapshots (the rollout artifact)
# ---------------------------------------------------------------------------

def save_weight_snapshot(ckpt_dir, params, step: int = 0,
                         meta: Optional[dict] = None) -> str:
    """Write a weight-only snapshot a rollout can verify and a worker
    can load: ``ckpt-<step>/weights.npz`` (flattened keystr -> array)
    committed through ``utils.ckpt_manifest`` — payload fsync'd,
    manifest written LAST with a size + sha256 per file — so
    :func:`load_weight_snapshot` (and the autopilot, before it spawns a
    generation) can prove integrity without unpickling anything.
    Returns the snapshot directory path."""
    import os

    import jax
    import numpy as np

    from ..utils import ckpt_manifest

    snap = Path(ckpt_dir) / f"{ckpt_manifest.CKPT_PREFIX}{int(step)}"
    snap.mkdir(parents=True, exist_ok=True)
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    arrs = {jax.tree_util.keystr(path): np.asarray(leaf)
            for path, leaf in flat}
    with open(snap / "weights.npz", "wb") as f:
        np.savez(f, **arrs)
        f.flush()
        os.fsync(f.fileno())
    ckpt_manifest.commit(
        snap, {"step": int(step), "kind": "weights", **(meta or {})})
    return str(snap)


def load_weight_snapshot(snap_dir, template):
    """Verify then load a :func:`save_weight_snapshot` directory into
    the structure of ``template`` (the worker's seed-initialized params,
    which fixes the expected tree).  Raises ``ValueError`` on ANY
    integrity, missing/extra-leaf, shape or dtype mismatch — the fleet
    worker maps that to ``EXIT_ANOMALY`` (44, deterministic no-retry),
    the signal a canary rollback keys on."""
    import jax
    import numpy as np

    snap_dir = Path(snap_dir)
    from ..utils import ckpt_manifest

    problems = ckpt_manifest.verify(snap_dir)
    if problems:
        raise ValueError(
            f"weight snapshot {snap_dir} failed verification: "
            f"{'; '.join(problems[:3])}")
    with np.load(snap_dir / "weights.npz") as z:
        arrs = {k: z[k] for k in z.files}
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if key not in arrs:
            raise ValueError(f"snapshot missing leaf {key}")
        a = arrs.pop(key)
        if tuple(a.shape) != tuple(leaf.shape):
            raise ValueError(f"snapshot leaf {key}: shape {a.shape}, "
                             f"model expects {tuple(leaf.shape)}")
        if a.dtype != leaf.dtype:
            raise ValueError(f"snapshot leaf {key}: dtype {a.dtype}, "
                             f"model expects {leaf.dtype}")
        leaves.append(a)
    if arrs:
        raise ValueError(f"snapshot has leaves the model does not: "
                         f"{sorted(arrs)[:5]}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# the control loop
# ---------------------------------------------------------------------------

@dataclass
class AutopilotConfig:
    """Guard rails for the three decision kinds (module docstring).
    Defaults suit the tiny CPU-emulated fleets of the examples/bench;
    real deployments scale the holds and windows with their traffic's
    noise floor."""
    # fleet width
    min_replicas: int = 1
    max_replicas: int = 4
    # per-role floor for DISAGGREGATED fleets (prefill/decode roles,
    # DESIGN.md §11): a role pool that has ever served is kept at this
    # many replicas — scale-in refuses victims that would breach it,
    # and an EMPTIED pool (crash-then-retire, eviction) is backfilled
    # with the same role so degraded unified serving is a transient,
    # not a steady state.  Unified fleets never hit either path.
    min_per_role: int = 1
    # decision cadence: tick() is called every Fleet.pump but only
    # evaluates this often (the steady-state overhead knob)
    interval_s: float = 0.2
    # autoscaling signal + hysteresis
    high_occupancy: float = 1.25   # mean (in_flight+queued)/slots
    high_queue: int = 8            # router fleet-queue high water
    low_occupancy: float = 0.25
    scale_out_hold_s: float = 0.75
    scale_in_hold_s: float = 3.0
    cooldown_s: float = 5.0        # between successful scaling actions
    # bounded backoff after a FAILED/rolled-back action
    action_backoff_s: float = 1.0
    action_backoff_cap_s: float = 30.0
    # decommission / spawn liveness bounds
    drain_timeout_s: float = 10.0
    ready_timeout_s: float = 120.0
    # canary policy
    canary_replicas: int = 1
    canary_fraction: float = 0.25
    canary_window_s: float = 5.0
    canary_min_completed: int = 5
    canary_max_extensions: int = 3
    canary_max_p50_ratio: float = 3.0
    canary_max_miss_frac: float = 0.25
    # degraded-replica eviction (off by default: an A/B bench or an
    # operator turns it on).  A replica whose WINDOWED TTFT median — or
    # lifetime-rollup ITL p50 — sits ``evict_*_ratio`` x beyond the
    # median of its peers for ``evict_hold_s`` is replaced-then-drained:
    # the replacement spawns first, the victim decommissions only once
    # the replacement accepts, so the fleet never dips below
    # ``min_replicas`` (transiently +1 wide, like a rollout).
    health_eviction: bool = False
    evict_ttft_ratio: float = 3.0
    evict_itl_ratio: float = 3.0
    health_window_s: float = 6.0
    evict_hold_s: float = 1.0
    evict_min_samples: int = 8
    # decision-ledger persistence: when set, every decision is appended
    # as one ``kind="autopilot"`` JSON line (the control loop's flight
    # recorder — rendered by ``metrics_summary --autopilot`` and drawn
    # as instant events by ``trace_report``, joined into the goodput
    # ledger by ``goodput_report``)
    events_path: Optional[str] = None


class Autopilot:
    """The supervisor-side control loop over a running fleet.  The
    ``fleet`` object provides the actuation surface (``Fleet`` has all
    of it; tests drive an in-process stand-in): ``router``,
    ``add_replica``, ``decommission``, ``force_kill``, ``replica_done``,
    ``remove_replica``.  All state is host-side bookkeeping;
    :meth:`tick` is cheap enough to ride every service-loop pass."""

    def __init__(self, fleet, cfg: Optional[AutopilotConfig] = None,
                 log: Optional[Callable[[str], None]] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        self.fleet = fleet
        self.cfg = cfg or AutopilotConfig()
        self.log = log or (lambda m: None)
        self._now = now_fn
        self._t0 = now_fn()
        self.decisions: List[Dict[str, Any]] = []
        self._last_eval = -math.inf
        # hysteresis + flap guards
        self._high_since: Optional[float] = None
        self._low_since: Optional[float] = None
        self._cooldown_until = -math.inf
        self._backoff_until = -math.inf
        self._failures = 0
        # in-flight actions
        self._pending_out: Optional[Dict[str, Any]] = None
        self._draining: Dict[str, Dict[str, Any]] = {}
        self._rollout: Optional[Dict[str, Any]] = None
        # preemption notices + health-eviction hysteresis
        self._noticed_seen: set = set()
        self._backfill_due: List[Tuple[str, Optional[str]]] = []
        self._unhealthy_since: Dict[str, float] = {}
        # disagg role memory: pools this fleet has served with.  A pool
        # that empties (all members dead AND removed) leaves no handle
        # to read the role from, so remember it here — _watch_pools
        # backfills from this set.
        self._roles_seen: set = set()
        # one-shot WAL-recovery disclosure (first tick after a router
        # relaunch): everything this loop observes — rollups, rates,
        # per-replica history — was REBUILT from the journal, not
        # carried across the crash
        self._recovery_disclosed = False

    # ---- bookkeeping ---------------------------------------------------
    def _decide(self, action: str, **extra) -> Dict[str, Any]:
        d = {"t": round(self._now() - self._t0, 3), "action": action,
             **extra}
        self.decisions.append(d)
        self.log(f"[autopilot] {action}: "
                 + ", ".join(f"{k}={v}" for k, v in extra.items()))
        if self.cfg.events_path:
            # append-only flight recorder; t_unix puts decisions on the
            # same wall-clock axis as the trace spans, so trace_report
            # can draw them as instants over the tick timeline
            import json
            import os

            try:
                rec = {"kind": "autopilot",
                       "t_unix": round(time.time(), 3),
                       "run": os.environ.get("NNPT_RUN_ID", ""),
                       "p": int(os.environ.get("NNPT_PROCESS_ID", "0")
                                or 0),
                       "inc": int(os.environ.get("NNPT_INCARNATION",
                                                 "0") or 0),
                       **d}
                with open(self.cfg.events_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
            except (OSError, TypeError, ValueError):
                pass  # the ledger must never take the control loop down
        return d

    def _action_failed(self, now: float, action: str,
                       why: str) -> None:
        self._failures += 1
        delay = min(self.cfg.action_backoff_s
                    * (2.0 ** (self._failures - 1)),
                    self.cfg.action_backoff_cap_s)
        self._backoff_until = now + delay
        self._decide("action_backoff", failed=action, why=why,
                     backoff_s=round(delay, 2))

    def _primary_gen(self) -> int:
        return self.fleet.router._primary_gen

    def _active(self) -> List[Any]:
        """Replicas the autopilot counts as serving capacity: registered
        at the router and not already being drained out."""
        return [h for h in self.fleet.router.replicas
                if h.name not in self._draining]

    def _spawn(self, role: Optional[str] = None, **kw):
        """``fleet.add_replica`` with the role passed ONLY when set, so
        unified fleets (and the in-process stand-ins tests drive) keep
        their pre-disagg call shape."""
        if role is not None and role != "unified":
            return self.fleet.add_replica(role=role, **kw)
        return self.fleet.add_replica(**kw)

    def _pool_counts(self) -> Dict[str, int]:
        """LIVE replicas per role kind (prefill / decode / unified),
        and the role-memory update: any disagg role seen here is
        remembered for empty-pool backfill.  Membership is ``alive``,
        not ``accepting`` — a replica still compiling occupies its
        pool (else the startup window would read as an empty pool and
        trigger a spurious backfill)."""
        by: Dict[str, int] = {}
        for h in self._active():
            kind = role_kind(h)
            if kind in ("prefill", "decode"):
                self._roles_seen.add(kind)
            alive = getattr(h, "alive", None)
            live = alive() if callable(alive) else h.accepting()
            if live and not getattr(h, "noticed", False):
                by[kind] = by.get(kind, 0) + 1
        return by

    def summary(self) -> Dict[str, Any]:
        """Decision counts per action (bench/test assertion surface)."""
        by: Dict[str, int] = {}
        for d in self.decisions:
            by[d["action"]] = by.get(d["action"], 0) + 1
        return {"decisions": len(self.decisions), "by_action": by,
                "draining": sorted(self._draining),
                "rollout": (self._rollout or {}).get("phase")}

    # ---- the judge's input ---------------------------------------------
    def breakdown(self) -> List[Dict[str, Any]]:
        """One row per replica in ``tools/obs_agg.py``'s per-writer
        breakdown shape, built from each replica's latest RAW
        ``kind="rollup"`` load report (the identical document obs_agg
        merges from the telemetry dirs — same sketches, same ``now``
        gauges), plus the generation tag the judge slices on."""
        rows = []
        for h in self.fleet.router.replicas:
            rec = getattr(h, "report", None)
            if rec is None and hasattr(h, "sched"):
                rec = h.sched.load_report()     # InprocReplica
            if not rec:
                continue
            row: Dict[str, Any] = {
                "name": h.name, "role": rec.get("role", "serve"),
                "replica": rec.get("replica"),
                "generation": getattr(h, "generation", 0),
                "step": rec.get("step"),
            }
            for metric in ("ttft_ms", "itl_ms"):
                doc = (rec.get("sketches") or {}).get(metric)
                if doc:
                    sk = QuantileSketch.from_dict(doc)
                    row[f"{metric}_p50"] = sk.quantile(0.5)
                    row[f"{metric}_p99"] = sk.quantile(0.99)
            now_d = rec.get("now") or {}
            for k in ("queue_depth", "in_flight", "block_utilization"):
                if k in now_d:
                    row[k] = now_d[k]
            rows.append(row)
        return rows

    # ---- the loop ------------------------------------------------------
    def tick(self) -> List[Dict[str, Any]]:
        """One control evaluation (rate-limited to ``interval_s``);
        returns the decisions made during this call."""
        now = self._now()
        if now - self._last_eval < self.cfg.interval_s:
            return []
        self._last_eval = now
        before = len(self.decisions)
        rec = getattr(self.fleet.router, "recovery", None)
        if not self._recovery_disclosed and rec and rec.get("recovered"):
            # disclose ONCE that this incarnation's state is journal-
            # rebuilt (serve/wal.py): consumers of the decision ledger
            # must not read pre-crash trends into post-crash rollups
            self._recovery_disclosed = True
            self._decide("post_recovery",
                         replayed=rec.get("replayed", 0),
                         deduped=rec.get("deduped", 0),
                         converted=rec.get("converted", 0),
                         lost=rec.get("lost", 0),
                         wall_s=rec.get("wall_s", 0.0))
        self._watch_pending_out(now)
        self._watch_notices(now)
        self._watch_draining(now)
        if self._rollout is not None:
            self._advance_rollout(now)
        else:
            self._watch_pools(now)
            self._autoscale(now)
            self._health_evict(now)
        return self.decisions[before:]

    # ---- disagg pool floors (DESIGN.md §11) ----------------------------
    def _watch_pools(self, now: float) -> None:
        """Backfill an EMPTIED disagg role pool.  While a pool is empty
        the router serves degraded-unified (correct but unpriced:
        prefill and decode interfere again), so this reacts like the
        preemption backfill — not gated on cooldown, only on the
        one-action gate and failure backoff.  Roles come from
        ``_roles_seen``: an empty pool leaves no handle to read."""
        counts = self._pool_counts()
        if (len(self._roles_seen) < 2       # never was a disagg fleet
                or self._rollout is not None
                or self._pending_out is not None
                or now < self._backoff_until):
            return
        missing = sorted(r for r in self._roles_seen
                         if counts.get(r, 0) < self.cfg.min_per_role)
        if not missing:
            return
        role = missing[0]
        try:
            h = self._spawn(role=role, generation=self._primary_gen())
        except Exception as exc:
            self._action_failed(now, "pool_backfill", str(exc)[:200])
            return
        self._pending_out = {"name": h.name, "t": now,
                             "deadline": now + self.cfg.ready_timeout_s}
        self._decide("pool_backfill", replica=h.name, role=role,
                     pool_size=counts.get(role, 0))

    # ---- autoscaling ---------------------------------------------------
    def _observe(self):
        router = self.fleet.router
        occs = []
        by_role: Dict[str, List[float]] = {}
        for h in self._active():
            if not h.accepting():
                continue
            sig = h.load()
            occ = sig.occupancy if sig is not None else 0.0
            occs.append(occ)
            by_role.setdefault(role_kind(h), []).append(occ)
        queue = len(router.queue)
        mean_occ = (sum(occs) / len(occs)) if occs else math.inf
        occ_by_role = {k: sum(v) / len(v) for k, v in by_role.items()}
        return mean_occ, queue, occ_by_role

    def _autoscale(self, now: float) -> None:
        cfg = self.cfg
        mean_occ, queue, occ_by_role = self._observe()
        # disagg fleets watch each pool: one hot role is a capacity
        # problem even when the other pool idles the fleet-wide mean
        # below the threshold (a long-prompt wave saturates prefill
        # while decode coasts)
        hot_roles = {k: v for k, v in occ_by_role.items()
                     if k in ("prefill", "decode")
                     and v >= cfg.high_occupancy}
        high = (mean_occ >= cfg.high_occupancy
                or queue >= cfg.high_queue or bool(hot_roles))
        low = mean_occ <= cfg.low_occupancy and queue == 0
        # hysteresis: the signal must HOLD before anything moves
        if high:
            self._low_since = None
            if self._high_since is None:
                self._high_since = now
        elif low:
            self._high_since = None
            if self._low_since is None:
                self._low_since = now
        else:
            self._high_since = self._low_since = None
        if (now < self._cooldown_until or now < self._backoff_until
                or self._pending_out is not None or self._draining):
            return                      # one action in flight at a time
        n = len(self._active())
        if (self._high_since is not None
                and now - self._high_since >= cfg.scale_out_hold_s
                and n < cfg.max_replicas):
            # disagg fleets scale the PRESSURED pool: the role with the
            # highest mean occupancy gets the new replica, so a
            # long-prompt wave widens prefill without over-building the
            # decode pool (and vice versa).  Unified fleets pass None.
            role = None
            disagg = [(v, k) for k, v in
                      (hot_roles or occ_by_role).items()
                      if k in ("prefill", "decode")]
            if disagg:
                role = max(disagg)[1]
            self._scale_out(now, reason={
                "mean_occupancy": round(mean_occ, 3)
                if math.isfinite(mean_occ) else None,
                "queue_depth": queue}, role=role)
        elif (self._low_since is not None
                and now - self._low_since >= cfg.scale_in_hold_s
                and n > cfg.min_replicas):
            self._scale_in(now, reason={
                "mean_occupancy": round(mean_occ, 3)
                if math.isfinite(mean_occ) else None})

    def _scale_out(self, now: float, reason,
                   role: Optional[str] = None) -> None:
        try:
            h = self._spawn(role=role, generation=self._primary_gen())
        except Exception as exc:          # spawn refusal = failed action
            self._action_failed(now, "scale_out", str(exc)[:200])
            return
        self._pending_out = {"name": h.name, "t": now,
                             "deadline": now + self.cfg.ready_timeout_s}
        self._high_since = None
        self._cooldown_until = now + self.cfg.cooldown_s
        if role is not None:
            reason = {**reason, "role": role}
        self._decide("scale_out", replica=h.name, **reason)

    def _watch_pending_out(self, now: float) -> None:
        p = self._pending_out
        if p is None:
            return
        h = next((r for r in self.fleet.router.replicas
                  if r.name == p["name"]), None)
        if h is not None and h.accepting():
            self._pending_out = None
            self._failures = 0
            self._decide("scale_out_ready", replica=p["name"],
                         reaction_s=round(now - p["t"], 3))
            # replace-then-drain: the eviction victim leaves only once
            # its replacement accepts, so capacity never dips
            victim = p.get("then_evict")
            if victim is not None and victim not in self._draining \
                    and any(r.name == victim
                            for r in self.fleet.router.replicas):
                self._begin_decommission(now, victim,
                                         kind="health_evict")
            return
        rc = self.fleet.replica_done(p["name"])
        if rc is not None:
            # the supervisor gave up on (or terminally stopped) the new
            # child before it ever served — undo the registration
            self._pending_out = None
            self.fleet.remove_replica(p["name"])
            self._action_failed(now, "scale_out",
                                f"{p['name']} never ready (rc {rc})")
            return
        if now >= p["deadline"]:
            self._pending_out = None
            try:
                self.fleet.supervisor.retire(p["name"])
            except (KeyError, AttributeError):
                pass
            self.fleet.force_kill(p["name"])
            self.fleet.remove_replica(p["name"])
            self._action_failed(now, "scale_out",
                                f"{p['name']} ready timeout")

    def _scale_in(self, now: float, reason) -> None:
        gen = self._primary_gen()
        victims = [h for h in self._active()
                   if getattr(h, "generation", 0) == gen]
        if len(victims) <= self.cfg.min_replicas:
            return
        # per-role floor: in a disagg fleet, removing a replica must not
        # drop its role pool below min_per_role — an emptied pool means
        # degraded unified serving, which scale-in must never cause.
        pool = {}
        for h in victims:
            pool[role_kind(h)] = pool.get(role_kind(h), 0) + 1
        victims = [h for h in victims
                   if role_kind(h) == "unified"
                   or pool[role_kind(h)] > self.cfg.min_per_role]
        if not victims:
            return
        victim = max(victims, key=lambda h: h.name)  # newest out first
        self._begin_decommission(now, victim.name, kind="scale_in")
        self._low_since = None
        self._cooldown_until = now + self.cfg.cooldown_s
        self._decide("scale_in", replica=victim.name, **reason)

    # ---- decommission (the no-drop removal primitive) ------------------
    def _begin_decommission(self, now: float, name: str,
                            kind: str) -> None:
        sent = self.fleet.decommission(name)
        self._draining[name] = {
            "t": now, "deadline": now + self.cfg.drain_timeout_s,
            "forced": False, "kind": kind, "op_sent": sent,
            "base_requeued": self.fleet.router.requeued}

    def _watch_draining(self, now: float) -> None:
        for name, st in list(self._draining.items()):
            rc = self.fleet.replica_done(name)
            if rc is not None:
                self.fleet.remove_replica(name)
                del self._draining[name]
                self._decide(
                    "drained", replica=name, rc=rc, kind=st["kind"],
                    forced=st["forced"],
                    wall_s=round(now - st["t"], 3),
                    requeued=self.fleet.router.requeued
                    - st["base_requeued"])
                if self._rollout is not None:
                    self._check_promote_done(now)
                continue
            if now >= st["deadline"] and not st["forced"]:
                # stalled drain: the child is already retired, so the
                # kill is terminal — no relaunch, ledger requeues once
                st["forced"] = True
                self.fleet.force_kill(name)
                self._decide("drain_stalled_kill", replica=name,
                             kind=st["kind"],
                             after_s=round(now - st["t"], 3))

    # ---- preemption notices (advance-notice drain + backfill) ----------
    def _watch_notices(self, now: float) -> None:
        """A replica that announced a preemption notice
        (``preempt_notice`` on the wire) stops accepting new work on
        its own — the router's admission closes the moment the pump
        lands the event — and exits 47 when idle or at its grace
        deadline.  The autopilot's job is attribution and backfill:
        record the notice ONCE in the decision ledger, reap the
        self-initiated exit (it never enters ``_draining``), and spawn
        a replacement while the victim is still finishing its
        in-flight work, so capacity is restored before the death."""
        for h in list(self.fleet.router.replicas):
            if not getattr(h, "noticed", False):
                continue
            if h.name not in self._noticed_seen:
                self._noticed_seen.add(h.name)
                # record the role AT NOTICE TIME: the handle may be
                # gone by the time the backfill slot frees up
                self._backfill_due.append((h.name, role_kind(h)))
                g = getattr(h, "notice_grace_s", None)
                self._decide("preempt_notice", replica=h.name,
                             grace_s=(round(float(g), 3)
                                      if g is not None else None))
            if h.name in self._draining:
                continue            # an explicit drain already owns it
            rc = self.fleet.replica_done(h.name)
            if rc is not None:
                self.fleet.remove_replica(h.name)
                self._decide("preempt_drained", replica=h.name, rc=rc,
                             requeued=0 if rc == 47 else None)
        # backfill one replacement per notice.  Deliberately NOT gated
        # on cooldown: the capacity loss is involuntary, reacting to it
        # is not flapping.  The one-action gate and failure backoff
        # still apply, and a rollout owns spawning while active.
        if (not self._backfill_due or self._rollout is not None
                or self._pending_out is not None
                or now < self._backoff_until):
            return
        width = len([h for h in self._active()
                     if not getattr(h, "noticed", False)])
        if width >= self.cfg.max_replicas:
            self._backfill_due.clear()
            return
        victim, vrole = self._backfill_due.pop(0)
        try:
            # the replacement inherits the victim's role, so a preempted
            # prefill replica is backfilled INTO the prefill pool
            h = self._spawn(role=vrole,
                            generation=self._primary_gen())
        except Exception as exc:
            self._backfill_due.insert(0, (victim, vrole))
            self._action_failed(now, "preempt_backfill",
                                str(exc)[:200])
            return
        self._pending_out = {"name": h.name, "t": now,
                             "deadline": now + self.cfg.ready_timeout_s}
        self._decide("preempt_backfill", replica=h.name,
                     replaces=victim)

    # ---- degraded-replica eviction -------------------------------------
    def _health_windowed(self, now: float) -> Dict[str, Any]:
        """Per-replica windowed TTFT medians from the router's
        completion samples (``FleetRouter.recent``) — the same windowed
        signal the canary judge reads, so a degraded replica cannot
        hide behind a healthy lifetime sketch."""
        t_cut = now - self.cfg.health_window_s
        by: Dict[str, List[float]] = {}
        for s in self.fleet.router.recent:
            if s["t"] < t_cut or s["ttft_ms"] is None:
                continue
            by.setdefault(s["replica"], []).append(s["ttft_ms"])
        return {n: (sorted(v)[len(v) // 2], len(v))
                for n, v in by.items()}

    def _health_evict(self, now: float) -> None:
        """Force-drain a slow-but-alive replica: windowed TTFT median
        (or lifetime-rollup ITL p50) ``evict_*_ratio`` x beyond the
        median of its PEERS, held for ``evict_hold_s``.  Shares the
        one-action-in-flight gate, cooldown and backoff with the
        autoscaler, and goes replace-then-drain through
        ``_pending_out["then_evict"]`` so the fleet never dips below
        ``min_replicas`` — even when the victim IS the floor."""
        cfg = self.cfg
        if not cfg.health_eviction:
            return
        if (now < self._cooldown_until or now < self._backoff_until
                or self._pending_out is not None or self._draining):
            return                  # one action in flight at a time
        candidates = [h for h in self._active()
                      if h.accepting()
                      and not getattr(h, "noticed", False)]
        if len(candidates) < 2:
            self._unhealthy_since.clear()
            return                  # no peers to compare against
        names = {h.name for h in candidates}
        windowed = {n: v for n, v
                    in self._health_windowed(now).items()
                    if n in names and v[1] >= cfg.evict_min_samples}
        itl = {r["name"]: r.get("itl_ms_p50")
               for r in self.breakdown() if r["name"] in names}
        worst = None                # (name, verdict-extras)
        for n in sorted(names):
            vs: Dict[str, Any] = {}
            if n in windowed and len(windowed) >= 2:
                peers = sorted(m for k, (m, _) in windowed.items()
                               if k != n)
                base = peers[len(peers) // 2]
                if base > 0 and windowed[n][0] / base \
                        >= cfg.evict_ttft_ratio:
                    vs["ttft_p50_ms"] = round(windowed[n][0], 1)
                    vs["ttft_ratio"] = round(windowed[n][0] / base, 2)
            mine = itl.get(n)
            peers_i = sorted(v for k, v in itl.items()
                             if k != n and v is not None)
            if mine is not None and peers_i:
                base_i = peers_i[len(peers_i) // 2]
                if base_i > 0 and mine / base_i >= cfg.evict_itl_ratio:
                    vs["itl_p50_ms"] = round(mine, 1)
                    vs["itl_ratio"] = round(mine / base_i, 2)
            if vs and (worst is None
                       or vs.get("ttft_ratio", 0)
                       > worst[1].get("ttft_ratio", 0)):
                worst = (n, vs)
        # hysteresis: the verdict must HOLD before anything moves
        for n in list(self._unhealthy_since):
            if worst is None or n != worst[0]:
                del self._unhealthy_since[n]
        if worst is None:
            return
        name, verdict = worst
        since = self._unhealthy_since.setdefault(name, now)
        if now - since < cfg.evict_hold_s:
            return
        del self._unhealthy_since[name]
        # replace-then-drain: spawn the replacement first (same role as
        # the victim, so an evicted prefill replica is replaced in the
        # prefill pool); the victim decommissions in _watch_pending_out
        # once it accepts
        vrole = next((role_kind(h) for h in candidates
                      if h.name == name), None)
        try:
            h = self._spawn(role=vrole,
                            generation=self._primary_gen())
        except Exception as exc:
            self._action_failed(now, "health_evict", str(exc)[:200])
            return
        self._pending_out = {"name": h.name, "t": now,
                             "deadline": now + cfg.ready_timeout_s,
                             "then_evict": name}
        self._cooldown_until = now + cfg.cooldown_s
        self._decide("health_evict", replica=name,
                     replacement=h.name, **verdict)

    # ---- rollout / canary ----------------------------------------------
    def start_rollout(self, snapshot_dir,
                      canary_replicas: Optional[int] = None,
                      canary_fraction: Optional[float] = None,
                      step_sleep_ms: Optional[float] = None) -> bool:
        """Begin a zero-downtime weight rollout from a snapshot dir
        (:func:`save_weight_snapshot` layout).  Verification happens
        HERE, before any process spawns: a bad snapshot returns False
        with the serving generation untouched (decision
        ``rollout_rejected``).  ``step_sleep_ms`` overrides the canary
        workers' emulated device latency (chaos/testing knob: a slow
        canary must roll back on its SLO judgment)."""
        if self._rollout is not None:
            raise RuntimeError("a rollout is already in progress")
        now = self._now()
        from ..utils import ckpt_manifest

        problems = ckpt_manifest.verify(Path(snapshot_dir))
        if problems:
            self._decide("rollout_rejected",
                         snapshot=str(snapshot_dir),
                         problems=problems[:3])
            self._action_failed(now, "rollout", "snapshot unverified")
            return False
        gen = self._primary_gen() + 1
        k = canary_replicas or self.cfg.canary_replicas
        names = []
        try:
            for _ in range(k):
                h = self.fleet.add_replica(
                    generation=gen, ckpt=str(snapshot_dir),
                    step_sleep_ms=step_sleep_ms)
                names.append(h.name)
        except Exception as exc:
            for n in names:
                self.fleet.force_kill(n)
                self.fleet.remove_replica(n)
            self._action_failed(now, "rollout", str(exc)[:200])
            return False
        self._rollout = {
            "phase": "wait_ready", "gen": gen,
            "snapshot": str(snapshot_dir), "canary": names,
            "step_sleep_ms": step_sleep_ms, "t0": now,
            "fraction": (canary_fraction
                         if canary_fraction is not None
                         else self.cfg.canary_fraction),
            "deadline": now + self.cfg.ready_timeout_s,
            "extensions": 0,
        }
        self._decide("canary_spawn", generation=gen,
                     replicas=list(names),  # copy: _promote grows it
                     snapshot=str(snapshot_dir))
        return True

    def _canary_handles(self) -> List[Any]:
        names = set(self._rollout["canary"])
        return [h for h in self.fleet.router.replicas
                if h.name in names]

    def _advance_rollout(self, now: float) -> None:
        ro = self._rollout
        phase = ro["phase"]
        if phase == "promote_drain":
            self._check_promote_done(now)
            return
        # a canary child that terminally died (bad checkpoint -> exit
        # 44; supervisor gave up) fails the rollout in ANY phase
        for name in list(ro["canary"]):
            rc = self.fleet.replica_done(name)
            if rc is not None and name not in self._draining:
                self._rollback(now, f"canary {name} died (rc {rc})")
                return
        if phase == "wait_ready":
            if all(h.accepting() for h in self._canary_handles()) \
                    and self._canary_handles():
                router = self.fleet.router
                router.set_traffic(self._primary_gen(),
                                   canary_generation=ro["gen"],
                                   canary_fraction=ro["fraction"])
                ro["phase"] = "judge"
                ro["window_end"] = now + self.cfg.canary_window_s
                ro["base_completed"] = router.per_replica_completed()
                ro["base_missed"] = router.per_replica_missed()
                self._decide("canary_traffic",
                             fraction=ro["fraction"],
                             generation=ro["gen"])
            elif now >= ro["deadline"]:
                self._rollback(now, "canary never became ready")
            return
        if phase == "judge" and now >= ro["window_end"]:
            self._judge(now)

    def _judge(self, now: float) -> None:
        ro = self._rollout
        cfg = self.cfg
        router = self.fleet.router
        canary = set(ro["canary"])
        comp = router.per_replica_completed()
        miss = router.per_replica_missed()
        done = sum(comp.get(n, 0) - ro["base_completed"].get(n, 0)
                   for n in canary)
        missed = sum(miss.get(n, 0) - ro["base_missed"].get(n, 0)
                     for n in canary)
        if done < cfg.canary_min_completed:
            if ro["extensions"] < cfg.canary_max_extensions:
                ro["extensions"] += 1
                ro["window_end"] = now + cfg.canary_window_s
                self._decide("canary_window_extended",
                             completed=done,
                             extension=ro["extensions"])
                return
            self._rollback(now, f"insufficient canary traffic "
                                f"({done} completed)")
            return
        miss_frac = missed / done
        # latency verdict from the router's WINDOWED completion samples
        # (FleetRouter.recent), not the replicas' lifetime sketches: a
        # fresh canary's first-compile TTFTs would dominate a lifetime
        # p50 forever and roll back every healthy push.  Samples before
        # the traffic shift (minus the judge window, for the stable
        # side's sample size) are out of scope.
        t_cut = now - self.cfg.canary_window_s * (
            1 + ro["extensions"] + 1)
        canary_ts, stable_ts = [], []
        for s in router.recent:
            if s["t"] < t_cut or s["ttft_ms"] is None:
                continue
            if s["generation"] == ro["gen"]:
                canary_ts.append(s["ttft_ms"])
            elif s["generation"] == self._primary_gen():
                stable_ts.append(s["ttft_ms"])
        ratio = None
        if canary_ts and stable_ts:
            c_p50 = sorted(canary_ts)[len(canary_ts) // 2]
            s_p50 = sorted(stable_ts)[len(stable_ts) // 2]
            if s_p50 > 0:
                ratio = c_p50 / s_p50
        verdict = {"completed": done, "missed": missed,
                   "miss_frac": round(miss_frac, 3),
                   "p50_ratio": (round(ratio, 2)
                                 if ratio is not None else None)}
        if miss_frac > cfg.canary_max_miss_frac:
            self._rollback(now, f"canary SLO burn {miss_frac:.0%}",
                           **verdict)
            return
        if ratio is not None and ratio > cfg.canary_max_p50_ratio:
            self._rollback(now, f"canary p50 {ratio:.1f}x stable",
                           **verdict)
            return
        self._promote(now, verdict)

    def _promote(self, now: float, verdict: Dict[str, Any]) -> None:
        ro = self._rollout
        router = self.fleet.router
        old_gen = self._primary_gen()
        old = [h for h in self._active()
               if getattr(h, "generation", 0) == old_gen]
        # grow the new generation to the old serving width, then shift
        # all traffic; old-gen replicas stay accepting until their drain
        # lands (generation preference, not partition — zero downtime
        # while the extras compile)
        grow = max(0, len(old) - len(ro["canary"]))
        try:
            for _ in range(grow):
                h = self.fleet.add_replica(
                    generation=ro["gen"], ckpt=ro["snapshot"],
                    step_sleep_ms=ro["step_sleep_ms"])
                ro["canary"].append(h.name)
        except Exception as exc:
            self._rollback(now, f"promote spawn failed: {exc}")
            return
        router.set_traffic(ro["gen"])
        for h in old:
            self._begin_decommission(now, h.name, kind="rollout_old")
        ro["phase"] = "promote_drain"
        ro["old"] = [h.name for h in old]
        self._decide("canary_promote", generation=ro["gen"],
                     draining=[h.name for h in old], **verdict)

    def _check_promote_done(self, now: float) -> None:
        ro = self._rollout
        if ro is None or ro["phase"] != "promote_drain":
            return
        if any(n in self._draining for n in ro["old"]):
            return
        self._failures = 0
        self._decide("rollout_complete", generation=ro["gen"],
                     wall_s=round(now - ro["t0"], 3))
        self._rollout = None

    def _rollback(self, now: float, reason: str, **extra) -> None:
        ro = self._rollout
        router = self.fleet.router
        # restore traffic FIRST: the old generation takes everything
        # again before the canaries disappear
        router.set_traffic(self._primary_gen())
        for name in ro["canary"]:
            if name in self._draining:
                continue
            if self.fleet.replica_done(name) is not None:
                self.fleet.remove_replica(name)
            else:
                self._begin_decommission(now, name, kind="rollback")
        self._decide("canary_rollback", generation=ro["gen"],
                     reason=reason, **extra)
        self._rollout = None
        self._action_failed(now, "rollout", reason)
