"""Write-ahead log for the serving control plane.

Every worker failure in the fleet is recoverable (requeue ledger,
handoff ledger, notice drain) — but through PR 19 the commit point for
all of it was the ROUTER'S MEMORY: a SIGKILL of the operator process
silently lost every accepted request and every committed handoff
record.  This module makes the router's ledger durable:

* **Append-only, fsynced, per-record checksummed.**  A record is one
  line ``<sha16> <canonical-json>\\n`` where ``sha16`` is the first 16
  hex chars of sha256 over the json body (``sort_keys``, tight
  separators).  ``append`` returns only after write+flush+fsync, so a
  record the caller saw acknowledged survives the very next SIGKILL.
* **Torn tails truncated, never fatal.**  A crash mid-append leaves a
  partial last line (no newline, bad json, or bad checksum with
  nothing valid after it).  ``replay`` truncates the active file at
  the last valid record — exactly the ckpt-manifest stance that an
  uncommitted write does not exist.
* **Checksum-corrupt records quarantined.**  A mid-file record that
  fails its checksum (bit rot, not a torn write — valid records follow
  it) is moved to ``quarantined-records.jsonl`` with its provenance
  and COUNTED; replay continues.  A lost record degrades to
  re-execution of that request (greedy decode is deterministic), never
  to wrong bytes or a duplicate delivery.
* **Segment rotation via the checkpoint manifest discipline.**  Every
  ``segment_records`` appends, the active file is sealed into
  ``walseg-<k>/records.jsonl`` and committed with
  :func:`utils.ckpt_manifest.commit` — payload fsynced first, manifest
  written last — so a sealed segment is verifiable (``verify``) and a
  corrupt one is quarantined (``corrupt-walseg-<k>``) with its intact
  records salvaged.

The module is deliberately stdlib-only and free of package-relative
hard dependencies: ``utils/chaos.py``'s ``stub_router_kill`` arm
file-path-loads it so the no-jax CI lane kills and replays the REAL
WAL code, not a model of it.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

try:
    from ..utils import ckpt_manifest as _manifest
except ImportError:      # file-path loaded (chaos stub, offline triage)
    import importlib.util as _ilu
    import sys as _sys

    _p = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "utils", "ckpt_manifest.py")
    _spec = _ilu.spec_from_file_location("_wal_ckpt_manifest", _p)
    _manifest = _ilu.module_from_spec(_spec)
    _sys.modules["_wal_ckpt_manifest"] = _manifest
    _spec.loader.exec_module(_manifest)

SEG_PREFIX = "walseg-"
ACTIVE = "wal-active.jsonl"
QUARANTINE_FILE = "quarantined-records.jsonl"


def _body(rec: Dict[str, Any]) -> str:
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


def _sha16(body: str) -> str:
    return hashlib.sha256(body.encode()).hexdigest()[:16]


def encode_record(rec: Dict[str, Any]) -> str:
    body = _body(rec)
    return f"{_sha16(body)} {body}\n"


def decode_line(line: str) -> Optional[Dict[str, Any]]:
    """The record, or None when the line is torn/corrupt (wrong
    checksum, unparsable json, missing separator)."""
    if not line.endswith("\n"):
        return None                      # torn: the newline IS the seal
    try:
        sha, body = line[:-1].split(" ", 1)
    except ValueError:
        return None
    if _sha16(body) != sha:
        return None
    try:
        rec = json.loads(body)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


def _scan_lines(data: str) -> Tuple[List[Tuple[int, str]], int]:
    """[(byte_offset, line)] including a torn final fragment, plus the
    total byte length scanned."""
    out: List[Tuple[int, str]] = []
    off = 0
    while off < len(data):
        nl = data.find("\n", off)
        if nl < 0:
            out.append((off, data[off:]))
            off = len(data)
        else:
            out.append((off, data[off:nl + 1]))
            off = nl + 1
    return out, off


def _segments(root: str) -> List[Tuple[int, str]]:
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        p = os.path.join(root, name)
        if os.path.isdir(p) and name.startswith(SEG_PREFIX):
            try:
                out.append((int(name[len(SEG_PREFIX):]), p))
            except ValueError:
                continue
    return sorted(out)


def replay(root: str, *, repair: bool = False
           ) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Replay every surviving record in commit order: sealed segments
    (manifest-verified; a failed segment is quarantined and its intact
    lines salvaged from the quarantine location) then the active file
    (mid-file corrupt lines quarantined, torn tail truncated).

    ``repair=False`` is a read-only scan — safe against a LIVE wal
    (the bench's kill trigger polls progress this way); ``repair=True``
    additionally truncates the torn tail and moves corrupt records to
    ``quarantined-records.jsonl`` (what :meth:`WriteAheadLog.open`
    does before reopening for append)."""
    records: List[Dict[str, Any]] = []
    report: Dict[str, Any] = {
        "segments": 0, "quarantined_segments": 0,
        "records": 0, "quarantined_records": 0,
        "torn_tail_bytes": 0, "torn_tail_truncated": False,
    }
    if not os.path.isdir(root):
        return records, report
    quarantined_lines: List[Dict[str, Any]] = []

    def _parse_file(path: str, origin: str, tail_is_torn: bool) -> int:
        """Parse one record file; returns the byte offset of the end of
        the last VALID prefix (for tail truncation)."""
        try:
            with open(path, "r") as f:
                data = f.read()
        except OSError:
            return 0
        lines, _ = _scan_lines(data)
        valid_end = 0
        bad: List[Tuple[int, str]] = []
        for off, line in lines:
            rec = decode_line(line)
            if rec is None:
                bad.append((off, line))
                continue
            # a bad line FOLLOWED by a valid one is corruption, not a
            # torn tail: quarantine the bad line, keep going
            for boff, bline in bad:
                quarantined_lines.append(
                    {"origin": origin, "offset": boff,
                     "line": bline.rstrip("\n")})
                report["quarantined_records"] += 1
            bad = []
            records.append(rec)
            report["records"] += 1
            valid_end = off + len(line)
        if bad:
            if tail_is_torn:
                report["torn_tail_bytes"] += sum(
                    len(line) for _, line in bad)
            else:
                for boff, bline in bad:
                    quarantined_lines.append(
                        {"origin": origin, "offset": boff,
                         "line": bline.rstrip("\n")})
                    report["quarantined_records"] += 1
        return valid_end

    for idx, seg in _segments(root):
        report["segments"] += 1
        rec_path = os.path.join(seg, "records.jsonl")
        problems = _manifest.verify(seg)
        if problems:
            report["quarantined_segments"] += 1
            if repair:
                seg = str(_manifest.quarantine(seg))
                rec_path = os.path.join(seg, "records.jsonl")
            # salvage: intact lines inside a failed segment still
            # replay; the broken ones are quarantined per record
            _parse_file(rec_path, f"{SEG_PREFIX}{idx}",
                        tail_is_torn=False)
        else:
            _parse_file(rec_path, f"{SEG_PREFIX}{idx}",
                        tail_is_torn=False)

    active = os.path.join(root, ACTIVE)
    if os.path.exists(active):
        valid_end = _parse_file(active, ACTIVE, tail_is_torn=True)
        if report["torn_tail_bytes"] and repair:
            with open(active, "r+") as f:
                f.truncate(valid_end)
                f.flush()
                os.fsync(f.fileno())
            report["torn_tail_truncated"] = True

    if quarantined_lines and repair:
        qpath = os.path.join(root, QUARANTINE_FILE)
        with open(qpath, "a") as f:
            for row in quarantined_lines:
                f.write(json.dumps(row, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
    return records, report


class WriteAheadLog:
    """Append/rotate/replay for one wal directory.

    ``open()`` replays (with repair), remembers the report, and reopens
    the active file for append; ``append(kind, **fields)`` stamps a
    monotonically increasing ``seq``, checksums, writes, fsyncs;
    ``rotate()`` seals the active file into a manifest-committed
    segment.  ``fsync=False`` exists only so tests can model a torn
    write; production callers keep the default."""

    def __init__(self, root: str, *, segment_records: int = 4096,
                 fsync: bool = True):
        self.root = str(root)
        self.segment_records = int(segment_records)
        self.fsync = bool(fsync)
        self._f = None
        self._seq = 0
        self._n_active = 0
        self.report: Dict[str, Any] = {}
        os.makedirs(self.root, exist_ok=True)

    # -- lifecycle ----------------------------------------------------
    def open(self) -> List[Dict[str, Any]]:
        records, self.report = replay(self.root, repair=True)
        self._seq = 1 + max((int(r.get("seq", -1)) for r in records),
                            default=-1)
        active = os.path.join(self.root, ACTIVE)
        self._n_active = 0
        if os.path.exists(active):
            with open(active, "r") as f:
                self._n_active = sum(1 for _ in f)
        self._f = open(active, "a")
        return records

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            self._f.close()
            self._f = None

    # -- append path --------------------------------------------------
    def append(self, kind: str, **fields: Any) -> Dict[str, Any]:
        assert self._f is not None, "append before open()"
        rec = {"seq": self._seq, "kind": str(kind), **fields}
        self._f.write(encode_record(rec))
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._seq += 1
        self._n_active += 1
        if self._n_active >= self.segment_records:
            self.rotate()
        return rec

    def rotate(self) -> Optional[str]:
        """Seal the active file into the next ``walseg-<k>`` and commit
        it (payload fsynced, manifest last).  No-op when empty."""
        if self._n_active == 0:
            return None
        assert self._f is not None
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        idx = 1 + max((i for i, _ in _segments(self.root)), default=-1)
        seg = os.path.join(self.root, f"{SEG_PREFIX}{idx}")
        os.makedirs(seg, exist_ok=True)
        os.replace(os.path.join(self.root, ACTIVE),
                   os.path.join(seg, "records.jsonl"))
        _manifest.commit(seg, meta={"kind": "walseg",
                                    "records": self._n_active,
                                    "seq_hi": self._seq - 1})
        _manifest.fsync_path(self.root)
        self._f = open(os.path.join(self.root, ACTIVE), "a")
        self._n_active = 0
        return seg
