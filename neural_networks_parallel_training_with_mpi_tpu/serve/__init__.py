"""Production serving subsystem: paged KV cache + continuous batching.

``models/serve.py`` is the library-shaped slot server: every slot
reserves a dense ``max_len`` KV allocation and admission is "return None
when full".  This package is the service-shaped runtime above it:

* :mod:`serve.paged_kv` — a block-allocated KV pool with per-stream
  block tables and static-shape gathered attention, so heterogeneous
  stream lengths share device memory instead of each padding to max.
* :mod:`serve.scheduler` — a continuous-batching scheduler: bounded
  wait queue, per-tick admit/retire, chunked prefill interleaved with
  decode, admission control gated on free blocks + token budget, and
  SLO-aware eviction/requeue under block exhaustion.  Serving metrics
  ride the PR 2 telemetry records + heartbeat, so the PR 1 supervisor
  can babysit a serving fleet unchanged.
* :mod:`serve.loadgen` — a closed-loop load generator measuring
  tokens/s and TTFT/ITL percentiles vs. offered load
  (``bench.py --serve`` -> BENCH_SERVE.json).
"""

from .paged_kv import (
    BlockAllocator,
    BlockExhausted,
    PagedDecodeServer,
    init_paged_kv,
)
from .scheduler import Request, Scheduler, ServeConfig
from .loadgen import run_closed_loop, sweep_loads

__all__ = [
    "BlockAllocator", "BlockExhausted", "PagedDecodeServer",
    "init_paged_kv", "Request", "Scheduler", "ServeConfig",
    "run_closed_loop", "sweep_loads",
]
