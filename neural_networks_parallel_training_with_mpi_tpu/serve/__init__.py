"""Production serving subsystem: paged KV cache + continuous batching.

``models/serve.py`` is the library-shaped slot server: every slot
reserves a dense ``max_len`` KV allocation and admission is "return None
when full".  This package is the service-shaped runtime above it:

* :mod:`serve.paged_kv` — a block-allocated KV pool with per-stream
  block tables, so heterogeneous stream lengths share device memory
  instead of each padding to max.  Attention is dispatched behind the
  ``attn_impl`` seam: ``'gathered'`` (static-shape ``pool[table]``
  materialization, the parity reference) or ``'fused'`` (the Pallas
  paged-attention kernel, ``ops.pallas_kernels.paged_attention``, which
  reads K/V straight from the pool and stops at each stream's true
  length — the FLOPs win on top of the memory win).  With
  ``prefix_cache=True`` identical prompt prefixes share blocks across
  streams (refcounts + a host-side prefix index + copy-on-write forks),
  so a cached prefix admits without re-prefilling — near-zero TTFT for
  shared system prompts.
* :mod:`serve.scheduler` — a continuous-batching scheduler: bounded
  wait queue, per-tick admit/retire, chunked prefill interleaved with
  decode, admission control gated on free blocks + token budget, and
  SLO-aware eviction/requeue under block exhaustion.  Serving metrics
  ride the PR 2 telemetry records + heartbeat, so the PR 1 supervisor
  can babysit a serving fleet unchanged.
* :mod:`serve.loadgen` — a closed-loop load generator measuring
  tokens/s and TTFT/ITL percentiles vs. offered load
  (``bench.py --serve`` -> BENCH_SERVE.json).
"""

from .paged_kv import (
    BlockAllocator,
    BlockExhausted,
    PagedDecodeServer,
    PrefixIndex,
    init_paged_kv,
)
from .paged_kv import ATTN_IMPLS
from .scheduler import Request, Scheduler, ServeConfig
from .loadgen import (
    MIXES,
    make_requests,
    prewarm,
    resolve_mix,
    run_closed_loop,
    run_fleet_closed_loop,
    sweep_loads,
)
from .fleet import (
    Fleet,
    FleetRequest,
    FleetRouter,
    InprocReplica,
    LoadSignal,
    ProcReplica,
    TPGenerateReplica,
    launch_fleet,
    role_kind,
)
from .autopilot import (
    Autopilot,
    AutopilotConfig,
    load_weight_snapshot,
    save_weight_snapshot,
)

__all__ = [
    "ATTN_IMPLS", "BlockAllocator", "BlockExhausted", "PagedDecodeServer",
    "PrefixIndex", "init_paged_kv", "Request", "Scheduler", "ServeConfig",
    "MIXES", "make_requests", "prewarm", "resolve_mix",
    "run_closed_loop", "sweep_loads",
    "Fleet", "FleetRequest", "FleetRouter", "InprocReplica", "LoadSignal",
    "ProcReplica", "TPGenerateReplica", "launch_fleet", "role_kind",
    "run_fleet_closed_loop",
    "Autopilot", "AutopilotConfig", "load_weight_snapshot",
    "save_weight_snapshot",
]
