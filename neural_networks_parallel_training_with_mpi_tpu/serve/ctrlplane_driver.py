"""Killable control-plane driver: the process the crash benches SIGKILL.

The router lives in the operator's process, so "kill the control plane"
cannot be modelled in-process — the experimenter would die with its
subject.  This module is the subject: it launches a fleet (router +
workers, WAL-backed via ``router_kwargs["wal_dir"]``), runs the
closed-loop load, and writes one JSON result row atomically (tmp +
``os.replace``) to ``--out``.  The parent (``bench.py --ctrlplane`` or
the chaos ``fleet_ctrlplane`` scenario) spawns it with
``start_new_session=True`` and then:

* **router_kill** — ``os.kill(driver_pid, SIGKILL)``.  Workers inherit
  the driver's process group and survive as orphans; their stdin hits
  EOF without an ``exit`` op, which arms the advance-notice drain with
  zero grace so each orphan quiesces its allocator and exits 47
  (EXIT_DECOMMISSION), leaking nothing.
* **fleet_kill** — ``os.killpg(driver_pgid, SIGKILL)``.  Everything
  dies mid-flight; durability rests entirely on the fsynced WAL.

Relaunching the driver with the SAME ``--wal-dir`` is recovery: the
router replays the journal (completed requests dedupe by idempotency
key, committed handoffs re-inject, the rest re-queue) and this module
wraps the resumed launch in a ``recovery`` trace span so the goodput
ledger prices the outage window as ``recovery``, not generic idle.

Progress is observable from outside without IPC: the parent polls the
WAL read-only (``wal.replay(root, repair=False)``) and counts
``complete`` records to decide when to pull the trigger.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Optional

from . import wal as wal_mod
from .fleet import launch_fleet
from .loadgen import run_fleet_closed_loop
from ..train import trace


def _write_atomic(path: str, doc: Dict[str, Any]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, indent=2)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="WAL-backed fleet under closed-loop load; one JSON "
                    "row to --out (the process the crash benches kill)")
    ap.add_argument("--wal-dir", default="",
                    help="WAL root ('' disables the WAL: baseline arm)")
    ap.add_argument("--out", required=True)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--roles", default="",
                    help="comma list, one per replica (e.g. "
                         "'prefill,decode'); overrides --replicas")
    ap.add_argument("--clients", type=int, default=6)
    ap.add_argument("--rpc", type=int, default=4)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--mix", default="")
    ap.add_argument("--step-sleep-ms", type=float, default=15.0)
    ap.add_argument("--queue-depth", type=int, default=128)
    ap.add_argument("--handoff-timeout-s", type=float, default=60.0)
    ap.add_argument("--max-restarts", type=int, default=2)
    ap.add_argument("--max-wall-s", type=float, default=600.0)
    ap.add_argument("--trace-dir", default="")
    args = ap.parse_args(argv)

    roles = ([r.strip() or None for r in args.roles.split(",")]
             if args.roles else None)
    n = len(roles) if roles else int(args.replicas)
    # the bench-wide tiny-model shape (matches bench_serve_disagg):
    # identity across arms comes from greedy decode + init_seed, not
    # from model size
    model = dict(vocab=256, seq=128, layers=2, d_model=64, heads=4,
                 d_ff=128, init_seed=0)
    serve_cfg = dict(slots=4, block_size=16, prefill_chunk=32,
                     queue_depth=16)
    wal_dir = args.wal_dir or None

    resuming = False
    if wal_dir:
        prior, _ = wal_mod.replay(wal_dir, repair=False)
        resuming = bool(prior)

    tracer = None
    if args.trace_dir:
        tracer = trace.start_run(args.trace_dir, ledger=False)

    t0 = time.perf_counter()

    def _launch():
        fl = launch_fleet(
            n, model=model, serve=serve_cfg,
            step_sleep_ms=float(args.step_sleep_ms),
            router_kwargs=dict(queue_depth=int(args.queue_depth),
                               handoff_timeout_s=float(
                                   args.handoff_timeout_s),
                               wal_dir=wal_dir),
            prewarm=True, max_restarts=int(args.max_restarts),
            roles=roles, log=lambda msg: None)
        fl.wait_ready(600)
        return fl

    # the recovery window: from relaunch to fleet-serving-again.  Only
    # a RESUMED launch is recovery — a cold start is ordinary compile.
    if resuming:
        with trace.span("recovery"):
            fleet = _launch()
    else:
        fleet = _launch()
    ready_wall_s = round(time.perf_counter() - t0, 6)

    rc = 0
    try:
        row = run_fleet_closed_loop(
            fleet, int(args.clients), int(args.rpc),
            vocab_size=model["vocab"], prompt_lens=(4, 24),
            max_new=(8, 24), seed=int(args.seed),
            classes=[{"name": "all", "slo_ms": None}],
            mix=(args.mix or None), max_wall_s=float(args.max_wall_s))
        router = fleet.router
        doc = {
            "row": row,
            "resumed": resuming,
            "ready_wall_s": ready_wall_s,
            "recovery": dict(router.recovery),
            "handoff_stats": router.handoff_stats(),
            "completed": int(router.completed),
            "wal": (dict(router._wal.report)
                    if router._wal is not None else None),
        }
        _write_atomic(args.out, doc)
    finally:
        fleet.close()
        if tracer is not None:
            trace.stop_run()
    return rc


if __name__ == "__main__":
    sys.exit(main())
