"""Continuous-batching scheduler: the service loop over the paged server.

``PagedDecodeServer`` is mechanism (slots, blocks, one compiled step);
this module is policy — the part a production decode service needs on
top of the library loop the repo had before this subsystem:

* **Bounded wait queue**: ``submit()`` enqueues (FIFO) up to
  ``queue_depth``; beyond that requests are REJECTED (counted, and the
  caller told), because an unbounded queue just converts overload into
  unbounded latency.
* **Per-tick admit/retire**: every :meth:`Scheduler.tick` retires
  finished streams, admits from the queue head while a slot + the
  prompt's blocks + the token budget allow, runs at most one chunked
  prefill chunk, and advances all decoding streams one batched step —
  requests join and leave mid-flight, never stalling the batch.
  Admission is head-of-line (no skip-ahead): simple, and what makes the
  no-starvation property provable — the queue head cannot be bypassed
  forever by luckier requests.
* **Chunked prefill interleaved with decode**: a long prompt is written
  ``prefill_chunk`` positions per tick, so admission of a 10k-token
  prompt costs in-flight streams bounded added latency per tick instead
  of one giant stall (the continuous-batching contract).
* **SLO-aware eviction**: every request carries a deadline
  (``t_submit + slo_ms``; no SLO = +inf).  When the pool cannot supply a
  stream's next block, the LATEST-deadline stream is evicted — its
  blocks freed, the request requeued at the FRONT of the queue (original
  arrival time and deadline kept).  The earliest-deadline stream is
  never evicted while others exist, so the oldest obligation always
  makes progress: under any closed arrival sequence the system drains
  (the fuzz test's no-starvation/no-leak invariant).
* **Serving telemetry**: ``kind="serve"`` tick records (queue/pool
  state plus attended/padded/kernel key counters — the decode work the
  fused paged-attention kernel skips, measurable per tick) and
  ``kind="serve_req"`` per-request completion records (TTFT/ITL) go into
  the same ``metrics.jsonl`` stream PR 2's trainer writes, and the
  heartbeat is the same atomic snapshot under the role-qualified name
  ``heartbeat-serve-p<P>.json`` (two programs sharing one dir no longer
  collide) — ``train.resilience.supervise(heartbeat_path=...)`` and
  ``tools/metrics_summary.py`` work on a serving process unchanged
  through the back-compat fallback read.
* **Fleet plane** (DESIGN.md §7): ``rollup_every`` snapshots the
  streaming quantile sketches (TTFT/ITL/total, queue depth, block
  utilization, tokens/s — ``utils/sketches.py``) as ``kind="rollup"``
  records ``tools/obs_agg.py`` merges across replicas into fleet
  percentiles; deadline misses burn an SLO error budget whose
  burn-rate alerts land as ``kind="alert"`` records (observe-and-
  annotate); with a tracer installed, each request id threads an
  admit -> prefill -> decode -> retire Perfetto FLOW across the tick
  spans (``train/trace.py``) — the primitive a cross-replica block
  handoff will ride.
"""

from __future__ import annotations

import collections
import json
import math
import os
import time
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional

from ..models.transformer import Transformer
from ..train import telemetry as telemetry_lib
from ..train import trace as trace_lib
from ..train.telemetry import Heartbeat
from ..utils import goodput as goodput_lib
from ..utils.logging import log
from ..utils.sketches import ErrorBudget, Gauge, QuantileSketch
from .paged_kv import PagedDecodeServer

Pytree = Any


@dataclass
class ServeConfig:
    """Geometry + policy knobs of the serving runtime."""
    slots: int = 8                 # concurrent streams in the batched step
    num_blocks: int = 128          # KV pool blocks (block 0 is the sink)
    block_size: int = 16           # cache positions per block
    max_len: Optional[int] = None  # per-stream cap (default model max)
    queue_depth: int = 64          # bounded wait queue; beyond = rejected
    prefill_chunk: int = 32        # prompt positions prefilled per tick
    token_budget: int = 0          # max committed (prompt+max_new) tokens
    #                                in flight; 0 disables the gate
    default_slo_ms: Optional[float] = None  # deadline for SLO-less submits
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    kv_quant: bool = False
    attn_impl: str = "gathered"    # 'gathered' (parity reference) or
    #                                'fused' (Pallas paged-attention
    #                                kernel: walks only allocated blocks,
    #                                stops at each stream's true length)
    prefix_cache: bool = False     # share identical prompt-prefix blocks
    #                                across streams (refcounts + copy-on-
    #                                write; serve/paged_kv.py): a cached
    #                                prefix admits without re-prefilling,
    #                                so TTFT collapses to the suffix
    telemetry_dir: Optional[str] = None
    metrics_every: int = 25        # ticks between kind="serve" records
    # fleet-plane rollups (utils/sketches.py): every N ticks emit a
    # kind="rollup" record carrying SERIALIZED quantile-sketch state
    # (TTFT/ITL/total, queue depth, block utilization, tokens/s) +
    # cumulative counters, stamped with the (process, run, incarnation)
    # identity so tools/obs_agg.py can merge fleet percentiles without
    # raw samples.  0 = off (a final rollup still writes on close when
    # any cadence was configured)
    rollup_every: int = 0
    # SLO burn-rate alerting over deadline misses (kind="alert"
    # records; observe-and-annotate — the scheduler never acts on
    # them).  Only requests WITH a deadline count toward the budget.
    alerts: bool = True
    slo_target: float = 0.99       # SLO: fraction of deadlines met
    slo_burn_threshold: float = 2.0  # alert at >= this x budget burn
    # goodput accounting (utils/goodput.py): meter the tick-phase spans
    # plus the inter-tick queue_wait/sched_bubble gap spans into
    # kind="goodput" records on the rollup cadence (file stream only —
    # needs telemetry_dir); the fleet dashboard shows the serve role's
    # goodput fraction next to train's
    goodput: bool = True
    goodput_target: float = 0.5    # fraction floor for the burn alert
    # span tracing + compile ledger (train/trace.py): per-tick
    # admit/prefill/decode/retire spans and the serve programs' compile
    # events under this dir; None = ride any tracer the enclosing
    # process already installed (or off)
    trace_dir: Optional[str] = None
    completed_history: int = 1024  # completed Requests kept for stats();
    #                                older ones (and their unconsumed
    #                                results) are pruned so a long-lived
    #                                serving process cannot grow without
    #                                bound
    replica: Optional[int] = None  # fleet replica index (serve/fleet.py):
    #                                stamps rollup records and qualifies
    #                                per-request flow-trace ids so two
    #                                replicas of one process identity can
    #                                never collide on a merged timeline
    #                                (the scheduler-local rid restarts at
    #                                0 in every replica)
    # disaggregated serving role (DESIGN.md §11): 'unified' (default —
    # this scheduler prefills AND decodes, every pre-existing path),
    # 'prefill' (chunked prefill only: a completed prefill EXPORTS the
    # stream — block contents + first sampled token — for handoff to a
    # decode replica instead of decoding it here; take_handoffs()
    # drains the exports), or 'decode' (accepts handoffs via inject()).
    # Either role still serves plain submits end-to-end when asked
    # (``unified=True`` on submit) — the degraded fallback an empty
    # peer pool routes through.  Telemetry roles become
    # 'serve-prefill'/'serve-decode' so a hot prefill pool is visible
    # per-role in tools/obs_agg.py, never averaged into decode numbers.
    role: str = "unified"


@dataclass
class Request:
    """One request's lifecycle; the scheduler keeps it (with timings)
    after completion so load generators can read TTFT/ITL off it."""
    rid: int
    prompt: List[int]
    max_new: int
    t_submit: float
    deadline: float                       # t_submit + slo_ms, or +inf
    slo_ms: Optional[float] = None
    t_first: Optional[float] = None       # first output token sampled
    t_done: Optional[float] = None
    evictions: int = 0
    unified: bool = False                 # serve end-to-end regardless of
    #                                       the scheduler's role (degraded
    #                                       single-pool fallback)

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.t_first is None:
            return None
        return (self.t_first - self.t_submit) * 1e3

    @property
    def itl_ms(self) -> Optional[float]:
        """Mean inter-token latency over the decode phase."""
        if self.t_done is None or self.t_first is None:
            return None
        return ((self.t_done - self.t_first)
                / max(1, self.max_new - 1)) * 1e3

    @property
    def deadline_missed(self) -> Optional[bool]:
        if self.t_done is None:
            return None
        return bool(math.isfinite(self.deadline)
                    and self.t_done > self.deadline)


class _ServeTelemetry:
    """Serving metrics through the PR 2 channel: kind="serve" /
    "serve_req" records into metrics.jsonl + the role-qualified
    heartbeat, plus the fleet plane's kind="rollup" sketch snapshots
    and kind="alert" SLO burn-rate records (utils/sketches.py).

    The in-memory sketch/counter/gauge state is ALWAYS maintained (host
    arithmetic, bounded O(1/eps) memory): the fleet router's placement
    signal is :meth:`rollup_record` — the same serialized-sketch record
    the file stream carries — and a replica must be routable whether or
    not an operator pointed a ``telemetry_dir`` at it.  File/heartbeat
    IO stays gated on ``telemetry_dir``."""

    # the quantile-sketched serving series: latency percentiles are THE
    # serving SLO numbers and only compose fleet-wide through sketches
    SKETCH_KEYS = ("ttft_ms", "itl_ms", "total_ms", "queue_depth",
                   "block_utilization", "tokens_per_sec")

    def __init__(self, cfg: "ServeConfig"):
        dirpath = cfg.telemetry_dir
        self.enabled = bool(dirpath)
        self.metrics_every = max(1, int(cfg.metrics_every))
        self.rollup_every = max(0, int(cfg.rollup_every))
        self.replica = cfg.replica
        # role-qualified telemetry identity: unified keeps the historic
        # "serve" role; disaggregated roles split into serve-prefill /
        # serve-decode so per-role fleet rollups fall out of the
        # aggregator's existing role grouping
        role = getattr(cfg, "role", "unified") or "unified"
        self.role = "serve" if role == "unified" else f"serve-{role}"
        self._jsonl = None
        self.heartbeat = Heartbeat(None)
        self.alerts_fired = 0
        self.rollups_written = 0
        self._t0 = time.perf_counter()
        self._last_tokens = 0
        self._last_t = self._t0
        self._sketches = {k: QuantileSketch() for k in self.SKETCH_KEYS}
        self._gauges = {k: Gauge() for k in ("tokens_per_sec",
                                             "queue_depth",
                                             "block_utilization")}
        self._counters: Dict[str, int] = {}
        self._budget = (ErrorBudget("slo", target=cfg.slo_target,
                                    burn_threshold=cfg.slo_burn_threshold)
                        if cfg.alerts else None)
        # goodput accounting: the span-listener meter hears the tick
        # phases + the inter-tick queue_wait/sched_bubble gap spans and
        # is snapshotted as kind="goodput" next to each rollup.  File
        # stream only, so it stays gated on telemetry_dir like the rest
        # of the IO (the router's placement signal doesn't need it).
        self.goodput_meter: Optional[goodput_lib.GoodputMeter] = None
        self._goodput_budget: Optional[ErrorBudget] = None
        self._goodput_frac_min = float(getattr(cfg, "goodput_target", 0.5))
        if self.enabled and bool(getattr(cfg, "goodput", True)):
            self.goodput_meter = goodput_lib.GoodputMeter()
            trace_lib.add_listener(self.goodput_meter.on_span)
            if cfg.alerts:
                self._goodput_budget = ErrorBudget(
                    "goodput", target=0.9,
                    window=50, min_events=5, cooldown=10)
        if not self.enabled:
            return
        os.makedirs(dirpath, exist_ok=True)
        self.metrics_path = os.path.join(dirpath, "metrics.jsonl")
        self._jsonl = open(self.metrics_path, "a")
        self.heartbeat = Heartbeat(os.path.join(
            dirpath, telemetry_lib.heartbeat_filename(self.role)))

    def _write(self, rec: Dict[str, Any]) -> None:
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()

    def on_tick(self, tick: int, snap: Dict[str, Any]) -> None:
        # per-tick sketch feed (host floats, no device traffic): queue
        # and pool state distributions, not just their sampled points
        self._sketches["queue_depth"].add(snap["queue_depth"])
        self._sketches["block_utilization"].add(
            snap["block_utilization"])
        if tick % self.metrics_every:
            # the heartbeat still refreshes (throttled internally): the
            # supervisor's staleness monitor watches mtime, not records
            self.heartbeat.beat(tick, None)
            self._maybe_rollup(tick)
            return
        now = time.perf_counter()
        rec = {"kind": "serve", "step": int(tick),
               "t": round(now - self._t0, 6), **snap}
        dt = now - self._last_t
        if dt > 0:
            tps = round((snap["tokens_out"] - self._last_tokens) / dt, 2)
            rec["tokens_per_sec"] = tps
            self._sketches["tokens_per_sec"].add(tps)
            self._gauges["tokens_per_sec"].set(tps)
        self._gauges["queue_depth"].set(snap["queue_depth"])
        self._gauges["block_utilization"].set(snap["block_utilization"])
        for key in ("admitted", "rejected", "evicted", "completed",
                    "tokens_out", "handed_off", "injected"):
            if key in snap:
                self._counters[key] = int(snap[key])
        self._last_tokens = snap["tokens_out"]
        self._last_t = now
        self._write(rec)
        self.heartbeat.beat(tick, rec)
        self._maybe_rollup(tick)

    def on_request_done(self, req: Request, n_generated: int) -> None:
        total_ms = round((req.t_done - req.t_submit) * 1e3, 3)
        ttft, itl = round(req.ttft_ms, 3), round(req.itl_ms, 3)
        self._write({
            "kind": "serve_req", "rid": req.rid,
            "t": round(time.perf_counter() - self._t0, 6),
            "prompt_tokens": len(req.prompt),
            "new_tokens": int(n_generated),
            "ttft_ms": ttft,
            "itl_ms": itl,
            "total_ms": total_ms,
            "evictions": req.evictions,
            "deadline_missed": req.deadline_missed,
        })
        self._sketches["ttft_ms"].add(ttft)
        self._sketches["itl_ms"].add(itl)
        self._sketches["total_ms"].add(total_ms)
        self._counters["requests"] = self._counters.get("requests", 0) + 1
        if math.isfinite(req.deadline):
            # only SLO-carrying requests burn (or bank) the budget
            missed = bool(req.deadline_missed)
            self._counters["deadline_total"] = (
                self._counters.get("deadline_total", 0) + 1)
            if missed:
                self._counters["deadline_missed"] = (
                    self._counters.get("deadline_missed", 0) + 1)
            if self._budget is not None:
                alert = self._budget.observe(missed)
                if alert and self.enabled:
                    self._emit_alert(alert, rid=req.rid)

    def on_handoff(self, ttft_ms: float) -> None:
        """A prefill-role handoff: the prefill side OWNS the TTFT number
        (the first token was sampled here), so it lands in this
        replica's sketch — the decode side records only decode-phase
        ITL for injected streams."""
        self._sketches["ttft_ms"].add(round(ttft_ms, 3))

    def _emit_alert(self, alert: Dict[str, Any], **extra) -> None:
        self.alerts_fired += 1
        rec = {"kind": "alert", "role": self.role,
               "t": round(time.perf_counter() - self._t0, 6),
               "t_unix": round(time.time(), 3), **alert, **extra}
        self._write(rec)
        log(f"[serve] ALERT {alert.get('alert')} "
            f"(burn rate {alert.get('burn_rate')}x of the "
            f"{alert.get('target')} SLO budget)")

    def rollup_record(self, tick: int,
                      snap: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        """The ``kind="rollup"`` record for this scheduler RIGHT NOW —
        the identical serialized-sketch document the telemetry file
        stream carries (tools/obs_agg.py merges it), which is also THE
        fleet router's placement signal (``Scheduler.load_report``): one
        telemetry path, not two.  With ``snap`` (a live
        :meth:`Scheduler._snapshot`), the occupancy gauges refresh first
        and the record carries a ``now`` sub-dict of instantaneous
        queue/pool state — rollup cadence must not stale an admission
        decision."""
        if snap is not None:
            self._gauges["queue_depth"].set(snap["queue_depth"])
            self._gauges["block_utilization"].set(
                snap["block_utilization"])
        # identity cached per writer: run_identity() FABRICATES a fresh
        # run id when NNPT_RUN_ID is unset, and a per-record call would
        # split one scheduler's cumulative rollups across several
        # "writers" in the aggregator — which then SUMS the same
        # cumulative counters once per fabricated id
        if not hasattr(self, "_ident"):
            self._ident = trace_lib.run_identity()
        ident = self._ident
        counters = dict(self._counters)
        counters["alerts"] = self.alerts_fired
        if self._budget is not None:
            counters["slo_events"] = self._budget.events
            counters["slo_misses"] = self._budget.misses
        rec = {
            "kind": "rollup", "role": self.role, "step": int(tick),
            "t": round(time.perf_counter() - self._t0, 6),
            "t_unix": round(time.time(), 3),
            "p": ident["process_id"], "run": ident["run_id"],
            "inc": ident["incarnation"],
            "sketches": {k: s.to_dict()
                         for k, s in self._sketches.items() if s.n},
            "counters": counters,
            "gauges": {k: g.to_dict() for k, g in self._gauges.items()
                       if g.last is not None},
        }
        if self.replica is not None:
            rec["replica"] = int(self.replica)
        if snap is not None:
            rec["now"] = {k: snap[k] for k in
                          ("queue_depth", "live", "prefilling",
                           "free_blocks", "block_utilization",
                           "committed_tokens") if k in snap}
        return rec

    def _maybe_rollup(self, tick: int, final: bool = False) -> None:
        if self.rollup_every <= 0:
            return
        if not final and tick % self.rollup_every:
            return
        rec = self.rollup_record(tick)
        self.rollups_written += 1
        self._write(rec)
        self._write_goodput(tick)

    def _write_goodput(self, tick: int) -> None:
        """One ``kind="goodput"`` record next to each serve rollup
        (cumulative per incarnation — the aggregator takes the newest
        per identity); sustained goodput-fraction misses burn the same
        ErrorBudget contract as the train role."""
        if self.goodput_meter is None:
            return
        snap = self.goodput_meter.snapshot()
        rec = goodput_lib.goodput_record(
            snap, role=self.role, step=tick,
            ident=getattr(self, "_ident", None) or trace_lib.run_identity())
        if self.replica is not None:
            rec["replica"] = int(self.replica)
        self._write(rec)
        if self._goodput_budget is not None and snap["spans"] > 0:
            frac = snap["goodput_fraction"] or 0.0
            alert = self._goodput_budget.observe(
                frac < self._goodput_frac_min)
            if alert:
                self._emit_alert({**alert, "goodput_fraction": frac,
                                  "goodput_target":
                                      self._goodput_frac_min})

    def close(self, tick: int, snap: Optional[Dict[str, Any]] = None
              ) -> None:
        if not self.enabled:
            return
        final_rec = None
        if snap is not None:
            # the drain can end off the metrics_every cadence; the final
            # record must carry the terminal counters regardless
            final_rec = {"kind": "serve", "step": int(tick),
                         "t": round(time.perf_counter() - self._t0, 6),
                         "final": True, **snap}
            self._write(final_rec)
            for key in ("admitted", "rejected", "evicted", "completed",
                        "tokens_out", "handed_off", "injected"):
                if key in snap:
                    self._counters[key] = int(snap[key])
        self._maybe_rollup(tick, final=True)
        self.heartbeat.beat(tick, final_rec, force=True, final=True)
        if self.goodput_meter is not None:
            trace_lib.remove_listener(self.goodput_meter.on_span)
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None


class Scheduler:
    """The continuous-batching service loop (see module docstring).

    ``now_fn`` injects the clock: tests and the fuzz harness drive a
    virtual clock so deadline policy is deterministic; production uses
    ``time.monotonic``."""

    def __init__(self, model: Transformer, params: Pytree,
                 cfg: Optional[ServeConfig] = None, now_fn=time.monotonic):
        # fresh default per instance: ServeConfig is a plain mutable
        # dataclass, and a shared default instance would leak one
        # caller's tweaks into every later default-constructed Scheduler
        self.cfg = cfg = ServeConfig() if cfg is None else cfg
        if cfg.role not in ("unified", "prefill", "decode"):
            raise ValueError(f"role must be 'unified', 'prefill' or "
                             f"'decode', got {cfg.role!r}")
        self.now = now_fn
        # install the span tracer + compile ledger BEFORE the server
        # builds its programs, so their compiles land in the ledger; an
        # already-active tracer (an enclosing run) is never displaced
        self._tracer = None
        if cfg.trace_dir and trace_lib.active() is None:
            self._tracer = trace_lib.start_run(cfg.trace_dir)
        self.server = PagedDecodeServer(
            model, params, slots=cfg.slots, num_blocks=cfg.num_blocks,
            block_size=cfg.block_size, max_len=cfg.max_len,
            temperature=cfg.temperature, top_k=cfg.top_k,
            top_p=cfg.top_p, seed=cfg.seed, kv_quant=cfg.kv_quant,
            attn_impl=cfg.attn_impl, prefix_cache=cfg.prefix_cache)
        self.queue: Deque[Request] = collections.deque()
        self.reqs: Dict[int, Request] = {}      # every request ever seen
        self._srv_rid: Dict[int, int] = {}      # scheduler rid -> server
        self._sched_rid: Dict[int, int] = {}    # server rid -> scheduler
        self._prefilling: Deque[int] = collections.deque()
        self._results: Dict[int, List[int]] = {}
        self._done_order: Deque[int] = collections.deque()
        self._next_rid = 0
        self.tick_no = 0
        self.admitted = 0
        self.rejected = 0
        self.evicted = 0
        self.completed = 0
        self.tokens_out = 0
        # disaggregated-handoff state: exports a prefill-role tick
        # produced, waiting for the worker loop to take them; counters
        # for both directions of the handoff
        self._handoffs: List[Dict[str, Any]] = []
        self.handed_off = 0
        self.injected = 0
        # decode-step key accounting (host arithmetic, zero device
        # traffic): attended = what the math needs, padded = what the
        # gathered path reduces over, kernel = whole blocks the fused
        # kernel walks — attended/padded is the measured skipped work
        self.attended_keys = 0
        self.padded_keys = 0
        self.kernel_keys = 0
        self.telemetry = _ServeTelemetry(cfg)
        # per-request flow-trace ids must stay unique across the fleet's
        # merged timeline: prefix the scheduler-local rid with this
        # process's identity (free when no tracer is installed) AND the
        # replica index when one is set — N replica processes launched
        # from one operator shell can share a process id, and their
        # scheduler-local rids all count from 0
        rep = "" if cfg.replica is None else f"R{int(cfg.replica)}-"
        self._flow_prefix = (
            f"p{trace_lib.run_identity()['process_id']}-{rep}r")
        # inter-tick gap attribution (utils/goodput.py): at the end of
        # each tick remember the wall-clock and WHY the next gap would
        # not be idle — requests queued with no live stream (queue_wait:
        # admission capacity, not the model, is the bottleneck) vs
        # streams mid-decode (sched_bubble: the loop owns the time).
        # The next tick retro-emits that gap as a span, so the goodput
        # taxonomy prices scheduler dead time instead of dropping it.
        self._gap_wall: Optional[float] = None
        self._gap_state: Optional[str] = None

    # ---- client surface ------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int,
               slo_ms: Optional[float] = None,
               unified: bool = False) -> Optional[int]:
        """Enqueue a request; returns its id, or None when the bounded
        queue is full (the request is REJECTED — overload sheds load
        instead of growing latency without bound).  Raises for requests
        the server could never hold (over ``max_len`` / pool capacity),
        mirroring ``PagedDecodeServer.try_admit``'s loud refusal.
        ``unified=True`` pins the request to end-to-end service on THIS
        scheduler regardless of its role — the degraded fallback a
        router uses when the peer pool is empty."""
        prompt_ids = [int(t) for t in prompt_ids]
        p = len(prompt_ids)
        if p == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens {max_new_tokens} < 1")
        if p + max_new_tokens > self.server.max_len:
            raise ValueError(f"prompt {p} + {max_new_tokens} exceeds "
                             f"max_len {self.server.max_len}")
        if (self.server.blocks_for(p + max_new_tokens)
                > self.server.allocator.capacity):
            raise ValueError("request needs more KV blocks than the pool "
                             "owns: unservable at any load")
        if len(self.queue) >= self.cfg.queue_depth:
            self.rejected += 1
            return None
        slo = self.cfg.default_slo_ms if slo_ms is None else slo_ms
        now = self.now()
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid=rid, prompt=prompt_ids,
                      max_new=int(max_new_tokens), t_submit=now,
                      deadline=(now + slo / 1e3 if slo is not None
                                else math.inf),
                      slo_ms=slo, unified=bool(unified))
        self.reqs[rid] = req
        self.queue.append(req)
        return rid

    def done(self, rid: int) -> bool:
        if rid in self._results:
            return True
        if rid in self._srv_rid or any(r.rid == rid for r in self.queue):
            return False
        raise KeyError(f"request {rid}: unknown or already consumed")

    def result(self, rid: int) -> List[int]:
        """Prompt + generated ids (pops the tokens; timings stay
        readable via :meth:`stats`)."""
        return self._results.pop(rid)

    def stats(self, rid: int) -> Request:
        return self.reqs[rid]

    def in_flight(self) -> int:
        return len(self._srv_rid)

    def pending(self) -> int:
        return len(self.queue)

    # ---- the service loop ----------------------------------------------
    def tick(self) -> List[int]:
        """One scheduler tick: retire/admit/prefill/decode.  Returns the
        rids completed during this tick."""
        self.tick_no += 1
        done_now: List[int] = []
        tracer = trace_lib.active()
        if tracer is not None and self._gap_state is not None:
            gap = time.time() - self._gap_wall
            if gap >= 1e-4:  # sub-100us gaps are loop overhead, not waits
                tracer.record_span(self._gap_state, self._gap_wall, gap,
                                   {"tick": self.tick_no})
        with trace_lib.span("admit", tick=self.tick_no):
            self._admit()
        with trace_lib.span("prefill", tick=self.tick_no):
            done_now += self._prefill_tick()
        if self.server.any_active():
            with trace_lib.span("decode", tick=self.tick_no):
                self._grow_or_evict()
                if trace_lib.active() is not None:
                    # flow step per decoding stream: the arrow chain
                    # that links this tick's decode span into each
                    # in-flight request's admit->...->retire path
                    for rid in self._srv_rid:
                        if rid not in self._prefilling:
                            trace_lib.flow(
                                "req", f"{self._flow_prefix}{rid}", "t",
                                rid=rid, stage="decode",
                                tick=self.tick_no)
                acct = self.server.keys_accounting()
                self.attended_keys += acct["attended_keys"]
                self.padded_keys += acct["padded_keys"]
                self.kernel_keys += acct["kernel_keys"]
                finished = self.server.step()
            with trace_lib.span("retire", tick=self.tick_no):
                for srv_rid in finished:
                    done_now.append(self._retire(srv_rid))
        self.telemetry.on_tick(self.tick_no, self._snapshot())
        self._gap_wall = time.time()
        self._gap_state = ("sched_bubble" if self._srv_rid
                           else ("queue_wait" if self.queue else None))
        return done_now

    def run_until_drained(self, max_ticks: int = 100_000) -> List[int]:
        """Tick until queue + in-flight are empty; returns completion
        order.  ``max_ticks`` is a hard stop so a policy bug shows up as
        a loud failure, not a hang."""
        order: List[int] = []
        for _ in range(max_ticks):
            if not (self.queue or self._srv_rid):
                return order
            order += self.tick()
        raise RuntimeError(
            f"not drained after {max_ticks} ticks: queue="
            f"{len(self.queue)} in_flight={len(self._srv_rid)}")

    def close(self) -> None:
        self.telemetry.close(self.tick_no, self._snapshot())
        if self._tracer is not None:
            trace_lib.stop_run(self._tracer)
            self._tracer = None

    # ---- fleet surface (serve/fleet.py) --------------------------------
    def load_report(self) -> Dict[str, Any]:
        """This replica's live load signal for a fleet router: the
        ``kind="rollup"`` record the telemetry stream already emits
        (serialized utils/sketches state — TTFT/ITL percentiles, queue
        depth, block utilization) refreshed with a ``now`` sub-dict of
        instantaneous occupancy, plus the admission capacity the router
        needs (``free_slots``).  One record shape everywhere: the router
        parses the same document tools/obs_agg.py merges."""
        rec = self.telemetry.rollup_record(self.tick_no, self._snapshot())
        rec["now"]["free_slots"] = self.server.free_slots()
        rec["now"]["in_flight"] = len(self._srv_rid)
        rec["now"]["slots"] = self.cfg.slots
        rec["now"]["queue_cap"] = self.cfg.queue_depth
        rec["now"]["tokens_at_risk"] = self.tokens_at_risk()
        rec["now"]["role"] = self.cfg.role
        rec["now"]["handoffs_ready"] = len(self._handoffs)
        return rec

    def take_handoffs(self) -> List[Dict[str, Any]]:
        """Drain the handoff exports a prefill-role scheduler has
        produced since the last call: one ``{"rid", "payload",
        "slo_ms", "ttft_ms", "prompt_tokens"}`` descriptor per stream
        whose prefill completed.  The caller (the fleet worker loop /
        InprocReplica) forwards each to the router, which owns the
        record from that commit point on."""
        out, self._handoffs = self._handoffs, []
        return out

    def inject(self, payload: Dict[str, Any],
               slo_ms: Optional[float] = None) -> Optional[int]:
        """Admit a handed-off stream directly into decode: imports the
        exported block contents + first sampled token
        (:meth:`PagedDecodeServer.import_stream`) and registers the
        request as decoding — no queue, no prefill duty.  Returns a
        request id, or None when a slot or the blocks are unavailable
        (nothing consumed; the router retries elsewhere or later).
        ``t_first`` is stamped now — the REAL time-to-first-token lives
        on the prefill side (the router composes end-to-end timings);
        this side's numbers price the decode phase only."""
        srv_rid = self.server.import_stream(payload)
        if srv_rid is None:
            return None
        now = self.now()
        slo = self.cfg.default_slo_ms if slo_ms is None else slo_ms
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid=rid,
                      prompt=[int(t) for t in payload["prompt"]],
                      max_new=int(payload["max_new"]), t_submit=now,
                      deadline=(now + slo / 1e3 if slo is not None
                                else math.inf),
                      slo_ms=slo, t_first=now)
        self.reqs[rid] = req
        self._srv_rid[rid] = srv_rid
        self._sched_rid[srv_rid] = rid
        self.injected += 1
        trace_lib.flow("req", f"{self._flow_prefix}{rid}", "t",
                       rid=rid, stage="inject", tick=self.tick_no)
        if self.server.done(srv_rid):
            # degenerate single-token handoff: already complete
            self._retire(srv_rid)
        return rid

    def tokens_at_risk(self) -> int:
        """Tokens of consumed work an unannounced kill would discard
        right now: prefilled + generated across every in-flight stream
        (queued requests carry zero — nothing has been spent on them).
        The advance-notice drain exists to take this to zero before the
        process dies; a chaos campaign's ``tokens_lost`` for a SIGKILL
        arm is exactly this quantity at the moment of the kill."""
        total = 0
        for rid, srv_rid in self._srv_rid.items():
            req = self.reqs[rid]
            st = self.server._streams[srv_rid]
            slot = self.server._slot_of[srv_rid]
            prefilled, p = st.prefilled, len(req.prompt)
            generated = (int(self.server._pos_host[slot]) - p + 1
                         if prefilled >= p else 0)
            total += prefilled + max(0, generated)
        return total

    def drain(self) -> List[Dict[str, Any]]:
        """Stop serving and hand every unfinished request back for
        requeue: evicts all in-flight streams (their blocks release, the
        allocator's ``assert_drained`` holds afterwards) and empties the
        wait queue, returning one descriptor per request in ORIGINAL
        submission order — ``{"rid", "prompt", "max_new", "slo_ms",
        "prefilled", "generated"}``.  ``prefilled``/``generated`` are the
        consumed-token state at drain time (observability: how much work
        the drain discards); the tokens themselves are NOT carried —
        greedy decode is deterministic, so re-admission on any replica
        with the same params reproduces them exactly (pinned by
        tests/test_serve_sched.py).  Completed-but-unconsumed results
        stay readable via :meth:`result`."""
        out: List[Dict[str, Any]] = []
        for rid in list(self._srv_rid):
            srv_rid = self._srv_rid.pop(rid)
            self._sched_rid.pop(srv_rid)
            req = self.reqs[rid]
            st = self.server._streams[srv_rid]
            slot = self.server._slot_of[srv_rid]
            prefilled, p = st.prefilled, len(req.prompt)
            # generated-so-far: position p holds the first sampled token
            # once prefill completes, then one per decode step
            generated = (int(self.server._pos_host[slot]) - p + 1
                         if prefilled >= p else 0)
            self.server.evict(srv_rid)
            if rid in self._prefilling:
                self._prefilling.remove(rid)
            req.t_first = None      # TTFT restarts on re-admission
            out.append({"rid": rid, "prompt": list(req.prompt),
                        "max_new": req.max_new, "slo_ms": req.slo_ms,
                        "prefilled": prefilled,
                        "generated": max(0, generated),
                        "t_submit": req.t_submit,
                        "evictions": req.evictions})
        # handoffs exported but never taken by the worker loop: the
        # stream is gone from the server, but the REQUEST must not
        # vanish — hand it back as undone work (full re-prefill on
        # whichever replica the router picks next)
        for h in self._handoffs:
            req = self.reqs[h["rid"]]
            req.t_first = None
            out.append({"rid": req.rid, "prompt": list(req.prompt),
                        "max_new": req.max_new, "slo_ms": req.slo_ms,
                        "prefilled": 0, "generated": 0,
                        "t_submit": req.t_submit,
                        "evictions": req.evictions})
        self._handoffs = []
        for req in self.queue:
            out.append({"rid": req.rid, "prompt": list(req.prompt),
                        "max_new": req.max_new, "slo_ms": req.slo_ms,
                        "prefilled": 0, "generated": 0,
                        "t_submit": req.t_submit,
                        "evictions": req.evictions})
        self.queue.clear()
        out.sort(key=lambda d: (d["t_submit"], d["rid"]))
        return out

    def quiesce(self) -> List[Dict[str, Any]]:
        """:meth:`drain` plus the proof: evict everything, then assert
        the allocator really is empty before the caller exits.  The one
        call shared by every worker shutdown path — the advance-notice
        preemption drain, the decommission handshake, and the orphaned
        worker whose control plane died (stdin EOF) — so "exited
        cleanly" always MEANS "leaked no blocks"."""
        out = self.drain()
        self.server.allocator.assert_drained()
        return out

    # ---- internals -----------------------------------------------------
    def _committed_tokens(self) -> int:
        """In-flight committed (prompt + max_new) tokens, refcount-aware:
        token positions resident in a SHARED block are physical once, so
        each extra reference's worth is discounted (the server's
        block-granular upper bound) instead of charged per stream —
        otherwise a token budget would reject admissions whose residency
        the cache already holds."""
        raw = sum(len(r.prompt) + r.max_new
                  for rid, r in self.reqs.items()
                  if rid in self._srv_rid)
        return max(0, raw - self.server.shared_token_discount())

    def _admit(self) -> None:
        while self.queue:
            req = self.queue[0]
            p = len(req.prompt)
            if self.server.free_slots() == 0:
                return
            # normal admission overcommits (blocks for the prompt + first
            # token only — growth is on demand; that overcommit IS the
            # capacity win).  A request that already got evicted proved
            # overcommit fails for it right now: hold it at the head
            # until the pool can cover its FULL need, else it would
            # thrash admit->grow->evict while the same streams hold the
            # pool.  Both needs are REFCOUNT-AWARE: a prefix match onto
            # in-use blocks consumes no free block (admit_need subtracts
            # them, and adds the reserved CoW fork block for a mid-block
            # match boundary).
            need = self.server.admit_need(req.prompt, req.max_new,
                                          full_residency=bool(
                                              req.evictions))
            if self.server.free_blocks < need:
                return
            if (self.cfg.token_budget > 0
                    and self._committed_tokens() + p + req.max_new
                    > self.cfg.token_budget):
                return
            srv_rid = self.server.try_admit(req.prompt, req.max_new)
            if srv_rid is None:
                return
            self.queue.popleft()
            self._srv_rid[req.rid] = srv_rid
            self._sched_rid[srv_rid] = req.rid
            self._prefilling.append(req.rid)
            self.admitted += 1
            # flow START (or re-start after an eviction's re-admission)
            trace_lib.flow("req", f"{self._flow_prefix}{req.rid}", "s",
                           rid=req.rid, stage="admit",
                           prompt_tokens=p, tick=self.tick_no)

    def _prefill_tick(self) -> List[int]:
        """At most one prefill chunk per tick (interleaving: decoding
        streams advance every tick regardless of admission work)."""
        done_now: List[int] = []
        if not self._prefilling:
            return done_now
        rid = self._prefilling[0]
        srv_rid = self._srv_rid[rid]
        trace_lib.flow("req", f"{self._flow_prefix}{rid}", "t",
                       rid=rid, stage="prefill", tick=self.tick_no)
        if self.server.prefill_step(srv_rid, self.cfg.prefill_chunk):
            self._prefilling.popleft()
            req = self.reqs[rid]
            req.t_first = self.now()
            if self.server.done(srv_rid):   # single-token request
                done_now.append(self._retire(srv_rid))
            elif self.cfg.role == "prefill" and not req.unified:
                # disaggregated handoff: the stream leaves this replica
                # at the prefill->decode boundary.  Export FIRST (read-
                # only), then release — under prefix_cache the owned
                # prompt blocks were registered during prefill, so the
                # release parks them cached-free and the content stays
                # resident for future prefix hits
                self._export_handoff(rid, srv_rid)
        return done_now

    def _export_handoff(self, rid: int, srv_rid: int) -> None:
        req = self.reqs[rid]
        payload = self.server.export_stream(srv_rid)
        self._srv_rid.pop(rid)
        self._sched_rid.pop(srv_rid)
        self.server.evict(srv_rid)
        ttft = round((req.t_first - req.t_submit) * 1e3, 3)
        self.handed_off += 1
        self.telemetry.on_handoff(ttft)
        self._handoffs.append({
            "rid": rid, "payload": payload, "slo_ms": req.slo_ms,
            "ttft_ms": ttft, "prompt_tokens": len(req.prompt)})
        trace_lib.flow("req", f"{self._flow_prefix}{rid}", "t",
                       rid=rid, stage="handoff", tick=self.tick_no)

    def _grow_or_evict(self) -> None:
        """Supply every decoding stream's next block, evicting
        latest-deadline streams under exhaustion.  The earliest-deadline
        stream is never evicted while another in-flight stream exists —
        the oldest obligation always progresses."""
        while self.server.ensure_blocks():
            victim = self._pick_victim()
            if victim is None:
                # unreachable when submit()'s capacity guard holds: a
                # sole stream owns every non-free block, and the pool
                # covers any single stream end to end
                raise RuntimeError("block exhaustion with no evictable "
                                   "stream (capacity guard violated)")
            self._evict(victim)

    def _pick_victim(self) -> Optional[int]:
        inflight = [self.reqs[rid] for rid in self._srv_rid]
        if len(inflight) <= 1:
            return None
        key = lambda r: (r.deadline, r.t_submit, r.rid)   # noqa: E731
        protected = min(inflight, key=key)
        victim = max(inflight, key=key)
        if victim.rid == protected.rid:
            return None
        return victim.rid

    def _evict(self, rid: int) -> None:
        srv_rid = self._srv_rid.pop(rid)
        self._sched_rid.pop(srv_rid)
        self.server.evict(srv_rid)
        if rid in self._prefilling:
            self._prefilling.remove(rid)
        req = self.reqs[rid]
        req.evictions += 1
        req.t_first = None          # TTFT restarts: tokens are recomputed
        self.queue.appendleft(req)  # front: original arrival order kept
        self.evicted += 1
        log(f"[serve] evicted rid={rid} (deadline "
            f"{'inf' if math.isinf(req.deadline) else round(req.deadline, 3)}"
            f"); requeued at front")

    def _retire(self, srv_rid: int) -> int:
        rid = self._sched_rid.pop(srv_rid)
        self._srv_rid.pop(rid)
        req = self.reqs[rid]
        req.t_done = self.now()
        trace_lib.flow("req", f"{self._flow_prefix}{rid}", "f",
                       rid=rid, stage="retire", tick=self.tick_no)
        if req.t_first is None:
            req.t_first = req.t_done
        toks = self.server.result(srv_rid)
        self._results[rid] = toks
        n_gen = len(toks) - len(req.prompt)
        self.completed += 1
        self.tokens_out += n_gen
        self.telemetry.on_request_done(req, n_gen)
        # bounded retention: stats()/result() stay readable for the last
        # completed_history completions (plenty for a load generator's
        # post-completion read), then both the Request and any
        # never-consumed result are pruned — a service that runs for
        # days must not grow per-request state without bound
        self._done_order.append(rid)
        while len(self._done_order) > max(1, self.cfg.completed_history):
            old = self._done_order.popleft()
            self.reqs.pop(old, None)
            self._results.pop(old, None)
        return rid

    def _snapshot(self) -> Dict[str, Any]:
        prefix: Dict[str, Any] = {}
        if self.cfg.prefix_cache:
            ps = self.server.prefix_stats()
            prefix = dict(ps)
            # hit rate over prompt TOKENS (not requests): the fraction
            # of admitted prompt work served from resident blocks — the
            # number RadixAttention-style stores are judged on
            prefix["prefix_hit_rate"] = (
                round(ps["prefix_hit_tokens"]
                      / ps["prompt_tokens_admitted"], 4)
                if ps["prompt_tokens_admitted"] else None)
        return {
            **prefix,
            "queue_depth": len(self.queue),
            "live": len(self._srv_rid),
            "prefilling": len(self._prefilling),
            "free_blocks": self.server.free_blocks,
            "block_utilization": round(self.server.block_utilization(), 4),
            "committed_tokens": self._committed_tokens(),
            "admitted": self.admitted,
            "rejected": self.rejected,
            "evicted": self.evicted,
            "completed": self.completed,
            "tokens_out": self.tokens_out,
            "handed_off": self.handed_off,
            "injected": self.injected,
            "attended_keys": self.attended_keys,
            "padded_keys": self.padded_keys,
            "kernel_keys": self.kernel_keys,
            "attended_ratio": (
                round(self.attended_keys / self.padded_keys, 4)
                if self.padded_keys else None),
        }
