"""Serving fleet: N replica programs behind one SLO-aware router.

One ``Scheduler`` over one ``PagedDecodeServer`` is a single REPLICA —
a single program on a single replica group, which is where every
subsystem in this repo stopped before this module (ROADMAP item 1).
Millions-of-users traffic needs several cooperating single-purpose
programs joined by queues (the Podracer shape, arXiv 2104.06272): here,
N serving replica processes under the process-group supervisor
(``train.resilience.GroupSupervisor``) with a front-end router
load-balancing one bounded fleet wait queue across them.

* **Replica handles** — the router speaks one interface
  (:class:`ReplicaHandle`) to three replica shapes:
  :class:`InprocReplica` (a ``serve.Scheduler`` in this process — the
  budgeted core-lane test shape, and the zero-IPC baseline),
  :class:`ProcReplica` (a subprocess running :func:`worker_main`,
  newline-JSON over stdio — the production shape, one process per
  replica so an XLA crash takes out ONE replica's runtime), and
  :class:`TPGenerateReplica` (one replica SPANNING a tensor-parallel
  mesh through ``models.generate_tp`` — ragged batched decode on
  ``tensor``-sharded params, token-identical to the single-device
  replica since both are pinned against ``models.generate``).
* **Placement** — least-loaded with deadline feasibility, fed by each
  replica's LIVE load report: the ``kind="rollup"`` record the
  telemetry plane already emits (``Scheduler.load_report`` — serialized
  ``utils/sketches.py`` quantile state for TTFT/ITL plus instantaneous
  queue-depth/block-utilization occupancy).  One telemetry path: the
  router parses the same document ``tools/obs_agg.py`` merges, so the
  admission signal and the dashboard can never disagree about what a
  replica reported.
* **Admission** — overload is rejected at the ROUTER (one bounded fleet
  queue), not by N replica queues rejecting blind: each replica keeps
  only a shallow local backlog (``replica_queue_cap``) so almost all
  waiting work sits where it can still be re-placed.  A request whose
  deadline no replica can plausibly meet (predicted wait from the TTFT
  rollup + queue occupancy exceeds its slack) can be rejected up front
  (``reject_infeasible=True``) instead of admitted into a miss.
* **Replica death drains cleanly** — the router keeps the authoritative
  ledger of every dispatched request; when a replica dies (crash,
  SIGKILL, hang-kill) its uncompleted requests REQUEUE at the front of
  the fleet queue in original submission order and re-place on
  siblings.  Greedy decode is deterministic, so re-execution reproduces
  byte-identical tokens (pinned by tests/test_fleet.py); p99 TTFT
  degrades, no request starves.  The supervisor relaunches the dead
  replica under its own backoff/budget without disturbing siblings, and
  the relaunched process re-registers through its ``ready`` event.

``python -m neural_networks_parallel_training_with_mpi_tpu.serve.fleet
--worker ...`` is the replica-process entry (:func:`worker_main`);
``tools/serve_fleet.py`` is the operator launcher over
:func:`launch_fleet`.
"""

from __future__ import annotations

import collections
import json
import math
import os
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import log
from ..utils.sketches import Gauge, QuantileSketch

Pytree = Any

# wire protocol (one JSON object per line):
#   parent -> worker : {"op": "submit", "rid", "prompt", "max_new",
#                       "slo_ms", "unified"?} | {"op": "drain"}
#                     | {"op": "inject", "rid", "payload", "slo_ms"}
#                     | {"op": "decommission"} | {"op": "exit"}
#   worker -> parent : {"ev": "ready", ...} | {"ev": "done", "rid",
#                       "tokens", "ttft_ms", "itl_ms", ...}
#                     | {"ev": "reject", "rid", "inject"?}
#                     | {"ev": "handoff", "rid", "payload", "ttft_ms"}
#                     | {"ev": "injected", "rid"}
#                     | {"ev": "status", "report": <load_report>}
#                     | {"ev": "drained", "requests": [...]}
#                     | {"ev": "load_error", "error": ...}
# fleet rids ride the wire verbatim, so completions need no id
# translation on the way back.  "decommission" is "drain" followed by a
# terminal exit with train.resilience.EXIT_DECOMMISSION (47) — the
# autopilot's scale-in handshake (the supervisor must have retired the
# child first so the exit is final, not relaunched).
#
# Disaggregated handoff (DESIGN.md §11): a PREFILL-role worker answers
# a submit with "handoff" instead of "done" — the exported stream
# (serve/paged_kv.export_stream: block contents + first sampled token)
# rides the event, and emitting it is the COMMIT point: the router owns
# the record from that line on.  The router forwards it to a
# decode-role worker as an "inject" op, which acks "injected" (or
# rejects with "inject": true when a slot/blocks are unavailable) and
# later reports the normal "done".  "unified": true on a submit pins
# end-to-end service regardless of the worker's role — the degraded
# single-pool fallback.

# replica ids encode the WEIGHT GENERATION: a generation-g replica gets
# id g * GEN_STRIDE + k, so its flow-trace prefix (p{id}-R{id}-r...) and
# telemetry identity attribute every token it emits to its generation
# (id // GEN_STRIDE) without a side channel — the PR 14 trace contract
# the zero-downtime rollout is judged on.
GEN_STRIDE = 1000


# ---------------------------------------------------------------------------
# load signal
# ---------------------------------------------------------------------------

@dataclass
class LoadSignal:
    """One replica's placement signal, parsed from its
    ``Scheduler.load_report()`` rollup record (serialized sketches +
    ``now`` occupancy) — NOT from private scheduler state, so a
    subprocess replica and an in-process one feed the router
    identically."""
    t_unix: float = 0.0
    queue_depth: int = 0
    in_flight: int = 0
    free_slots: int = 0
    slots: int = 1
    queue_cap: int = 0
    free_blocks: int = 0
    block_utilization: float = 0.0
    ttft_p50_ms: Optional[float] = None
    ttft_p99_ms: Optional[float] = None
    replica: Optional[int] = None
    role: str = "unified"              # scheduler serving role

    @classmethod
    def from_report(cls, rec: Dict[str, Any]) -> "LoadSignal":
        now = rec.get("now") or {}
        sig = cls(
            t_unix=float(rec.get("t_unix") or 0.0),
            queue_depth=int(now.get("queue_depth", 0)),
            in_flight=int(now.get("in_flight", now.get("live", 0))),
            free_slots=int(now.get("free_slots", 0)),
            slots=max(1, int(now.get("slots", 1))),
            queue_cap=int(now.get("queue_cap", 0)),
            free_blocks=int(now.get("free_blocks", 0)),
            block_utilization=float(now.get("block_utilization", 0.0)),
            replica=rec.get("replica"),
            role=str(now.get("role", "unified") or "unified"),
        )
        doc = (rec.get("sketches") or {}).get("ttft_ms")
        if doc:
            sk = QuantileSketch.from_dict(doc)
            sig.ttft_p50_ms = sk.quantile(0.5)
            sig.ttft_p99_ms = sk.quantile(0.99)
        return sig

    @property
    def occupancy(self) -> float:
        """Queued + running work, normalized by the replica's slot
        count — the least-loaded score (heterogeneous replicas compare
        by RELATIVE load, not absolute stream counts)."""
        return (self.in_flight + self.queue_depth) / self.slots


# ---------------------------------------------------------------------------
# the router's request ledger
# ---------------------------------------------------------------------------

@dataclass
class FleetRequest:
    """One request's fleet-level lifecycle.  The ROUTER owns this
    ledger — it is what makes replica death recoverable: a dead
    replica's uncompleted entries requeue from here, never from the
    dead process's memory."""
    rid: int
    prompt: List[int]
    max_new: int
    slo_ms: Optional[float]
    t_submit: float
    deadline: float
    replica: Optional[str] = None      # current / last placement
    t_dispatch: Optional[float] = None
    t_done: Optional[float] = None
    requeues: int = 0                  # times re-placed after a death
    ttft_ms: Optional[float] = None    # fleet-level: router wait included
    itl_ms: Optional[float] = None
    n_generated: Optional[int] = None
    generation: Optional[int] = None   # weight generation that COMPLETED
    #                                    this request (set at completion)
    # --- disaggregated-handoff ledger (DESIGN.md §11) ---------------
    # phase: queued -> prefilling -> handoff_inflight -> decoding.
    # ``handoff`` holds the COMMITTED export payload until completion:
    # it IS the decode-death recovery record (re-inject, no re-prefill).
    phase: str = "queued"
    unified: bool = False              # degraded end-to-end dispatch
    handoff: Optional[Dict[str, Any]] = None
    handoff_t: Optional[float] = None  # commit time (handoff received)
    handoff_ms: Optional[float] = None # commit -> injected ack latency
    handoff_retries: int = 0
    handoff_next_t: float = 0.0        # backoff: earliest re-dispatch
    prefill_replica: Optional[str] = None

    @property
    def deadline_missed(self) -> Optional[bool]:
        if self.t_done is None:
            return None
        return bool(math.isfinite(self.deadline)
                    and self.t_done > self.deadline)


# ---------------------------------------------------------------------------
# replica handles
# ---------------------------------------------------------------------------

def role_kind(handle_or_role) -> str:
    """Collapse a handle's role string to one of the three placement
    kinds: ``"prefill"`` / ``"decode"`` / ``"unified"``.  Legacy role
    strings ("replica", "serve", "serve-replica") are unified — a
    pre-disagg fleet routes exactly as before."""
    role = handle_or_role if isinstance(handle_or_role, str) else \
        getattr(handle_or_role, "role", "replica")
    role = str(role or "replica")
    if role.endswith("prefill"):
        return "prefill"
    if role.endswith("decode"):
        return "decode"
    return "unified"


class ReplicaHandle:
    """What the router needs from a replica, regardless of where it
    runs.  ``submit`` may refuse (False) — the request stays at the
    fleet queue head; ``pump`` advances the replica (in-process shapes)
    and returns completion dicts carrying the FLEET rid."""

    name: str = "replica"
    role: str = "replica"
    generation: int = 0     # weight generation this replica serves

    def alive(self) -> bool:
        raise NotImplementedError

    def accepting(self) -> bool:
        raise NotImplementedError

    def load(self) -> Optional[LoadSignal]:
        raise NotImplementedError

    def submit(self, req: FleetRequest) -> bool:
        raise NotImplementedError

    def can_inject(self) -> bool:
        """Whether this handle understands the ``inject`` op at all
        (batch engines don't)."""
        return False

    def inject(self, req: FleetRequest, payload: Dict[str, Any]) -> bool:
        """Dispatch a committed handoff record.  May refuse (False) —
        the record stays in the router's handoff queue."""
        return False

    def forget(self, rid: int) -> None:
        """Drop one rid from the assigned set WITHOUT completing it —
        the router's handoff-timeout path, which re-owns the record
        before re-dispatching it elsewhere."""

    def pump(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def assigned(self) -> List[int]:
        """Fleet rids dispatched here and not yet completed."""
        raise NotImplementedError

    def take_assigned(self) -> List[int]:
        """Drop and return the assigned set (the router requeues them
        after a death)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class InprocReplica(ReplicaHandle):
    """A ``serve.Scheduler`` in this process.  The core-lane test shape
    (no subprocesses inside the budgeted lane) and the mechanism
    baseline: everything the router does to a subprocess replica it
    does to this one, through the same load-report record."""

    def __init__(self, scheduler, name: str = "replica-0"):
        self.name = name
        self.sched = scheduler
        srole = str(getattr(scheduler.cfg, "role", "unified") or "unified")
        self.role = "replica" if srole == "unified" else srole
        self._local: Dict[int, int] = {}     # fleet rid -> scheduler rid
        self._events: List[Dict[str, Any]] = []   # pending injected acks
        self._dead = False

    def alive(self) -> bool:
        return not self._dead

    def accepting(self) -> bool:
        return (not self._dead
                and self.sched.pending() < self.sched.cfg.queue_depth)

    def load(self) -> Optional[LoadSignal]:
        if self._dead:
            return None
        return LoadSignal.from_report(self.sched.load_report())

    def submit(self, req: FleetRequest) -> bool:
        if self._dead:
            return False
        lrid = self.sched.submit(req.prompt, req.max_new,
                                 slo_ms=req.slo_ms, unified=req.unified)
        if lrid is None:
            return False
        self._local[req.rid] = lrid
        return True

    def can_inject(self) -> bool:
        return not self._dead

    def inject(self, req: FleetRequest, payload: Dict[str, Any]) -> bool:
        if self._dead:
            return False
        try:
            lrid = self.sched.inject(payload, slo_ms=req.slo_ms)
        except ValueError:
            return False
        if lrid is None:
            return False
        self._local[req.rid] = lrid
        # the ack rides the next pump so the router sees the same
        # event order a subprocess replica produces
        self._events.append({"ev": "injected", "rid": req.rid})
        return True

    def forget(self, rid: int) -> None:
        self._local.pop(rid, None)

    def pump(self) -> List[Dict[str, Any]]:
        if self._dead:
            return []
        out, self._events = self._events, []
        if self.sched.pending() or self.sched.in_flight():
            done_local = set(self.sched.tick())
        else:
            done_local = set()
        for rec in self.sched.take_handoffs():
            frid = next((f for f, l in self._local.items()
                         if l == rec["rid"]), None)
            if frid is None:
                continue
            del self._local[frid]
            out.append({"ev": "handoff", "rid": frid,
                        "payload": rec["payload"],
                        "ttft_ms": rec.get("ttft_ms")})
        for frid, lrid in list(self._local.items()):
            fin = lrid in done_local
            if not fin:
                # injected single-token streams retire inside inject()
                # and never appear in a tick's done list
                try:
                    fin = self.sched.done(lrid)
                except KeyError:
                    fin = False
            if not fin:
                continue
            st = self.sched.stats(lrid)
            out.append({"rid": frid,
                        "tokens": self.sched.result(lrid),
                        "ttft_ms": st.ttft_ms, "itl_ms": st.itl_ms,
                        "evictions": st.evictions})
            del self._local[frid]
        return out

    def assigned(self) -> List[int]:
        return list(self._local)

    def take_assigned(self) -> List[int]:
        rids = list(self._local)
        self._local.clear()
        return rids

    def fail(self) -> None:
        """Test hook: simulate this replica's death (the in-process
        analogue of SIGKILL — its scheduler state is unreachable)."""
        self._dead = True

    def drain(self) -> List[Dict[str, Any]]:
        return self.sched.drain()

    def close(self) -> None:
        if not self._dead:
            self.sched.close()


class TPGenerateReplica(ReplicaHandle):
    """One replica SPANNING a tensor-parallel mesh: batched ragged
    decode through ``models.generate_tp`` on ``tensor``-sharded params
    (the native Megatron layout).  This is a batch engine, not a
    continuous-batching scheduler — each :meth:`pump` takes up to
    ``batch`` queued requests and decodes them in ONE shard_mapped
    program across the mesh, so TTFT is batch-granular; what it buys is
    a replica whose model no longer fits (or saturates) one device.
    Prompt width, batch and total length pad to power-of-two buckets so
    the compiled-program set stays O(log²), the same discipline as the
    paged server's prefill buckets.  Greedy tokens are identical to the
    single-device replica: both paths are pinned against
    ``models.generate`` (tests/test_generate_tp.py,
    tests/test_serve_paged.py) and the fleet pin closes the triangle
    (tests/test_fleet.py)."""

    def __init__(self, model, params_tp, mesh, *, batch: int = 4,
                 queue_cap: int = 64, name: str = "tp-replica",
                 pad_id: int = 0, now_fn=time.monotonic):
        self.name = name
        self.model = model
        self.params_tp = params_tp
        self.mesh = mesh
        self.batch = int(batch)
        self.queue_cap = int(queue_cap)
        self.pad_id = int(pad_id)
        self.now = now_fn
        self._queue: Deque[FleetRequest] = collections.deque()
        self._dead = False
        self._ttft = QuantileSketch()
        self._itl = QuantileSketch()
        self._q_gauge = Gauge()
        self._batches = 0

    @staticmethod
    def _bucket(n: int, lo: int = 8) -> int:
        b = lo
        while b < n:
            b *= 2
        return b

    def alive(self) -> bool:
        return not self._dead

    def accepting(self) -> bool:
        return not self._dead and len(self._queue) < self.queue_cap

    def load_report(self) -> Dict[str, Any]:
        """The same record shape ``Scheduler.load_report`` emits, built
        from this engine's own sketches — the router must not
        special-case replica shapes."""
        self._q_gauge.set(len(self._queue))
        return {
            "kind": "rollup", "role": "serve",
            "t_unix": round(time.time(), 3),
            "sketches": {k: s.to_dict()
                         for k, s in (("ttft_ms", self._ttft),
                                      ("itl_ms", self._itl)) if s.n},
            "counters": {"batches": self._batches},
            "gauges": {"queue_depth": self._q_gauge.to_dict()},
            "now": {"queue_depth": len(self._queue), "in_flight": 0,
                    "free_slots": self.batch, "slots": self.batch,
                    "queue_cap": self.queue_cap, "free_blocks": 1 << 20,
                    "block_utilization": 0.0},
        }

    def load(self) -> Optional[LoadSignal]:
        if self._dead:
            return None
        return LoadSignal.from_report(self.load_report())

    def submit(self, req: FleetRequest) -> bool:
        if not self.accepting():
            return False
        self._queue.append(req)
        return True

    def pump(self) -> List[Dict[str, Any]]:
        if self._dead or not self._queue:
            return []
        import jax.numpy as jnp
        import numpy as np

        from ..models.generate_tp import generate_tp

        reqs = [self._queue.popleft()
                for _ in range(min(self.batch, len(self._queue)))]
        lens = [len(r.prompt) for r in reqs]
        p_pad = self._bucket(max(lens))
        total = self._bucket(max(l + r.max_new
                                 for l, r in zip(lens, reqs)),
                             lo=p_pad + 1)
        b_pad = self._bucket(len(reqs), lo=1)
        prompts = np.full((b_pad, p_pad), self.pad_id, np.int32)
        plens = np.ones((b_pad,), np.int32)
        for i, r in enumerate(reqs):
            prompts[i, :lens[i]] = r.prompt
            plens[i] = lens[i]
        t0 = self.now()
        toks = generate_tp(self.model, self.params_tp,
                           jnp.asarray(prompts), self.mesh,
                           max_new_tokens=total - p_pad,
                           prompt_lens=jnp.asarray(plens),
                           pad_id=self.pad_id)
        toks = np.asarray(toks)
        t1 = self.now()
        self._batches += 1
        out = []
        for i, r in enumerate(reqs):
            row = [int(t) for t in toks[i, :lens[i] + r.max_new]]
            ttft = (t1 - t0) * 1e3   # batch-granular: first token
            #                          lands when the batch returns
            itl = 0.0 if r.max_new <= 1 else ttft / (r.max_new - 1)
            self._ttft.add(ttft)
            self._itl.add(itl)
            out.append({"rid": r.rid, "tokens": row,
                        "ttft_ms": ttft, "itl_ms": itl, "evictions": 0})
        return out

    def assigned(self) -> List[int]:
        return [r.rid for r in self._queue]

    def take_assigned(self) -> List[int]:
        rids = [r.rid for r in self._queue]
        self._queue.clear()
        return rids

    def fail(self) -> None:
        self._dead = True


class ProcReplica(ReplicaHandle):
    """A replica SUBPROCESS speaking the newline-JSON protocol (module
    header).  A dedicated reader thread drains the child's stdout into
    an event queue so the router's pump never blocks on a slow or dead
    pipe; writes detect a broken pipe and mark the replica down (the
    supervisor owns the relaunch, :meth:`attach` re-binds the fresh
    process and the ``ready`` event re-opens admission)."""

    def __init__(self, name: str, role: str = "replica",
                 generation: int = 0):
        self.name = name
        self.role = role
        self.generation = int(generation)
        self._proc = None
        self._stdin = None
        self._events: Deque[Dict[str, Any]] = collections.deque()
        self._lock = threading.Lock()
        self._reader: Optional[threading.Thread] = None
        self._assigned: Dict[int, FleetRequest] = {}
        self.ready = False
        self._signal: Optional[LoadSignal] = None
        self.report: Optional[Dict[str, Any]] = None   # last RAW rollup
        #   (the serve.autopilot judge reads the same document obs_agg
        #   merges, through this field instead of the filesystem)
        self.drained: Optional[List[Dict[str, Any]]] = None
        self.incarnation = -1
        # advance-notice preemption (PR 18): the worker announced it is
        # going away in ``notice_grace_s`` seconds — stop placing new
        # work here (accepting() gates) while in-flight requests finish;
        # the autopilot backfills BEFORE the exit lands
        self.noticed = False
        self.notice_grace_s: Optional[float] = None

    # ---- supervisor wiring --------------------------------------------
    def attach(self, proc, incarnation: int = 0) -> None:
        """Bind to a freshly spawned worker process (GroupSupervisor's
        ``on_spawn`` callback lands here on every (re)launch)."""
        self._proc = proc
        self._stdin = proc.stdin
        self.ready = False
        self._signal = None
        self.noticed = False
        self.notice_grace_s = None
        self.incarnation = incarnation
        t = threading.Thread(target=self._read_loop,
                             args=(proc.stdout,), daemon=True)
        t.start()
        self._reader = t

    def _read_loop(self, stream) -> None:
        try:
            for line in stream:
                line = line.strip()
                if not line or not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "ev" in rec:
                    with self._lock:
                        self._events.append(rec)
        except (OSError, ValueError):
            pass  # dead pipe: the supervisor reaps the exit

    # ---- handle interface ---------------------------------------------
    def alive(self) -> bool:
        return (self._proc is not None
                and self._proc.poll() is None)

    def accepting(self) -> bool:
        return self.alive() and self.ready and not self.noticed

    def load(self) -> Optional[LoadSignal]:
        return self._signal

    def _send(self, obj: Dict[str, Any]) -> bool:
        if self._stdin is None:
            return False
        try:
            self._stdin.write(json.dumps(obj) + "\n")
            self._stdin.flush()
            return True
        except (OSError, ValueError):
            return False

    def submit(self, req: FleetRequest) -> bool:
        if not self.accepting():
            return False
        op = {"op": "submit", "rid": req.rid, "prompt": req.prompt,
              "max_new": req.max_new, "slo_ms": req.slo_ms}
        if req.unified:
            op["unified"] = True
        if not self._send(op):
            return False
        self._assigned[req.rid] = req
        return True

    def can_inject(self) -> bool:
        return True

    def inject(self, req: FleetRequest, payload: Dict[str, Any]) -> bool:
        if not self.accepting():
            return False
        if not self._send({"op": "inject", "rid": req.rid,
                           "payload": payload, "slo_ms": req.slo_ms}):
            return False
        self._assigned[req.rid] = req
        return True

    def forget(self, rid: int) -> None:
        self._assigned.pop(rid, None)

    def request_drain(self) -> bool:
        return self._send({"op": "drain"})

    def request_decommission(self) -> bool:
        """Ask the worker to drain and exit
        :data:`train.resilience.EXIT_DECOMMISSION` — retire the child at
        the supervisor FIRST (``GroupSupervisor.retire``) so the exit is
        terminal even if the drain stalls and escalates to a kill."""
        return self._send({"op": "decommission"})

    def request_exit(self) -> bool:
        return self._send({"op": "exit"})

    def pump(self) -> List[Dict[str, Any]]:
        out = []
        while True:
            with self._lock:
                if not self._events:
                    break
                rec = self._events.popleft()
            ev = rec.get("ev")
            if ev == "ready":
                self.ready = True
            elif ev == "status":
                try:
                    self.report = rec.get("report") or {}
                    self._signal = LoadSignal.from_report(self.report)
                except (TypeError, ValueError, KeyError):
                    pass
            elif ev == "done":
                self._assigned.pop(int(rec["rid"]), None)
                out.append(rec)
            elif ev == "handoff":
                # the stream left this (prefill) worker: emitting the
                # event IS the commit — the router owns the record now
                self._assigned.pop(int(rec["rid"]), None)
                out.append(rec)
            elif ev == "injected":
                # inject ack: the stream is live on this (decode)
                # worker; it stays in the assigned set until done
                out.append(rec)
            elif ev == "reject":
                # the worker's local queue refused (should not happen
                # while the router respects its caps): back to the
                # fleet queue like a death-requeue of one request
                req = self._assigned.pop(int(rec["rid"]), None)
                if req is not None:
                    rec["requeue"] = req
                    out.append(rec)
            elif ev == "drained":
                self.drained = rec.get("requests") or []
            elif ev == "preempt_notice":
                # the worker is going away on purpose: close admission
                # NOW (in-flight work finishes inside the grace window)
                # so the autopilot can backfill before the exit lands
                self.noticed = True
                try:
                    self.notice_grace_s = float(rec.get("grace_s"))
                except (TypeError, ValueError):
                    self.notice_grace_s = None
        return out

    def assigned(self) -> List[int]:
        return list(self._assigned)

    def take_assigned(self) -> List[int]:
        rids = list(self._assigned)
        self._assigned.clear()
        return rids

    def close(self) -> None:
        self.request_exit()


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class FleetRouter:
    """SLO-aware front-end over N :class:`ReplicaHandle`\\ s (module
    docstring).  ``pump()`` is the service loop step: collect
    completions (advancing in-process replicas), requeue any dead
    replica's ledger entries, place queued work.  Single-threaded by
    design — subprocess replicas compute concurrently; the router is
    pure host bookkeeping."""

    def __init__(self, replicas: Sequence[ReplicaHandle], *,
                 queue_depth: int = 256,
                 default_slo_ms: Optional[float] = None,
                 replica_queue_cap: int = 2,
                 reject_infeasible: bool = False,
                 feasibility_margin: float = 1.5,
                 telemetry_dir: Optional[str] = None,
                 rollup_every: int = 50,
                 handoff_timeout_s: float = 5.0,
                 handoff_max_retries: int = 8,
                 wal_dir: Optional[str] = None,
                 now_fn=time.monotonic):
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("a fleet needs at least one replica")
        names = [h.name for h in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.queue_depth = int(queue_depth)
        self.default_slo_ms = default_slo_ms
        self.replica_queue_cap = int(replica_queue_cap)
        self.reject_infeasible = bool(reject_infeasible)
        self.feasibility_margin = float(feasibility_margin)
        self.now = now_fn
        self.queue: Deque[FleetRequest] = collections.deque()
        self.reqs: Dict[int, FleetRequest] = {}
        self._results: Dict[int, List[int]] = {}
        self._next_rid = 0
        self._pumps = 0
        # inter-pump queue-wait attribution (utils/goodput.py): when a
        # pump ends with requests still queued (no feasible placement),
        # the time to the next pump is router queue-wait — retro-emitted
        # as a queue_wait span so the fleet goodput ledger prices it
        self._gap_wall: Optional[float] = None
        # completions collected OUTSIDE pump() (on_replica_down drains
        # a dead handle's raced events); the next pump() surfaces them
        self._completed_backlog: List[int] = []
        # --- disaggregated-handoff ledger (DESIGN.md §11) -------------
        # rids whose committed handoff record awaits a decode replica;
        # _inflight_injects maps a dispatched-but-unacked inject to
        # (handle name, deadline) so a stall times out and retries
        self.handoff_timeout_s = float(handoff_timeout_s)
        self.handoff_max_retries = int(handoff_max_retries)
        self._handoff_queue: Deque[int] = collections.deque()
        self._inflight_injects: Dict[int, Tuple[str, float]] = {}
        self._handoff_ms = QuantileSketch()
        self.handoffs = 0            # records committed at the router
        self.handoff_retries = 0     # inject rejects + timeouts
        self.handoff_reprefills = 0  # records dropped -> full re-prefill
        self.redecodes = 0           # decode deaths recovered from record
        self.duplicates_suppressed = 0
        # degraded single-pool mode: a disagg fleet with an empty
        # prefill or decode pool serves unified until backfill
        self.degraded_dispatches = 0
        self.degraded_mode_s = 0.0
        self._degraded_since: Optional[float] = None
        # counters (the router's own rollup record reports these)
        self.routed = 0
        self.rejected = 0            # bounded-queue + infeasible rejects
        self.rejected_infeasible = 0
        self.requeued = 0
        self.completed = 0
        self.replica_deaths = 0
        self.deadline_misses = 0
        self._completed_by: Dict[str, int] = {h.name: 0
                                              for h in self.replicas}
        self._missed_by: Dict[str, int] = {h.name: 0
                                           for h in self.replicas}
        self._completed_by_gen: Dict[int, int] = {}
        # windowed per-completion samples (t, replica, generation,
        # ttft_ms, missed) for the autopilot's canary judge; bounded so
        # a long-lived router cannot grow it
        self.recent: Deque[Dict[str, Any]] = collections.deque(
            maxlen=512)
        self._was_alive: Dict[str, bool] = {h.name: True
                                            for h in self.replicas}
        # generation-aware traffic policy (serve.autopilot rollouts):
        # placement PREFERS the primary generation — or, for the
        # deterministic rid-modulo canary slice, the canary generation —
        # and falls back to any accepting replica when the preferred
        # generation has none (availability beats generation purity)
        self._primary_gen = 0
        self._canary: Optional[Tuple[int, float]] = None
        # router telemetry: same sketch/rollup shape as a replica, role
        # "router", so obs_agg renders router vs replica side by side
        self._ttft = QuantileSketch()
        self._q_gauge = Gauge()
        self.rollup_every = max(0, int(rollup_every))
        self._jsonl = None
        self._t0 = time.perf_counter()
        self._heartbeat = None
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            self._jsonl = open(os.path.join(telemetry_dir,
                                            "metrics.jsonl"), "a")
            from ..train import telemetry as telemetry_lib

            self._heartbeat = telemetry_lib.Heartbeat(os.path.join(
                telemetry_dir,
                telemetry_lib.heartbeat_filename("router")))
        # --- durable control plane (write-ahead request ledger) -------
        # with a wal_dir, every commit point (accept, assign,
        # handoff-commit, completion) is journaled BEFORE the router's
        # in-memory state moves, and construction replays whatever a
        # previous incarnation journaled — the recovery path mirrors
        # the live protocol exactly (queued work requeues, committed
        # handoff records re-inject or degrade to unified reprefills,
        # completed requests answer from the journal)
        self._wal = None
        self._idem: Dict[str, int] = {}
        self._replayed_rids: set = set()
        self.recovery: Dict[str, Any] = {
            "recovered": False, "replayed": 0, "deduped": 0,
            "converted": 0, "lost": 0, "wall_s": 0.0}
        if wal_dir:
            from .wal import WriteAheadLog

            t_wal = time.perf_counter()
            self._wal = WriteAheadLog(wal_dir)
            self._recover(self._wal.open())
            self.recovery["lost"] = (
                self._wal.report.get("quarantined_records", 0)
                + self._wal.report.get("quarantined_segments", 0))
            self.recovery["wall_s"] = round(
                time.perf_counter() - t_wal, 6)

    def _recover(self, records) -> None:
        """Rebuild the request + handoff ledgers from a replayed WAL.
        Unfinished requests re-admit exactly once IN THEIR RECORDED
        PHASE: accepted/assigned work requeues for a full re-prefill
        (its replica died with the old incarnation — the pre-commit
        recovery row), committed handoff records rejoin the handoff
        queue (re-inject, or degrade to unified reprefills when the
        decode pool never comes back — the existing recovery table),
        and completed requests restore their results so an
        idempotency-key resubmit is answered from the journal with the
        exact bytes the first incarnation delivered."""
        if not records:
            return
        now = self.now()
        order: List[int] = []
        for rec in records:
            kind = rec.get("kind")
            rid = rec.get("rid")
            if kind == "accept":
                rid = int(rid)
                req = FleetRequest(
                    rid=rid, prompt=[int(t) for t in rec["prompt"]],
                    max_new=int(rec["max_new"]),
                    slo_ms=rec.get("slo_ms"), t_submit=now,
                    deadline=(now + rec["slo_ms"] / 1e3
                              if rec.get("slo_ms") is not None
                              else math.inf))
                self.reqs[rid] = req
                order.append(rid)
                if rec.get("idem"):
                    self._idem[str(rec["idem"])] = rid
            elif kind == "handoff" and int(rid) in self.reqs:
                req = self.reqs[int(rid)]
                req.handoff = rec.get("payload")
                req.prefill_replica = rec.get("prefill")
                req.phase = "handoff_inflight"
                req.handoff_t = now
                if rec.get("ttft_ms") is not None:
                    req.ttft_ms = float(rec["ttft_ms"])
            elif kind == "complete" and int(rid) in self.reqs:
                req = self.reqs[int(rid)]
                req.t_done = now
                req.phase = "done"
                req.handoff = None
                req.ttft_ms = rec.get("ttft_ms")
                req.itl_ms = rec.get("itl_ms")
                req.generation = int(rec.get("generation", 0))
                toks = [int(t) for t in rec["tokens"]]
                self._results[req.rid] = toks
                req.n_generated = len(toks) - len(req.prompt)
                self.completed += 1
                self._completed_by_gen[req.generation] = (
                    self._completed_by_gen.get(req.generation, 0) + 1)
            # "assign" records carry no recovery action of their own:
            # the assigned replica died with the old incarnation, so an
            # assigned-but-uncommitted request recovers exactly like a
            # queued one (full re-prefill) — the same row of the table
            # a live prefill death takes
        self._next_rid = 1 + max(order, default=-1)
        for rid in order:
            req = self.reqs[rid]
            if req.t_done is not None:
                continue
            self.recovery["replayed"] += 1
            self._replayed_rids.add(rid)
            if req.handoff is not None:
                self._handoff_queue.append(rid)
            else:
                req.phase = "queued"
                req.replica = None
                self.queue.append(req)
        self.recovery["recovered"] = True
        log(f"router: recovered {len(order)} journaled requests "
            f"({self.recovery['replayed']} re-admitted, "
            f"{self.completed} already complete, "
            f"{len(self._handoff_queue)} committed handoffs)")

    # ---- client surface ------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int,
               slo_ms: Optional[float] = None,
               idem: Optional[str] = None) -> Optional[int]:
        """Enqueue at the fleet; returns the fleet rid, or None when
        admission rejects (bounded queue full, or — with
        ``reject_infeasible`` — no replica can plausibly meet the
        deadline).  Validation mirrors ``Scheduler.submit``'s loud
        refusal for never-servable requests."""
        prompt_ids = [int(t) for t in prompt_ids]
        if not prompt_ids:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens {max_new_tokens} < 1")
        if idem is not None and idem in self._idem:
            # idempotency-key dedupe (durable control plane): the
            # journal already owns this request.  Completed -> answer
            # from the journal (the rid re-surfaces on the next pump
            # with the original bytes); still in flight -> re-attach
            # the client to the live rid, never a second execution.
            rid = self._idem[idem]
            req = self.reqs.get(rid)
            if req is not None:
                self.recovery["deduped"] += 1
                if req.t_done is not None:
                    self._completed_backlog.append(rid)
                return rid
        if len(self.queue) >= self.queue_depth:
            self.rejected += 1
            return None
        slo = self.default_slo_ms if slo_ms is None else slo_ms
        now = self.now()
        deadline = now + slo / 1e3 if slo is not None else math.inf
        if (self.reject_infeasible and math.isfinite(deadline)
                and not self._any_feasible(deadline, now)):
            self.rejected += 1
            self.rejected_infeasible += 1
            return None
        rid = self._next_rid
        self._next_rid += 1
        req = FleetRequest(rid=rid, prompt=prompt_ids,
                           max_new=int(max_new_tokens), slo_ms=slo,
                           t_submit=now, deadline=deadline)
        if self._wal is not None:
            # ACCEPT commit point: journal before the queue sees it —
            # an accepted request survives the very next SIGKILL
            self._wal.append("accept", rid=rid, prompt=prompt_ids,
                             max_new=int(max_new_tokens), slo_ms=slo,
                             idem=idem)
        if idem is not None:
            self._idem[idem] = rid
        self.reqs[rid] = req
        self.queue.append(req)
        return rid

    def done(self, rid: int) -> bool:
        if rid in self._results:
            return True
        if rid in self.reqs:
            return False
        raise KeyError(f"request {rid}: unknown or already consumed")

    def result(self, rid: int) -> List[int]:
        return self._results.pop(rid)

    def stats(self, rid: int) -> FleetRequest:
        return self.reqs[rid]

    def pending(self) -> int:
        return len(self.queue)

    def in_flight(self) -> int:
        # committed handoff records awaiting a decode replica are
        # in-flight work the fleet still owes, visible nowhere else
        return (sum(len(h.assigned()) for h in self.replicas)
                + len(self._handoff_queue))

    def per_replica_completed(self) -> Dict[str, int]:
        return dict(self._completed_by)

    def per_replica_missed(self) -> Dict[str, int]:
        """Completed-past-deadline counts per replica name — the canary
        judge's per-slice SLO-burn input."""
        return dict(self._missed_by)

    def per_generation_completed(self) -> Dict[int, int]:
        """Completions per weight generation — with the flow traces'
        ``R{id}`` prefix (``id // GEN_STRIDE`` = generation), the two
        views of rollout attribution that must agree."""
        return dict(self._completed_by_gen)

    # ---- fleet membership (the autopilot's scale/rollout surface) ------
    def add_replica(self, h: ReplicaHandle,
                    generation: Optional[int] = None) -> None:
        """Register a NEW replica at runtime (scale-out, or a rollout
        spawning the next weight generation).  It receives traffic as
        soon as it reports ready; the traffic policy (:meth:`set_traffic`)
        decides which requests PREFER it."""
        if any(r.name == h.name for r in self.replicas):
            raise ValueError(f"duplicate replica name: {h.name!r}")
        if generation is not None:
            h.generation = int(generation)
        self.replicas.append(h)
        self._completed_by.setdefault(h.name, 0)
        self._missed_by.setdefault(h.name, 0)
        self._was_alive[h.name] = h.alive()

    def remove_replica(self, name: str) -> None:
        """Deregister a replica (after a decommission completes or a
        canary rolls back).  The dead handle's raced completion events
        drain first and are HONORED; anything still assigned requeues
        exactly once through the ledger.  History counters persist so
        the bench/judge can still read what the replica served."""
        for i, h in enumerate(self.replicas):
            if h.name != name:
                continue
            self.on_replica_down(name)
            del self.replicas[i]
            self._was_alive.pop(name, None)
            return
        raise KeyError(f"unknown replica {name!r}")

    def set_traffic(self, primary_generation: int,
                    canary_generation: Optional[int] = None,
                    canary_fraction: float = 0.0) -> None:
        """Generation-aware traffic shift.  ``canary_fraction`` of rids
        (a deterministic rid-modulo slice, so the split is reproducible
        and survives requeues) prefer ``canary_generation``; everything
        else prefers ``primary_generation``.  Preference, not partition:
        when no replica of the desired generation is accepting,
        placement falls back to any accepting replica — a rollout must
        never become downtime."""
        self._primary_gen = int(primary_generation)
        if canary_generation is None or canary_fraction <= 0.0:
            self._canary = None
        else:
            self._canary = (int(canary_generation),
                            min(1.0, float(canary_fraction)))

    def _desired_gen(self, req: FleetRequest) -> int:
        if self._canary is not None:
            gen, frac = self._canary
            # Knuth multiplicative hash, NOT rid % 1000 directly:
            # rids issue sequentially, so an unhashed modulo slice is a
            # PREFIX of rid space — requests submitted before the
            # canary came up, i.e. zero canary traffic.  The hash
            # spreads the slice uniformly over arrival order while
            # staying deterministic per rid (a requeued request keeps
            # its generation preference).
            if ((req.rid * 2654435761) % 1000) < int(round(frac * 1000)):
                return gen
        return self._primary_gen

    # ---- placement -----------------------------------------------------
    def _est_wait_ms(self, h: ReplicaHandle,
                     sig: Optional[LoadSignal]) -> Optional[float]:
        """Predicted time-to-first-token on ``h`` from its rollup: the
        replica's observed TTFT p50 scaled by its relative backlog.
        None = no signal yet (cold replica) — treated as feasible, the
        optimistic default that lets a fresh fleet admit its first
        requests."""
        if sig is None or sig.ttft_p50_ms is None:
            return None
        # max(), not sum: the replica's reported occupancy already
        # CONTAINS the requests the router dispatched there — adding
        # h.assigned() on top would double the predicted wait and
        # reject genuinely feasible deadlines (the same discipline as
        # _place's occupancy)
        backlog = max(sig.in_flight + sig.queue_depth,
                      len(h.assigned())) / sig.slots
        return sig.ttft_p50_ms * max(1.0, backlog)

    def _any_feasible(self, deadline: float, now: float) -> bool:
        slack_ms = (deadline - now) * 1e3
        for h in self.replicas:
            if not h.accepting():
                continue
            est = self._est_wait_ms(h, h.load())
            if est is None or est * self.feasibility_margin <= slack_ms:
                return True
        return False

    def _place(self, req: FleetRequest,
               sigs: Optional[Dict[str, Optional[LoadSignal]]] = None,
               kinds: Optional[Tuple[str, ...]] = None
               ) -> Optional[ReplicaHandle]:
        """Least-loaded placement over the live load signals, deadline
        feasibility preferred: among accepting replicas whose router-
        side backlog is under ``slots + replica_queue_cap``, pick the
        lowest (occupancy, block_utilization) — the occupancy fed by
        the replica's own reported rollup combined with what the router
        knows it has dispatched there (robust to status staleness in
        both directions)."""
        best = None
        best_key = None
        desired_gen = self._desired_gen(req)
        for h in self.replicas:
            if not h.accepting():
                continue
            if kinds is not None and role_kind(h) not in kinds:
                continue
            sig = (sigs[h.name] if sigs is not None
                   and h.name in sigs else h.load())
            n_assigned = len(h.assigned())
            slots = sig.slots if sig is not None else 1
            if n_assigned >= slots + self.replica_queue_cap:
                continue
            if sig is None:
                occ, util = n_assigned, 0.0
            else:
                occ = max(sig.occupancy,
                          n_assigned / max(1, sig.slots))
                util = sig.block_utilization
            feasible = True
            if math.isfinite(req.deadline):
                est = self._est_wait_ms(h, sig)
                slack_ms = (req.deadline - self.now()) * 1e3
                feasible = (est is None
                            or est * self.feasibility_margin
                            <= slack_ms)
            # generation preference ranks BELOW feasibility (a rollout
            # must not turn deadlines into misses) and ABOVE load (the
            # canary slice really lands on the canary when it can)
            off_gen = getattr(h, "generation", 0) != desired_gen
            key = (not feasible, off_gen, occ, util, h.name)
            if best_key is None or key < best_key:
                best, best_key = h, key
        return best

    # ---- the service loop ----------------------------------------------
    def pump(self) -> List[int]:
        """One router pass; returns fleet rids completed during it."""
        from ..train import trace as trace_lib

        self._pumps += 1
        tracer = trace_lib.active()
        if tracer is not None and self._gap_wall is not None:
            gap = time.time() - self._gap_wall
            if gap >= 1e-4:
                tracer.record_span("queue_wait", self._gap_wall, gap,
                                   {"pump": self._pumps, "router": True})
        done_now: List[int] = self._completed_backlog
        self._completed_backlog = []
        for h in self.replicas:
            # death detection BEFORE pumping: a dead handle's last
            # events still drain (completions that raced the death are
            # honored, not re-run)
            alive = h.alive()
            for rec in h.pump():
                ev = rec.get("ev")
                if ev == "reject":
                    if rec.get("inject"):
                        self._handoff_failed(int(rec["rid"]), h.name)
                    else:
                        self._requeue_one(int(rec["rid"]), h.name)
                    continue
                if ev == "handoff":
                    self._on_handoff(h, rec)
                    continue
                if ev == "injected":
                    self._on_injected(h, int(rec["rid"]))
                    continue
                prev = self.reqs.get(int(rec["rid"]))
                if prev is not None and prev.t_done is not None:
                    # a timed-out inject that was actually alive can
                    # complete AFTER its re-dispatch did: exactly-once
                    # delivery means the second result is dropped here
                    self.duplicates_suppressed += 1
                    continue
                done_now.append(self._complete(h, rec))
            if not alive and self._was_alive.get(h.name, True):
                self._on_death(h)
            self._was_alive[h.name] = alive
        self._check_handoff_timeouts()
        self._update_degraded()
        self._dispatch_handoffs()
        self._dispatch()
        if self._heartbeat is not None:
            self._heartbeat.beat(self._pumps, None)
        if (self._jsonl is not None and self.rollup_every
                and self._pumps % self.rollup_every == 0):
            self._write_rollup()
        # requests still queued after dispatch = the next inter-pump gap
        # is queue-wait, not idle (see __init__)
        self._gap_wall = time.time() if self.queue else None
        return done_now

    def _pool_health(self) -> Tuple[bool, bool, bool]:
        """(disagg, prefill_ok, decode_ok): whether the fleet has role
        pools at all, and whether each duty has an accepting replica
        (unified replicas count for both)."""
        disagg = any(role_kind(h) in ("prefill", "decode")
                     for h in self.replicas)
        prefill_ok = decode_ok = False
        for h in self.replicas:
            if not h.accepting():
                continue
            kind = role_kind(h)
            prefill_ok = prefill_ok or kind in ("unified", "prefill")
            decode_ok = decode_ok or kind in ("unified", "decode")
        return disagg, prefill_ok, decode_ok

    def _update_degraded(self) -> None:
        """Track wall-clock spent with a missing pool.  Degraded is a
        MODE, not an error: traffic keeps flowing unified while the
        autopilot backfills the empty pool."""
        disagg, prefill_ok, decode_ok = self._pool_health()
        # XOR on purpose: one empty pool = degraded single-pool serving;
        # BOTH empty (startup compile window, total outage) is an
        # availability gap, not a serving mode
        degraded = disagg and (prefill_ok != decode_ok)
        if degraded and self._degraded_since is None:
            self._degraded_since = self.now()
            log(f"fleet: degraded single-pool mode "
                f"(prefill_ok={prefill_ok} decode_ok={decode_ok}) — "
                f"serving unified until backfill")
        elif not degraded and self._degraded_since is not None:
            self.degraded_mode_s += self.now() - self._degraded_since
            self._degraded_since = None
            log("fleet: both role pools healthy — degraded mode over")

    def _dispatch(self) -> None:
        # load signals fetched ONCE per pass: an InprocReplica's load()
        # serializes + re-parses its whole sketch state, and the signal
        # cannot change between consecutive placements within one pass
        # (the router-side assigned() count, which does, is read live)
        sigs = {h.name: h.load() for h in self.replicas
                if h.accepting()}
        disagg, prefill_ok, decode_ok = self._pool_health()
        while self.queue:
            req = self.queue[0]
            if not disagg:
                req.unified = False
                h = self._place(req, sigs)
            elif prefill_ok:
                # healthy prefill duty; unified pins end-to-end service
                # when there is no decode pool to hand off to
                req.unified = not decode_ok
                h = self._place(req, sigs, kinds=("unified", "prefill"))
            else:
                # no prefill-capable replica: the decode pool serves
                # end-to-end rather than stranding traffic
                req.unified = True
                h = self._place(req, sigs, kinds=("unified", "decode"))
            if h is None:
                return
            if not h.submit(req):
                # refused at the wire (filled up / died this instant):
                # try the next candidate on the next pump
                return
            self.queue.popleft()
            req.replica = h.name
            req.t_dispatch = self.now()
            req.phase = ("decoding" if req.unified or not disagg
                         or role_kind(h) != "prefill" else "prefilling")
            if self._wal is not None:
                # ASSIGN commit point: recovery treats assigned-but-
                # uncommitted exactly like queued (the replica dies
                # with the incarnation), so the record is provenance —
                # which replica owed this request when the lights went
                # out — not a distinct replay phase
                self._wal.append("assign", rid=req.rid, replica=h.name,
                                 phase=req.phase)
            if disagg and req.unified:
                self.degraded_dispatches += 1
            self.routed += 1

    # ---- the handoff ledger (DESIGN.md §11) ----------------------------
    def _on_handoff(self, h: ReplicaHandle, rec: Dict[str, Any]) -> None:
        """COMMIT: the prefill replica exported the stream and the
        router received the record.  From here the payload — block
        contents, block table, first sampled token — lives in the
        ledger, so a decode death re-decodes from it without repaying
        prefill."""
        rid = int(rec["rid"])
        req = self.reqs.get(rid)
        if req is None or req.t_done is not None:
            return
        req.handoff = rec.get("payload")
        req.prefill_replica = h.name
        req.replica = None
        req.phase = "handoff_inflight"
        req.handoff_t = self.now()
        req.handoff_next_t = 0.0
        # fleet-level TTFT is owned by the PREFILL side (the first
        # token was sampled there); the decode side only prices ITL
        if rec.get("ttft_ms") is not None:
            wait_ms = ((req.t_dispatch or req.t_submit)
                       - req.t_submit) * 1e3
            req.ttft_ms = wait_ms + float(rec["ttft_ms"])
        if self._wal is not None:
            # HANDOFF-COMMIT point: the exported payload itself is
            # journaled — after a full-fleet SIGKILL the next
            # incarnation re-injects from the journal without repaying
            # prefill, the same row a live decode death takes
            self._wal.append("handoff", rid=rid, payload=req.handoff,
                             prefill=h.name, ttft_ms=req.ttft_ms)
        self.handoffs += 1
        self._handoff_queue.append(rid)

    def _on_injected(self, h: ReplicaHandle, rid: int) -> None:
        req = self.reqs.get(rid)
        if req is None:
            return
        self._inflight_injects.pop(rid, None)
        req.phase = "decoding"
        req.replica = h.name
        if req.handoff_t is not None and req.handoff_ms is None:
            req.handoff_ms = (self.now() - req.handoff_t) * 1e3
            self._handoff_ms.add(req.handoff_ms)

    def _handoff_failed(self, rid: int, from_name: str) -> None:
        """An inject was rejected, timed out, or its target died before
        acking: retry with deterministic jittered backoff; after
        ``handoff_max_retries`` the record is dropped and the request
        re-prefills from scratch (the one path that repays prefill)."""
        req = self.reqs.get(rid)
        if req is None or req.t_done is not None:
            return
        self._inflight_injects.pop(rid, None)
        req.replica = None
        req.handoff_retries += 1
        self.handoff_retries += 1
        if req.handoff is None or (req.handoff_retries
                                   > self.handoff_max_retries):
            req.handoff = None
            req.handoff_t = None
            req.phase = "queued"
            self.handoff_reprefills += 1
            self._requeue_one(rid, from_name)
            return
        # deterministic jitter (same discipline as the canary slice:
        # hash the rid, don't consult a clock-seeded RNG) so chaos arms
        # replay identically
        base = min(2.0, 0.05 * (2 ** (req.handoff_retries - 1)))
        jitter = ((rid * 2654435761 + req.handoff_retries * 40503)
                  % 1000) / 1000.0
        req.handoff_next_t = self.now() + base * (0.5 + jitter)
        req.phase = "handoff_inflight"
        if rid not in self._handoff_queue:
            self._handoff_queue.append(rid)

    def _check_handoff_timeouts(self) -> None:
        now = self.now()
        for rid, (name, deadline) in list(self._inflight_injects.items()):
            if now < deadline:
                continue
            # re-own the record BEFORE re-dispatch: the stalled worker
            # must not surface this rid as assigned work anymore (a
            # late completion is suppressed as a duplicate)
            for h in self.replicas:
                if h.name == name:
                    h.forget(rid)
                    break
            self._handoff_failed(rid, name)

    def _place_inject(self, req: FleetRequest) -> Optional[ReplicaHandle]:
        """Least-loaded inject placement: decode pool preferred,
        unified replicas as fallback, prefill replicas never (the whole
        point is taking decode work OFF them)."""
        best = None
        best_key = None
        for h in self.replicas:
            if not h.accepting() or not h.can_inject():
                continue
            kind = role_kind(h)
            if kind == "prefill":
                continue
            sig = h.load()
            n_assigned = len(h.assigned())
            slots = sig.slots if sig is not None else 1
            if n_assigned >= slots + self.replica_queue_cap:
                continue
            if sig is None:
                occ, util = float(n_assigned), 0.0
            else:
                occ = max(sig.occupancy, n_assigned / max(1, sig.slots))
                util = sig.block_utilization
            key = (kind != "decode", occ, util, h.name)
            if best_key is None or key < best_key:
                best, best_key = h, key
        return best

    def _dispatch_handoffs(self) -> None:
        now = self.now()
        disagg, prefill_ok, decode_ok = self._pool_health()
        if disagg and prefill_ok and not decode_ok:
            # the decode DUTY is gone (pool dead or drained, no unified
            # fallback): a committed record has no target and waiting
            # is a hang, not a recovery.  Degrade the records the same
            # way queued traffic degrades — drop to a unified requeue
            # (re-prefill, the one path that repays prefill) on the
            # surviving pool.  A transient relaunch window pays one
            # extra prefill per in-flight record; tokens are unchanged
            # (greedy re-execution), and the reprefill is COUNTED.
            for _ in range(len(self._handoff_queue)):
                rid = self._handoff_queue.popleft()
                req = self.reqs.get(rid)
                if (req is None or req.t_done is not None
                        or req.handoff is None):
                    continue
                req.handoff = None
                req.handoff_t = None
                req.phase = "queued"
                self.handoff_reprefills += 1
                if rid in self._replayed_rids:
                    # a journaled handoff record whose decode pool
                    # never came back: converted to a unified
                    # reprefill, the recovery table's last row
                    self.recovery["converted"] += 1
                    self._replayed_rids.discard(rid)
                self._requeue_one(rid, req.prefill_replica or "?")
            return
        for _ in range(len(self._handoff_queue)):
            rid = self._handoff_queue.popleft()
            req = self.reqs.get(rid)
            if req is None or req.t_done is not None or req.handoff is None:
                continue
            if now < req.handoff_next_t:
                self._handoff_queue.append(rid)
                continue
            h = self._place_inject(req)
            if h is None or not h.inject(req, req.handoff):
                # no decode-capable target right now: keep the record;
                # timeout/retry accounting only starts at dispatch
                self._handoff_queue.append(rid)
                continue
            req.replica = h.name
            self._inflight_injects[rid] = (
                h.name, now + self.handoff_timeout_s)

    def _complete(self, h: ReplicaHandle, rec: Dict[str, Any]) -> int:
        rid = int(rec["rid"])
        req = self.reqs[rid]
        req.t_done = self.now()
        if req.handoff is None and req.ttft_ms is None:
            # unified path: the serving replica owns TTFT.  Handed-off
            # requests already composed router wait + prefill TTFT at
            # commit; the decode side's "ttft_ms" is inject latency,
            # not a user-visible first token.
            wait_ms = ((req.t_dispatch or req.t_submit)
                       - req.t_submit) * 1e3
            req.ttft_ms = (wait_ms + float(rec["ttft_ms"])
                           if rec.get("ttft_ms") is not None else wait_ms)
        req.itl_ms = rec.get("itl_ms")
        req.handoff = None             # record retired: exactly-once
        req.phase = "done"
        self._inflight_injects.pop(rid, None)
        toks = [int(t) for t in rec["tokens"]]
        self._results[rid] = toks
        req.n_generated = len(toks) - len(req.prompt)
        req.generation = getattr(h, "generation", 0)
        if self._wal is not None:
            # COMPLETION commit point: tokens ride the record so a
            # post-restart idempotency-key resubmit is answered with
            # the exact bytes this delivery carried
            self._wal.append("complete", rid=rid, tokens=toks,
                             ttft_ms=req.ttft_ms, itl_ms=req.itl_ms,
                             generation=req.generation)
        self.completed += 1
        self._completed_by[h.name] = (
            self._completed_by.get(h.name, 0) + 1)
        self._completed_by_gen[req.generation] = (
            self._completed_by_gen.get(req.generation, 0) + 1)
        if req.deadline_missed:
            self.deadline_misses += 1
            self._missed_by[h.name] = self._missed_by.get(h.name, 0) + 1
        if req.ttft_ms is not None:
            self._ttft.add(req.ttft_ms)
        # bounded recent-completions window: the autopilot's canary
        # judge needs WINDOWED per-generation latency, which a lifetime
        # sketch cannot answer (a fresh replica's first-compile TTFTs
        # would dominate its p50 forever)
        self.recent.append({
            "t": req.t_done, "replica": h.name,
            "generation": req.generation, "ttft_ms": req.ttft_ms,
            "missed": bool(req.deadline_missed)})
        return rid

    def _requeue_one(self, rid: int, from_name: str) -> None:
        req = self.reqs.get(rid)
        if req is None or rid in self._results:
            return
        req.requeues += 1
        req.replica = None
        req.t_dispatch = None
        self.requeued += 1
        # FRONT of the queue, original submission order among requeued
        # peers: the oldest obligation keeps its place — no starvation
        pos = 0
        while (pos < len(self.queue)
               and self.queue[pos].t_submit <= req.t_submit
               and self.queue[pos].requeues > 0):
            pos += 1
        self.queue.insert(pos, req)

    def _on_death(self, h: ReplicaHandle) -> None:
        self.replica_deaths += 1
        rids = h.take_assigned()
        # requeue in original submission order so insert-at-front
        # preserves it
        for rid in sorted(rids,
                          key=lambda r: (self.reqs[r].t_submit, r),
                          reverse=True):
            req = self.reqs.get(rid)
            if (req is not None and req.t_done is None
                    and req.handoff is not None):
                # decode death AFTER commit: the ledger still holds the
                # exported blocks + first token, so this is a re-decode,
                # not a re-prefill — prefill is not repaid
                self._inflight_injects.pop(rid, None)
                req.replica = None
                req.phase = "handoff_inflight"
                self.redecodes += 1
                if rid not in self._handoff_queue:
                    self._handoff_queue.appendleft(rid)
                continue
            self._requeue_one(rid, h.name)
        if getattr(h, "drained", None):
            # a gracefully drained replica reported its consumed-token
            # state; the ledger already holds these requests — the
            # report is observability, not a second source of truth
            h.drained = None

    def on_replica_down(self, name: str) -> None:
        """External death notice (the fleet supervisor's exit event) —
        idempotent with pump()'s own detection.  Drains the dead
        handle's pending events FIRST: a completion that raced the
        death must be honored (surfaced via the next pump()), never
        requeued into a duplicate execution."""
        for h in self.replicas:
            if h.name != name:
                continue
            for rec in h.pump():
                ev = rec.get("ev")
                if ev == "reject":
                    if rec.get("inject"):
                        self._handoff_failed(int(rec["rid"]), h.name)
                    else:
                        self._requeue_one(int(rec["rid"]), h.name)
                elif ev == "handoff":
                    # a commit that raced the death is a commit: the
                    # record reached the router, decode proceeds
                    self._on_handoff(h, rec)
                elif ev == "injected":
                    self._on_injected(h, int(rec["rid"]))
                else:
                    prev = self.reqs.get(int(rec["rid"]))
                    if prev is not None and prev.t_done is not None:
                        self.duplicates_suppressed += 1
                    else:
                        self._completed_backlog.append(
                            self._complete(h, rec))
            if h.assigned():
                self._on_death(h)
            self._was_alive[name] = False

    # ---- telemetry -----------------------------------------------------
    def load_report(self) -> Dict[str, Any]:
        """The router's own rollup record (role="router") — same
        serialized-sketch shape as a replica's, so the fleet aggregator
        renders router-observed TTFT next to replica-observed TTFT."""
        from ..train import trace as trace_lib

        # cached: a fabricated per-call run id would split this router
        # into N aggregator "writers" whose cumulative counters then
        # double-count (see _ServeTelemetry.rollup_record)
        if not hasattr(self, "_ident"):
            self._ident = trace_lib.run_identity()
        ident = self._ident
        self._q_gauge.set(len(self.queue))
        return {
            "kind": "rollup", "role": "router", "step": self._pumps,
            "t": round(time.perf_counter() - self._t0, 6),
            "t_unix": round(time.time(), 3),
            "p": ident["process_id"], "run": ident["run_id"],
            "inc": ident["incarnation"],
            "sketches": {k: s.to_dict()
                         for k, s in (("ttft_ms", self._ttft),
                                      ("handoff_ms", self._handoff_ms))
                         if s.n},
            "counters": {"routed": self.routed,
                         "rejected": self.rejected,
                         "rejected_infeasible": self.rejected_infeasible,
                         "requeued": self.requeued,
                         "completed": self.completed,
                         "replica_deaths": self.replica_deaths,
                         "deadline_misses": self.deadline_misses,
                         "handoffs": self.handoffs,
                         "handoff_retries": self.handoff_retries,
                         "handoff_reprefills": self.handoff_reprefills,
                         "redecodes": self.redecodes,
                         "degraded_dispatches": self.degraded_dispatches,
                         "duplicates_suppressed":
                             self.duplicates_suppressed,
                         "recovery_replayed": self.recovery["replayed"],
                         "recovery_deduped": self.recovery["deduped"],
                         "recovery_converted":
                             self.recovery["converted"],
                         "recovery_lost": self.recovery["lost"]},
            "gauges": {"queue_depth": self._q_gauge.to_dict()},
            "now": {"queue_depth": len(self.queue),
                    "in_flight": self.in_flight(),
                    "handoff_queue": len(self._handoff_queue),
                    # rebuilt-from-journal state is DISCLOSED, not
                    # passed off as organic history: the autopilot and
                    # the aggregator can tell a post-recovery rollup
                    # from a first-life one
                    "post_recovery": bool(self.recovery["recovered"]),
                    "degraded": self._degraded_since is not None,
                    "degraded_mode_s": round(self.degraded_mode_s
                                             + ((self.now()
                                                 - self._degraded_since)
                                                if self._degraded_since
                                                is not None else 0.0), 6)},
        }

    def handoff_stats(self) -> Dict[str, Any]:
        """The bench's one-call view of the handoff ledger."""
        return {
            "handoffs": self.handoffs,
            "handoff_ms_p50": self._handoff_ms.quantile(0.5),
            "handoff_ms_p99": self._handoff_ms.quantile(0.99),
            "handoff_retries": self.handoff_retries,
            "handoff_reprefills": self.handoff_reprefills,
            "redecodes": self.redecodes,
            "degraded_dispatches": self.degraded_dispatches,
            "degraded_mode_s": round(self.degraded_mode_s, 6),
            "duplicates_suppressed": self.duplicates_suppressed,
            "recovery": dict(self.recovery),
        }

    def _write_rollup(self) -> None:
        try:
            self._jsonl.write(json.dumps(self.load_report()) + "\n")
            self._jsonl.flush()
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        if self._degraded_since is not None:
            self.degraded_mode_s += self.now() - self._degraded_since
            self._degraded_since = None
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        if self._jsonl is not None:
            self._write_rollup()
            if self._heartbeat is not None:
                self._heartbeat.beat(self._pumps, None, force=True,
                                     final=True)
            self._jsonl.close()
            self._jsonl = None


# ---------------------------------------------------------------------------
# fleet assembly (subprocess replicas under the group supervisor)
# ---------------------------------------------------------------------------

def worker_cmd(python: str, *, replica: int, model: Dict[str, Any],
               serve: Dict[str, Any], telemetry_dir: Optional[str],
               status_every: int = 5, step_sleep_ms: float = 0.0,
               tp: int = 0, crash_at_request: int = 0,
               prewarm: bool = False, generation: int = 0,
               ckpt: Optional[str] = None,
               faults: Optional[str] = None) -> List[str]:
    """The replica worker command line (see :func:`worker_main`)."""
    cmd = [python, "-m",
           "neural_networks_parallel_training_with_mpi_tpu.serve"
           "._fleet_worker",
           "--worker", "--replica", str(int(replica))]
    for k, v in model.items():
        cmd += [f"--{k.replace('_', '-')}", str(v)]
    for k, v in serve.items():
        if isinstance(v, bool):
            if v:
                cmd += [f"--{k.replace('_', '-')}"]
        elif v is not None:
            cmd += [f"--{k.replace('_', '-')}", str(v)]
    if telemetry_dir:
        cmd += ["--telemetry-dir", telemetry_dir]
    cmd += ["--status-every", str(int(status_every))]
    if step_sleep_ms:
        cmd += ["--step-sleep-ms", str(float(step_sleep_ms))]
    if tp:
        cmd += ["--tp", str(int(tp))]
    if crash_at_request:
        cmd += ["--crash-at-request", str(int(crash_at_request))]
    if prewarm:
        cmd += ["--prewarm"]
    if generation:
        cmd += ["--generation", str(int(generation))]
    if ckpt:
        cmd += ["--ckpt", str(ckpt)]
    if faults:
        cmd += ["--faults", str(faults)]
    return cmd


def _spawn_replica(cfg: Dict[str, Any], k: int, *, generation: int = 0,
                   ckpt: Optional[str] = None,
                   faults: Optional[str] = None,
                   step_sleep_ms: Optional[float] = None,
                   crash_at_request: int = 0,
                   role: Optional[str] = None):
    """Build one subprocess replica's (handle, ChildSpec, telemetry dir)
    from a fleet spawn config — the per-replica constructor shared by
    :func:`launch_fleet` and :meth:`Fleet.add_replica` (the autopilot's
    scale-out / rollout path).  Generation-g replicas get the strided id
    ``g * GEN_STRIDE + k`` (flow-trace/telemetry attribution, module
    header)."""
    import subprocess

    from ..train.resilience import PREEMPT_NOTICE_ENV, ChildSpec

    rid = int(generation) * GEN_STRIDE + int(k)
    name = f"replica-{rid}"
    tdir = (os.path.join(cfg["telemetry_root"], name)
            if cfg["telemetry_root"] else None)
    serve = dict(cfg["serve"])
    if role is not None:
        serve["role"] = role
    srole = str(serve.get("role") or "unified")
    handle = ProcReplica(
        name=name,
        role=("replica" if srole == "unified" else srole),
        generation=generation)
    cmd = worker_cmd(
        cfg["python"], replica=rid, model=cfg["model"],
        serve=serve, telemetry_dir=tdir,
        status_every=cfg["status_every"],
        step_sleep_ms=(cfg["step_sleep_ms"] if step_sleep_ms is None
                       else step_sleep_ms),
        tp=cfg["tp"], crash_at_request=crash_at_request,
        prewarm=cfg["prewarm"], generation=generation, ckpt=ckpt,
        faults=faults)
    env = {"NNPT_PROCESS_ID": str(rid),
           "PYTHONPATH": cfg["repo_root"] + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    # the advance-notice file channel (train.resilience): both ends of
    # GroupSupervisor.notify_preempt agree on this path.  Without it the
    # signal still delivers but the grace window falls back to the 2 s
    # default, so a telemetry-less fleet gets a tempdir path instead.
    env[PREEMPT_NOTICE_ENV] = (
        os.path.join(tdir, "preempt-notice.json") if tdir
        else os.path.join(tempfile.gettempdir(),
                          f"nnpt-preempt-{os.getpid()}-{rid}.json"))

    def spawn(spec, env, _cmd=cmd):
        return subprocess.Popen(
            _cmd, env=env, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, text=True, bufsize=1)

    def on_spawn(spec, proc, inc, _h=handle):
        _h.attach(proc, inc)

    spec = ChildSpec(
        name=name, cmd=cmd,
        role=("serve-replica" if srole == "unified"
              else f"serve-{srole}"),
        env=env,
        max_restarts=cfg["max_restarts"], backoff=cfg["backoff"],
        backoff_cap=cfg["backoff_cap"],
        heartbeat_path=(os.path.join(
            tdir, f"heartbeat-serve-p{rid}.json") if tdir else None),
        heartbeat_timeout=cfg["heartbeat_timeout"],
        spawn=spawn, on_spawn=on_spawn)
    return handle, spec, tdir


@dataclass
class Fleet:
    """A running fleet: the router, its subprocess replica handles, and
    the group supervisor babysitting them.  ``pump()`` is the whole
    service loop from the owner's side: supervisor events (exits →
    router requeue; relaunches re-attach through ``on_spawn``) then one
    router pass."""
    router: FleetRouter
    supervisor: Any
    handles: List[ProcReplica]
    telemetry_dirs: List[str] = field(default_factory=list)
    events: List[dict] = field(default_factory=list)
    spawn_cfg: Optional[Dict[str, Any]] = None   # launch_fleet's recipe,
    #   so add_replica can scale out / spawn generations at runtime
    autopilot: Any = None    # attached control loop, ticked from pump()
    _next_index: int = 0     # next per-generation replica index k

    def pump(self) -> List[int]:
        for e in self.supervisor.poll():
            self.events.append(e)
            if e["event"] in ("exit", "hang_kill"):
                self.router.on_replica_down(e["child"])
        done = self.router.pump()
        if self.autopilot is not None:
            # the control loop rides the service loop: no extra thread,
            # so its steady-state cost is visible (and priced) in the
            # same tokens/s the fleet reports (bench --autopilot)
            self.autopilot.tick()
        return done

    # client surface: a Fleet IS a router whose replicas happen to be
    # supervised subprocesses — load drivers (serve.loadgen.
    # run_fleet_closed_loop) work on either unchanged
    def submit(self, prompt_ids, max_new_tokens: int,
               slo_ms: Optional[float] = None,
               idem: Optional[str] = None) -> Optional[int]:
        return self.router.submit(prompt_ids, max_new_tokens,
                                  slo_ms=slo_ms, idem=idem)

    def result(self, rid: int) -> List[int]:
        return self.router.result(rid)

    def stats(self, rid: int) -> FleetRequest:
        return self.router.stats(rid)

    def done(self, rid: int) -> bool:
        return self.router.done(rid)

    def per_replica_completed(self) -> Dict[str, int]:
        return self.router.per_replica_completed()

    @property
    def rejected(self) -> int:
        return self.router.rejected

    @property
    def requeued(self) -> int:
        return self.router.requeued

    # ---- runtime membership (the autopilot's actuation surface) --------
    def add_replica(self, *, generation: int = 0,
                    ckpt: Optional[str] = None,
                    faults: Optional[str] = None,
                    step_sleep_ms: Optional[float] = None,
                    role: Optional[str] = None
                    ) -> ProcReplica:
        """Spawn ONE new supervised replica at runtime from the stored
        launch recipe: scale-out (same generation) or a rollout spawning
        ``generation`` from a verified weight snapshot (``ckpt``).  The
        replica starts taking traffic when its ready event lands;
        ``faults`` injects the fleet fault kinds (utils/faults.py) into
        just this worker; ``role`` overrides the recipe's serving role
        (the autopilot backfills a dead prefill pool with
        ``role="prefill"``, not whatever the recipe says)."""
        if self.spawn_cfg is None:
            raise RuntimeError(
                "this Fleet was not built by launch_fleet (no spawn "
                "config to scale out from)")
        k = self._next_index
        self._next_index += 1
        handle, spec, tdir = _spawn_replica(
            self.spawn_cfg, k, generation=generation, ckpt=ckpt,
            faults=faults, step_sleep_ms=step_sleep_ms, role=role)
        self.handles.append(handle)
        if tdir:
            self.telemetry_dirs.append(tdir)
        self.supervisor.add_child(spec)    # launches immediately
        self.router.add_replica(handle, generation=generation)
        return handle

    def decommission(self, name: str) -> bool:
        """Begin intentional removal: retire the child at the supervisor
        (its next exit is terminal — no relaunch, no budget burn), then
        ask the worker to drain and exit 47.  Returns whether the
        decommission op reached the worker's pipe; the caller watches
        :meth:`replica_done` and escalates to :meth:`force_kill` if the
        drain stalls."""
        self.supervisor.retire(name)
        for h in self.handles:
            if h.name == name:
                return h.request_decommission()
        return False

    def notify_preempt(self, name: str, grace_s: float = 2.0) -> bool:
        """Deliver an advance preemption notice to one replica (the
        real-world seam: SIGUSR1 + the notice file, via
        ``GroupSupervisor.notify_preempt``).  The worker answers by
        closing admission, finishing in-flight work inside the grace
        window, and exiting 47 — terminal at the supervisor without a
        retire (47 is in the no-retry contract), and the autopilot
        backfills when it pumps the ``preempt_notice`` event."""
        return self.supervisor.notify_preempt(name, grace_s=grace_s)

    def force_kill(self, name: str) -> None:
        """Stalled-drain escalation: SIGKILL the (already retired)
        child.  The router's ledger requeues its in-flight work exactly
        once; the retirement keeps the supervisor from relaunching it."""
        proc = self.supervisor.proc(name)
        if proc is not None and proc.poll() is None:
            proc.kill()

    def replica_done(self, name: str) -> Optional[int]:
        """Final exit code once the child will never run again (None
        while it is still alive or could relaunch)."""
        return self.supervisor.done(name)

    def remove_replica(self, name: str) -> None:
        """Forget a terminal replica: router deregistration (raced
        completions honored, leftovers requeued once) + supervisor
        bookkeeping cleanup + handle removal."""
        self.router.remove_replica(name)
        try:
            self.supervisor.remove_child(name)
        except (KeyError, ValueError):
            pass
        self.handles = [h for h in self.handles if h.name != name]

    def wait_ready(self, timeout_s: float = 180.0) -> None:
        """Block until every replica has compiled + reported ready (or
        been given up on by the supervisor)."""
        t0 = time.time()
        while time.time() - t0 < timeout_s:
            self.pump()
            pending = [h.name for h in self.handles
                       if not h.ready
                       and self.supervisor.done(h.name) is None]
            if not pending:
                return
            time.sleep(0.05)
        raise TimeoutError(f"replicas never became ready: {pending}")

    def close(self) -> None:
        for h in self.handles:
            h.request_exit()
        deadline = time.time() + 5.0
        while time.time() < deadline and any(
                h.alive() for h in self.handles):
            self.supervisor.poll()
            time.sleep(0.05)
        self.supervisor.terminate_all()
        self.router.close()


def launch_fleet(n_replicas: int, *, model: Dict[str, Any],
                 serve: Dict[str, Any],
                 telemetry_root: Optional[str] = None,
                 router_kwargs: Optional[Dict[str, Any]] = None,
                 status_every: int = 5, step_sleep_ms: float = 0.0,
                 tp: int = 0, max_restarts: int = 2,
                 backoff: float = 0.5, backoff_cap: float = 10.0,
                 heartbeat_timeout: float = 0.0,
                 crash_at_request: int = 0,
                 prewarm: bool = False,
                 python: Optional[str] = None,
                 roles: Optional[Sequence[Optional[str]]] = None,
                 log=None) -> Fleet:
    """Assemble a subprocess fleet: N workers (each its own jax
    runtime) under a :class:`train.resilience.GroupSupervisor`, wired
    into a :class:`FleetRouter`.  ``model``/``serve`` are the worker's
    geometry flags (:func:`worker_cmd`); every replica gets its own
    telemetry dir under ``telemetry_root`` (``replica-K/``) and a
    distinct ``NNPT_PROCESS_ID`` so heartbeats, rollup identities and
    flow-trace ids never collide (tools/obs_agg.py merges the dirs).
    ``roles`` (optional, one entry per replica, e.g. ``["prefill",
    "decode", "decode"]``) builds a DISAGGREGATED fleet: each entry
    overrides the serve config's role for that replica; None entries
    keep the recipe's role."""
    from ..train.resilience import GroupSupervisor

    python = python or sys.executable
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    cfg = dict(python=python, model=dict(model), serve=dict(serve),
               telemetry_root=telemetry_root, status_every=status_every,
               step_sleep_ms=step_sleep_ms, tp=tp,
               max_restarts=max_restarts, backoff=backoff,
               backoff_cap=backoff_cap,
               heartbeat_timeout=heartbeat_timeout, prewarm=prewarm,
               repo_root=repo_root)
    handles: List[ProcReplica] = []
    specs = []
    tdirs: List[str] = []
    if roles is not None and len(roles) != int(n_replicas):
        raise ValueError(
            f"roles has {len(roles)} entries for {n_replicas} replicas")
    for k in range(int(n_replicas)):
        handle, spec, tdir = _spawn_replica(
            cfg, k, crash_at_request=(crash_at_request
                                      if k == 0 else 0),
            role=(roles[k] if roles is not None else None))
        handles.append(handle)
        specs.append(spec)
        tdirs.append(tdir)
    sup = GroupSupervisor(specs, log=log)
    router_tdir = (os.path.join(telemetry_root, "router")
                   if telemetry_root else None)
    router = FleetRouter(handles, telemetry_dir=router_tdir,
                         **(router_kwargs or {}))
    fleet = Fleet(router=router, supervisor=sup, handles=handles,
                  telemetry_dirs=[d for d in tdirs if d]
                  + ([router_tdir] if router_tdir else []),
                  spawn_cfg=cfg, _next_index=int(n_replicas))
    sup.start()
    return fleet


# ---------------------------------------------------------------------------
# the replica worker process
# ---------------------------------------------------------------------------

def _worker_argparser():
    import argparse

    ap = argparse.ArgumentParser(
        prog="serve.fleet --worker",
        description="one serving replica speaking the fleet pipe "
                    "protocol on stdio")
    ap.add_argument("--worker", action="store_true", required=True)
    ap.add_argument("--replica", type=int, default=0)
    # model geometry (replicas must agree bit-for-bit: same flags ->
    # same init -> same params -> identical greedy tokens anywhere)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--d-ff", type=int, default=128)
    ap.add_argument("--init-seed", type=int, default=0)
    # serve geometry
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--num-blocks", type=int, default=0,
                    help="0 = a non-starved pool for slots x max_len")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--queue-depth", type=int, default=64)
    ap.add_argument("--attn-impl", default="gathered")
    ap.add_argument("--prefix-cache", action="store_true")
    ap.add_argument("--kv-quant", action="store_true")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--role", default="unified",
                    choices=("unified", "prefill", "decode"),
                    help="serving role (DESIGN.md §11): prefill "
                         "replicas export streams at the prefill->"
                         "decode boundary as handoff events; decode "
                         "replicas admit them via the inject op; "
                         "unified serves end-to-end")
    # fleet plumbing
    ap.add_argument("--telemetry-dir", default=None)
    ap.add_argument("--status-every", type=int, default=5,
                    help="ticks between status (load-report) events")
    ap.add_argument("--step-sleep-ms", type=float, default=0.0,
                    help="emulated device latency added per decode "
                         "tick (bench.py --serve-fleet: on a CPU-only "
                         "host this stands in for the accelerator step "
                         "the host would overlap; disclosed in the "
                         "artifact)")
    ap.add_argument("--tp", type=int, default=0,
                    help="span this replica over a tensor-parallel "
                         "mesh of N local (virtual) devices through "
                         "generate_tp (0 = single-device paged "
                         "scheduler)")
    ap.add_argument("--crash-at-request", type=int, default=0,
                    help="fault injection: os._exit(17) when the Nth "
                         "submit arrives (chaos tests / example 23)")
    ap.add_argument("--generation", type=int, default=0,
                    help="weight generation this replica serves "
                         "(stamped into ready/status events; the "
                         "replica id already encodes it as "
                         "id // GEN_STRIDE)")
    ap.add_argument("--ckpt", default=None,
                    help="load params from this weight snapshot dir "
                         "(serve.autopilot.save_weight_snapshot "
                         "layout); manifest-verified before use — any "
                         "integrity/shape failure exits EXIT_ANOMALY "
                         "(44, deterministic no-retry), which is what "
                         "drives a canary rollback")
    ap.add_argument("--faults", default=None,
                    help="utils/faults.py spec for the FLEET kinds "
                         "(replica_kill@N, stall_drain@N-M); the step "
                         "counter is this worker's accepted-submit "
                         "count, proc= matches --replica")
    ap.add_argument("--prewarm", action="store_true",
                    help="pay every prefill-bucket + decode compile "
                         "BEFORE reporting ready (serve.loadgen."
                         "prewarm), so measured fleet TTFTs are "
                         "steady-state from the first routed request")
    ap.add_argument("--platform", default="cpu")
    return ap


def worker_main(argv: Optional[Sequence[str]] = None) -> int:
    args = _worker_argparser().parse_args(argv)
    # protocol stream = the REAL stdout fd; everything else (library
    # log(), XLA warnings) is pointed at stderr so a stray print can
    # never tear a protocol line
    proto = os.fdopen(os.dup(1), "w", buffering=1)
    os.dup2(2, 1)
    sys.stdout = sys.stderr

    from ..utils import platform as plat

    if args.platform == "cpu":
        plat.pin("cpu", num_devices=max(1, args.tp))

    import selectors

    from ..models import Transformer, TransformerConfig
    from ..utils import prng
    from .scheduler import Scheduler, ServeConfig

    model = Transformer(TransformerConfig(
        vocab_size=args.vocab, max_seq_len=args.seq,
        n_layers=args.layers, d_model=args.d_model, n_heads=args.heads,
        d_ff=args.d_ff))
    params = model.init(prng.init_key(args.init_seed))

    def emit(obj: Dict[str, Any]) -> None:
        try:
            proto.write(json.dumps(obj) + "\n")
            proto.flush()
        except BrokenPipeError:
            # the control plane died mid-write: the event has no
            # reader.  The stdin-EOF orphan path owns the exit; a
            # SIGPIPE-shaped crash here would turn a clean orphan
            # drain into a fake worker failure.
            pass

    if args.ckpt:
        # rollout path: replace the seed-derived params with a VERIFIED
        # weight snapshot.  Failure is a deterministic no-retry exit —
        # relaunching would re-read the same bad bytes; the autopilot
        # reads the stopped child as "canary never came up" and rolls
        # back with the old generation undisturbed.
        try:
            from .autopilot import load_weight_snapshot

            params = load_weight_snapshot(args.ckpt, params)
            print(f"[worker {args.replica}] loaded weight snapshot "
                  f"{args.ckpt}", file=sys.stderr, flush=True)
        except Exception as exc:
            emit({"ev": "load_error", "error": str(exc)[:500]})
            print(f"[worker {args.replica}] checkpoint load failed: "
                  f"{exc}", file=sys.stderr, flush=True)
            from ..train.resilience import EXIT_ANOMALY

            return EXIT_ANOMALY

    from ..utils.faults import FaultPlan

    fault_plan = FaultPlan.from_config(args.faults or "")

    engine: ReplicaHandle
    sched: Optional[Scheduler] = None
    if args.tp and args.tp > 1:
        import jax

        from ..config import MeshConfig
        from ..parallel import megatron
        from ..parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh(
            MeshConfig(data=1, tensor=args.tp),
            devices=jax.devices()[:args.tp])
        params_tp = dict(params)
        params_tp["blocks"] = megatron.permute_qkv(
            params["blocks"], model.cfg.d_model, model.cfg.n_heads,
            args.tp, kv_heads=model.cfg.kv_heads)
        engine = TPGenerateReplica(model, params_tp, mesh,
                                   batch=args.slots,
                                   queue_cap=args.queue_depth,
                                   name=f"replica-{args.replica}")
    else:
        num_blocks = args.num_blocks or (
            1 + args.slots * (-(-args.seq // args.block_size)))
        sched = Scheduler(model, params, ServeConfig(
            slots=args.slots, num_blocks=num_blocks,
            block_size=args.block_size,
            prefill_chunk=args.prefill_chunk,
            queue_depth=args.queue_depth, attn_impl=args.attn_impl,
            prefix_cache=args.prefix_cache, kv_quant=args.kv_quant,
            temperature=args.temperature, role=args.role,
            telemetry_dir=args.telemetry_dir,
            rollup_every=max(1, args.status_every) * 5,
            replica=args.replica))
        if args.prewarm:
            import dataclasses

            from .loadgen import prewarm

            # a throwaway scheduler with identical geometry/sampling:
            # compiled programs are lru-cached per (model, geometry,
            # sampling, attn_impl), so its warmth is THIS scheduler's.
            # Always warmed UNIFIED: a prefill-role throwaway would
            # hand its prewarm requests off instead of completing them
            # (prewarm drives requests to completion), and the program
            # cache is role-blind anyway.
            prewarm(lambda: Scheduler(model, params, dataclasses.replace(
                sched.cfg, role="unified", telemetry_dir=None,
                trace_dir=None)))
            if args.role == "decode":
                # warm the handoff import scatter (``serve_import``)
                # + the first post-inject decode step with one
                # export/import round trip through throwaway
                # prefill/decode schedulers — else the pool's first
                # real inject books the compile as a fake handoff_ms
                # outlier
                pre = Scheduler(model, params, dataclasses.replace(
                    sched.cfg, role="prefill", telemetry_dir=None,
                    trace_dir=None))
                dec = Scheduler(model, params, dataclasses.replace(
                    sched.cfg, role="decode", telemetry_dir=None,
                    trace_dir=None))
                try:
                    r = pre.submit([1, 2, 3, 4], 4)
                    assert r is not None, "handoff prewarm rejected"
                    for _ in range(64):
                        pre.tick()
                        hs = pre.take_handoffs()
                        if hs:
                            break
                    else:
                        raise AssertionError(
                            "handoff prewarm never exported")
                    r2 = dec.inject(hs[0]["payload"])
                    assert r2 is not None, "handoff prewarm inject "\
                        "rejected"
                    dec.run_until_drained()
                    dec.result(r2)
                finally:
                    pre.close()
                    dec.close()
        engine = InprocReplica(sched, name=f"replica-{args.replica}")

    # raw non-blocking stdin: a burst of submit lines must all drain in
    # one pass (a buffered readline-per-select would admit one request
    # per idle timeout); selectors only provide the idle wait
    stdin_fd = sys.stdin.fileno()
    os.set_blocking(stdin_fd, False)
    sel = selectors.DefaultSelector()
    sel.register(stdin_fd, selectors.EVENT_READ)
    inbuf = b""

    def read_ops() -> Tuple[List[Dict[str, Any]], bool]:
        nonlocal inbuf
        eof = False
        while True:
            try:
                chunk = os.read(stdin_fd, 65536)
            except BlockingIOError:
                break
            except OSError:
                eof = True
                break
            if chunk == b"":
                eof = True
                break
            inbuf += chunk
        ops = []
        while b"\n" in inbuf:
            line, inbuf = inbuf.split(b"\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                op = json.loads(line)
            except ValueError:
                continue
            if isinstance(op, dict):
                ops.append(op)
        return ops, eof

    # advance-notice preemption (train.resilience channel): SIGUSR1 from
    # the supervisor/platform — or the injected twin, the ``preempt``
    # fault kind — sets a deadline; the worker keeps serving its
    # in-flight work, stops getting NEW work once the router pumps the
    # announcement (ProcReplica.accepting gates), and exits 47 as soon
    # as it is idle or the grace window closes, whichever comes first.
    import signal as signal_lib

    from ..train.resilience import (EXIT_DECOMMISSION, PREEMPT_GRACE_ENV,
                                    read_preempt_notice)

    notice: Dict[str, Any] = {"deadline": None, "grace_s": None,
                              "announced": None}

    def _notice_grace(spec_grace: Optional[float] = None) -> float:
        if spec_grace is not None:
            return float(spec_grace)
        rec = read_preempt_notice() or {}
        try:
            return float(rec.get("grace_s")
                         or os.environ.get(PREEMPT_GRACE_ENV) or 2.0)
        except (TypeError, ValueError):
            return 2.0

    def _on_notice_signal(signum, frame):
        if notice["deadline"] is not None:
            return   # idempotent: a repeated notice never escalates
        g = _notice_grace()
        notice["grace_s"] = g
        notice["deadline"] = time.monotonic() + g

    try:
        signal_lib.signal(signal_lib.SIGUSR1, _on_notice_signal)
    except ValueError:
        pass   # not the main thread (in-process tests): no signal seam

    emit({"ev": "ready", "replica": args.replica, "pid": os.getpid(),
          "tp": args.tp, "role": args.role,
          "generation": args.generation, "incarnation":
          os.environ.get("NNPT_INCARNATION", "0")})
    submits_seen = 0
    injects_seen = 0
    handoffs_seen = 0
    ticks = 0
    last_status = 0.0
    stop = False
    while not stop:
        # 1) drain control ops without blocking while work is pending
        busy = bool(engine.assigned()) or (
            sched is not None and (sched.pending()
                                   or sched.in_flight()))
        if not busy:
            sel.select(timeout=0.05)    # idle: park until ops arrive
        ops, eof = read_ops()
        if eof and not any(op.get("op") == "exit" for op in ops):
            # stdin EOF without the exit handshake: the control plane
            # died and this worker is ORPHANED.  Its in-flight work is
            # already owed by the next incarnation's journal replay, so
            # finishing it would deliver to nobody — drain through the
            # existing advance-notice channel (zero grace) and take the
            # same terminal exit 47 a noticed preemption takes.
            if notice["deadline"] is None:
                notice["grace_s"] = 0.0
                notice["deadline"] = time.monotonic()
        elif eof:
            stop = True    # parent hung up after exit: leave cleanly
        for op in ops:
            kind = op.get("op")
            if kind == "submit":
                submits_seen += 1
                if (args.crash_at_request
                        and submits_seen >= args.crash_at_request):
                    proto.flush()
                    os._exit(17)   # injected crash: SIGKILL-shaped
                if fault_plan is not None and fault_plan.fire_if_due(
                        "replica_kill", submits_seen,
                        proc=args.replica):
                    print(f"[faults] replica_kill at submit "
                          f"{submits_seen}: SIGKILL", file=sys.stderr,
                          flush=True)
                    proto.flush()
                    os.kill(os.getpid(), signal_lib.SIGKILL)
                if fault_plan is not None and notice["deadline"] is None:
                    spec = fault_plan.due_spec(
                        "preempt", submits_seen, proc=args.replica)
                    if spec is not None:
                        # injected twin of the SIGUSR1 notice: same
                        # deadline bookkeeping, same drain-and-exit-47
                        notice["grace_s"] = float(spec.grace)
                        notice["deadline"] = (time.monotonic()
                                              + float(spec.grace))
                        print(f"[faults] preempt notice at submit "
                              f"{submits_seen} (grace {spec.grace:.1f}s)",
                              file=sys.stderr, flush=True)
                req = FleetRequest(
                    rid=int(op["rid"]),
                    prompt=[int(t) for t in op["prompt"]],
                    max_new=int(op["max_new"]),
                    slo_ms=op.get("slo_ms"),
                    t_submit=time.monotonic(), deadline=math.inf,
                    unified=bool(op.get("unified")))
                if not engine.submit(req):
                    emit({"ev": "reject", "rid": req.rid})
            elif kind == "inject":
                # a committed handoff record arriving at a decode
                # replica; ack "injected" or reject with "inject": true
                injects_seen += 1
                if fault_plan is not None and fault_plan.fire_if_due(
                        "handoff_stall", injects_seen,
                        proc=args.replica):
                    # wedged-inject stand-in: swallow the op (no ack,
                    # no stream) — the router's handoff timeout must
                    # abort and retry elsewhere
                    print(f"[faults] handoff_stall: ignoring inject "
                          f"{injects_seen}", file=sys.stderr, flush=True)
                    continue
                req = FleetRequest(
                    rid=int(op["rid"]),
                    prompt=[int(t) for t in
                            (op.get("payload") or {}).get("prompt", [])],
                    max_new=int((op.get("payload") or {})
                                .get("max_new", 1)),
                    slo_ms=op.get("slo_ms"),
                    t_submit=time.monotonic(), deadline=math.inf)
                ok = False
                try:
                    ok = engine.inject(req, op.get("payload") or {})
                except ValueError as exc:
                    print(f"[worker {args.replica}] inject rejected: "
                          f"{exc}", file=sys.stderr, flush=True)
                if not ok:
                    emit({"ev": "reject", "rid": req.rid,
                          "inject": True})
            elif kind in ("drain", "decommission"):
                if fault_plan is not None and fault_plan.fire_if_due(
                        "stall_drain", submits_seen,
                        proc=args.replica):
                    # wedged-shutdown stand-in: the op is swallowed; the
                    # autopilot's drain timeout must escalate to a kill
                    print(f"[faults] stall_drain: ignoring {kind}",
                          file=sys.stderr, flush=True)
                    continue
                if sched is not None:
                    reqs = sched.quiesce()
                else:
                    reqs = [{"rid": r, "prefilled": 0, "generated": 0}
                            for r in engine.take_assigned()]
                emit({"ev": "drained", "requests": reqs})
                if kind == "decommission":
                    # intentional-decommission handshake: drained state
                    # reported, now exit the code the (already retired)
                    # supervisor treats as terminal without budget burn
                    proto.flush()
                    if sched is not None:
                        sched.close()
                    from ..train.resilience import EXIT_DECOMMISSION

                    return EXIT_DECOMMISSION
            elif kind == "exit":
                stop = True
        if stop:
            break
        # 1b) advance-notice drain: announce once (the router closes
        # admission when it pumps this), keep serving in-flight work,
        # and exit 47 at idle-after-settle or the grace deadline —
        # whichever comes first.  An idle exit reports an EMPTY drained
        # set: the zero-requeue preemption the crash path cannot give.
        if notice["deadline"] is not None:
            now_m = time.monotonic()
            if notice["announced"] is None:
                notice["announced"] = now_m
                print(f"[worker {args.replica}] preemption notice: "
                      f"draining within {notice['grace_s']:.1f}s, then "
                      f"exit {EXIT_DECOMMISSION}", file=sys.stderr,
                      flush=True)
                emit({"ev": "preempt_notice",
                      "grace_s": notice["grace_s"]})
            idle = not (engine.assigned()
                        or (sched is not None
                            and (sched.pending() or sched.in_flight())))
            if now_m >= notice["deadline"] or (
                    idle and now_m >= notice["announced"] + 0.25):
                # the decommission handshake, self-initiated: report
                # drained state (leftovers requeue exactly once through
                # the router's ledger), then the terminal no-retry exit
                if sched is not None:
                    reqs = sched.quiesce()
                else:
                    reqs = [{"rid": r, "prefilled": 0, "generated": 0}
                            for r in engine.take_assigned()]
                emit({"ev": "drained", "requests": reqs})
                proto.flush()
                if sched is not None:
                    sched.close()
                return EXIT_DECOMMISSION
        # 2) advance the engine one step; report completions, handoffs
        # and inject acks (the engine tags non-done events with "ev")
        for rec in engine.pump():
            rec.pop("requeue", None)
            ev = rec.pop("ev", "done")
            if ev == "handoff":
                handoffs_seen += 1
                if fault_plan is not None and fault_plan.fire_if_due(
                        "handoff_kill", handoffs_seen,
                        proc=args.replica):
                    # die BEFORE the commit line reaches the wire: the
                    # router never saw the record, so the request
                    # requeues for a full re-prefill elsewhere
                    print(f"[faults] handoff_kill at handoff "
                          f"{handoffs_seen}: SIGKILL pre-commit",
                          file=sys.stderr, flush=True)
                    proto.flush()
                    os.kill(os.getpid(), signal_lib.SIGKILL)
                emit({"ev": "handoff", **rec})
                if fault_plan is not None and fault_plan.fire_if_due(
                        "handoff_kill_post", handoffs_seen,
                        proc=args.replica):
                    # die AFTER the commit line: the router owns the
                    # record — decode proceeds, prefill is not repaid
                    print(f"[faults] handoff_kill_post at handoff "
                          f"{handoffs_seen}: SIGKILL post-commit",
                          file=sys.stderr, flush=True)
                    proto.flush()
                    os.kill(os.getpid(), signal_lib.SIGKILL)
                continue
            emit({"ev": ev, **rec})
            if ev == "injected" and fault_plan is not None \
                    and fault_plan.fire_if_due(
                        "decode_kill", injects_seen,
                        proc=args.replica):
                # decode death mid-stream, after the ack: the router
                # re-injects from its ledger record (re-decode only)
                print(f"[faults] decode_kill after inject "
                      f"{injects_seen}: SIGKILL", file=sys.stderr,
                      flush=True)
                proto.flush()
                os.kill(os.getpid(), signal_lib.SIGKILL)
        ticks += 1
        slow_ms = (fault_plan.slow_penalty_ms(submits_seen,
                                              proc=args.replica)
                   if fault_plan is not None and busy else 0.0)
        if (args.step_sleep_ms and busy) or slow_ms:
            time.sleep(((args.step_sleep_ms if busy else 0.0)
                        + slow_ms) / 1e3)
        # 3) status cadence: every N ticks while busy, ~4 Hz floor
        now = time.monotonic()
        if (ticks % max(1, args.status_every) == 0
                or now - last_status > 0.25):
            report = (sched.load_report() if sched is not None
                      else engine.load_report())
            report["generation"] = args.generation
            emit({"ev": "status", "report": report})
            last_status = now
    if sched is not None:
        sched.close()
    return 0


if __name__ == "__main__":
    sys.exit(worker_main())
