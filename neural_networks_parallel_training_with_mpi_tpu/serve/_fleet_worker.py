"""Replica worker entry point.

``python -m neural_networks_parallel_training_with_mpi_tpu.serve.\
_fleet_worker --worker ...`` — a dedicated runnable module (NOT
re-exported by ``serve/__init__``) so runpy never finds the target
already imported by the package init (the "found in sys.modules"
warning ``-m serve.fleet`` would trip).  All logic lives in
:func:`serve.fleet.worker_main`.
"""

from __future__ import annotations

import sys

from .fleet import worker_main

if __name__ == "__main__":
    sys.exit(worker_main())
