"""Paged KV cache: block-allocated pools + static-shape gathered attention.

The dense slot server (``models/serve.py``) reserves ``max_len`` cache
positions per slot the moment a stream is admitted, so device memory is
spent on the WORST-case length of every stream simultaneously — the
classic serving waste paged attention removes (vLLM, Kwon et al. 2023;
the TPU angle is that everything must stay static-shape so one compiled
step serves any mix of lengths).  Here the cache is a pool of fixed-size
blocks:

* **Pools**: per layer, ``k``/``v`` of shape ``(num_blocks, block_size,
  kv_heads, head_dim)`` (plus f32 scale pools under ``kv_quant`` — the
  same int8 scheme as :func:`models.generate.init_kv_cache`, quantized
  per (position, head) so block boundaries never change the numbers).
* **Block tables**: per slot, ``(max_blocks,)`` int32 indices into the
  pool, host-owned (a tiny traced argument each step — never a
  recompile).  Unallocated entries point at the reserved **sink block
  0**, which is never handed to a stream: pad/frozen writes land there
  harmlessly and are never attended.
* **Attention dispatch** (``attn_impl``): the default **gathered** path
  gathers each row's blocks ``pool[table] -> (T_cap, kv_heads,
  head_dim)`` (``T_cap = max_blocks * block_size``) and attends under
  the causal mask ``t <= pos`` — the same reduction, over the same
  values in the same order, as the dense cache path, which is why
  greedy paged decode is token-identical to ``DecodeServer`` /
  ``models.generate.generate`` (pinned by tests/test_serve_paged.py).
  The gather materializes the attended window transiently (what dense
  attention reads anyway); the win is the PERSISTENT allocation, which
  now tracks actual tokens in flight instead of slots x max_len.  The
  **fused** path (``ops.pallas_kernels.paged_attention``) adds the
  FLOPs/bandwidth win on top: the Pallas kernel reads K/V straight from
  the pool through the tables and walks only ``ceil(len/block_size)``
  blocks per stream — token-identical to gathered, pinned by
  tests/test_paged_attn.py.
* **Writes** are scatters at ``(table[pos // block_size], pos %
  block_size)`` — one position per row at decode, a chunk of positions
  at prefill (chunks may straddle block boundaries; each position
  resolves its own block).

Invariant the step relies on (mirrors the dense server's "dead lanes
cost FLOPs, not recompiles" contract): every slot flows through the
batched step every tick, but live blocks are written ONLY by prefill
chunks and ACTIVE decode lanes.  ``step()`` masks every non-active
slot's table row to the sink (free, finished, and mid-prefill slots
alike), so a dead lane's unconditional write lands in the sink and its
gathered read is discarded garbage — parity never rests on a frozen
lane recomputing bitwise-identical K/V, and a finished/evicted slot's
table is additionally zeroed BEFORE its blocks are freed so nothing can
touch a block someone else just allocated.

Completion is detected from HOST-tracked position counters (positions
advance deterministically, one per active slot per step), so the decode
loop performs zero per-token device syncs — the discipline the trainer's
monitor uses, taken to its limit (see the satellite fix in
``models/serve.py``).

**Prefix caching + copy-on-write** (``prefix_cache=True``): real chat
traffic shares system prompts, and the block-table indirection above is
one refcount away from sharing the identical prefix K/V across streams
(vLLM's insight applied at admission; SGLang's RadixAttention shows the
hit rates a prefix-matched block store reaches on chat/agentic mixes).
A host-side :class:`PrefixIndex` maps hash-chained token chunks at block
granularity to resident blocks; ``try_admit`` longest-matches a new
prompt against it and points the matched table entries at the EXISTING
blocks instead of allocating and prefilling them — a fully cached prefix
admits with only the last prompt token left to prefill (its logits seed
the first sampled token), so TTFT collapses to the remaining-suffix
prefill.  :class:`BlockAllocator` grows per-block refcounts: a matched
in-use block is ``share()``d (refcount + 1), a matched cached-FREE block
(refcount 0, content intact, sitting in the allocator's LRU side of the
free list) is ``reuse_cached()``d, and fresh allocation under pressure
evicts cached-free blocks LRU-first (invalidating their index entries).
Sharing is read-only by construction: a stream may write ONLY blocks it
owns, and when its matched prefix ends mid-block the first write past
the shared boundary triggers **copy-on-write** — a fresh block (reserved
at admission, so the fork can never fail mid-prefill) receives the
shared block's contents via one on-device copy program (traced src/dst
scalars: forks never recompile), the table is repointed, and the share
is released.  This extends the block-0 sink invariant's discipline —
"nothing writes a block another stream can read" — to shared blocks,
asserted on every prefill chunk and decode step.  ``assert_drained``
extends to "all refcounts zero": after a drain every block is either
plain-free or cached-free, never referenced.
"""

from __future__ import annotations

import base64
import collections
import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import _quantize_kv, _sample
from ..models.transformer import Transformer, split_qkv
from ..ops.pallas_kernels import paged_attention

Pytree = Any

# attention dispatch seam: 'gathered' materializes pool[table] and reduces
# over all max_blocks*block_size key positions per stream (the parity
# reference); 'fused' reads K/V straight from the block pool via the
# Pallas paged-attention kernel and stops at each stream's true length
# (ops.pallas_kernels.paged_attention — token-identical, pinned)
ATTN_IMPLS = ("gathered", "fused")

# block 0 is reserved: pad positions and frozen slots write (and gather)
# here, so a scatter never needs dynamic masking to be allocation-safe
SINK_BLOCK = 0


def prefill_bucket(width: int) -> int:
    """The pow2 bucket a prefill chunk of ``width`` tokens pads to
    (minimum 8) — the rule :meth:`PagedDecodeServer.prefill_step`
    compiles against, shared with ``serve.loadgen.prewarm`` so the
    warmed bucket set can never drift from the compiled set."""
    b = 8
    while b < width:
        b *= 2
    return b


class BlockExhausted(RuntimeError):
    """The pool cannot supply the next block for one or more streams;
    carries the starving request ids so a scheduler can pick a victim."""

    def __init__(self, rids: List[int]):
        super().__init__(f"KV block pool exhausted; streams needing a "
                         f"block: {rids}")
        self.rids = list(rids)


class BlockAllocator:
    """Refcounted free-list allocator over block ids ``1..num_blocks-1``
    (0 is the sink).  A block is in one of three states: **in use**
    (refcount >= 1 — several streams may share one block), **cached-free**
    (refcount 0 but still holding prefix-cache content: allocatable, kept
    in LRU order and evicted under pressure via ``on_cache_evict``), or
    **plain free**.  Leak-proof by construction: every id is in exactly
    one state, :meth:`release` of a block with no references raises (the
    double-free hard error — ALL frees route through this one path), and
    :meth:`assert_drained` pins every refcount at zero with the free
    balance equal to capacity after a drain (the fuzz invariant)."""

    def __init__(self, num_blocks: int,
                 on_cache_evict: Optional[Callable[[int], None]] = None):
        if num_blocks < 2:
            raise ValueError(f"num_blocks {num_blocks} < 2: block 0 is "
                             "the reserved sink, so a usable pool needs "
                             "at least one more")
        self.num_blocks = int(num_blocks)
        # pop from the tail -> ascending ids hand out first (stable tests)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        # cached-free: refcount 0, prefix content intact; insertion order
        # = release order, so popitem(last=False) is LRU eviction
        self._cached: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self._cached_ids: set = set()   # blocks carrying a cache identity
        self._ref: Dict[int, int] = {}  # in-use refcounts (>= 1)
        self._on_cache_evict = on_cache_evict

    @property
    def capacity(self) -> int:
        """Usable blocks (the sink is not allocatable)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: plain free + cached-free (a cached block
        costs nothing to keep — it is reclaimed LRU-first on demand)."""
        return len(self._free) + len(self._cached)

    @property
    def used_blocks(self) -> int:
        return len(self._ref)

    @property
    def cached_free_blocks(self) -> int:
        return len(self._cached)

    @property
    def shared_extra(self) -> int:
        """Extra references across all shared blocks — the number of
        block allocations sharing is saving RIGHT NOW."""
        return sum(r - 1 for r in self._ref.values() if r > 1)

    def refcount(self, b: int) -> int:
        return self._ref.get(b, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh block ids at refcount 1, or None when the pool
        cannot satisfy the request (all-or-nothing: nothing is evicted
        or granted on refusal).  Plain-free blocks hand out first;
        beyond them, cached-free blocks are reclaimed LRU-first, their
        index entries invalidated via ``on_cache_evict``."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > self.free_blocks:
            return None
        out = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                b, _ = self._cached.popitem(last=False)   # LRU victim
                self._cached_ids.discard(b)
                if self._on_cache_evict is not None:
                    self._on_cache_evict(b)
            self._ref[b] = 1
            out.append(b)
        return out

    def share(self, b: int) -> None:
        """One more reader of an in-use block (a cache-hit admission
        mapping its table onto an existing block)."""
        if b not in self._ref:
            raise ValueError(f"share of block {b} not in use")
        self._ref[b] += 1

    def reuse_cached(self, b: int) -> None:
        """Revive a specific cached-free block (refcount 0 -> 1) — a
        cache hit on content whose last reader already finished."""
        if b not in self._cached:
            raise ValueError(f"reuse_cached of block {b} not cached-free")
        del self._cached[b]
        self._ref[b] = 1

    def release(self, blocks: List[int]) -> None:
        """THE single release path: drop one reference per listed block.
        A block reaching refcount 0 returns to the free list — the
        cached-free LRU side when it carries prefix content, plain
        otherwise.  Releasing a block with no references is a hard error
        (double free of a shared block, foreign id, or the sink)."""
        for b in blocks:
            r = self._ref.get(b)
            if r is None:
                raise ValueError(f"release of block {b} not in use "
                                 "(double free or foreign id)")
            if r > 1:
                self._ref[b] = r - 1
            else:
                del self._ref[b]
                if b in self._cached_ids:
                    self._cached[b] = None      # MRU end of the LRU queue
                else:
                    self._free.append(b)

    def free(self, blocks: List[int]) -> None:
        """Alias of :meth:`release` kept for callers predating refcounts
        — every free routes through the one release path, so a double
        free of a shared block raises instead of silently re-pooling a
        block someone still reads."""
        self.release(blocks)

    def mark_cached(self, b: int) -> None:
        """Tag a block as carrying prefix-cache content: when its last
        reference drops it parks in the cached-free LRU instead of the
        plain free list."""
        self._cached_ids.add(b)

    def assert_drained(self) -> None:
        if self._ref:
            raise AssertionError(
                "block leak: refcounts not drained after quiesce: "
                f"{dict(sorted(self._ref.items()))}")
        if len(self._free) + len(self._cached) != self.capacity:
            raise AssertionError(
                f"free-list balance {len(self._free)} plain + "
                f"{len(self._cached)} cached != capacity {self.capacity}")


class PrefixIndex:
    """Host-side prefix-cache index: hash-chained token chunks at block
    granularity -> resident block id.  A key is ``(parent_key,
    tokens_tuple)`` — the EXACT token ids, so a hit can never be a hash
    collision, and nesting shares structure with the parent key (O(1)
    extra per entry).  Full prompt blocks chain with ``tokens_tuple`` of
    ``block_size`` ids; the final partial prompt block registers under
    the same scheme with a shorter tuple.  One identity per block, at
    most one block per key (first writer wins); entries are invalidated
    when the allocator reclaims their block."""

    def __init__(self):
        self._map: Dict[Tuple, int] = {}
        self._key_of: Dict[int, Tuple] = {}
        # bumped on every mutation: lookup results are pure functions of
        # (prompt, version), which is what lets the server memoize the
        # admission lookup (admit_need + try_admit + a blocked queue
        # head re-polling every tick would otherwise re-hash the whole
        # prompt each time)
        self.version = 0

    def __len__(self) -> int:
        return len(self._map)

    def get(self, key: Tuple) -> Optional[int]:
        return self._map.get(key)

    def insert(self, key: Tuple, block: int) -> bool:
        """Register ``block`` under ``key``; False when the key is
        already claimed (a concurrent identical prefill — first writer
        wins) or the block already carries another identity."""
        if key in self._map or block in self._key_of:
            return False
        self._map[key] = block
        self._key_of[block] = key
        self.version += 1
        return True

    def invalidate_block(self, block: int) -> None:
        key = self._key_of.pop(block, None)
        if key is not None and self._map.get(key) == block:
            del self._map[key]
            self.version += 1


def init_paged_kv(model: Transformer, num_blocks: int, block_size: int,
                  quant: bool = False):
    """Per-layer paged pools ``(num_blocks, block_size, kv_heads,
    head_dim)`` — :func:`models.generate.init_kv_cache` with the length
    axis split into (block, offset).  ``quant=True`` stores int8 codes
    plus one f32 scale per (block, offset, head), the identical scheme
    the dense cache uses (scales are per position, so paging cannot
    change the numbers)."""
    c = model.cfg
    shape = (num_blocks, block_size, c.kv_heads, c.head_dim)
    if quant:
        zeros = lambda: jnp.zeros(shape, jnp.int8)          # noqa: E731
        ones = lambda: jnp.ones(shape[:-1], jnp.float32)    # noqa: E731
        return [{"k": zeros(), "v": zeros(),
                 "k_scale": ones(), "v_scale": ones()}
                for _ in range(c.n_layers)]
    zeros = lambda: jnp.zeros(shape, c.compute_dtype)       # noqa: E731
    return [{"k": zeros(), "v": zeros()} for _ in range(c.n_layers)]


@functools.lru_cache(maxsize=8)
def _paged_programs(model: Transformer, block_size: int, max_blocks: int,
                    temperature: float, top_k: int, top_p: float,
                    kv_quant: bool = False, attn_impl: str = "gathered"):
    """The four jitted programs of a paged server: chunk prefill (one
    per power-of-two chunk bucket, via jit's shape cache), the batched
    decode step, the copy-on-write block copy (``serve_cow``), and the
    block-handoff import scatter (``serve_import`` — the CoW copy's
    sibling with the source row arriving from the host instead of
    another pool row).  Cached per (model, geometry, sampling,
    attn_impl) so several servers compile once.  ``attn_impl='fused'``
    swaps the gathered attention for the Pallas paged kernel;
    everything else (scatter coordinates, sampling, bookkeeping) is
    shared, which is what makes gathered-vs-fused an attention-only
    A/B."""
    bs, mb = int(block_size), int(max_blocks)
    t_cap = bs * mb
    c = model.cfg
    if attn_impl not in ATTN_IMPLS:
        raise ValueError(f"attn_impl must be one of {ATTN_IMPLS}, "
                         f"got {attn_impl!r}")

    def block_fwd(layer_params, pool, tables, starts, x, valid, lengths):
        """One transformer block over a chunk ``x`` (B, W, D) whose rows
        sit at per-row start positions, K/V scattered into the paged
        pool and attention read back through the block tables — gathered
        (``pool[table]`` then a full-width masked reduction) or fused
        (the paged kernel walks only ``ceil(lengths/bs)`` live blocks).
        Mirrors ``models.generate._block_chunk`` (the pinned dense
        math) with the cache axis split into (block, offset).  ``valid``
        (W,) masks pad columns of a bucketed prefill chunk: their writes
        divert to the sink block.  ``lengths`` (B,) is each row's
        attendable-key count (0 = inactive lane), traced like the
        tables so length churn never recompiles."""
        mods = model._block_modules()
        h = mods["ln1"].apply(layer_params["ln1"], x)
        qkv = mods["qkv"].apply(layer_params["qkv"], h)
        b, w, _ = qkv.shape
        q, k, v = split_qkv(c, qkv)   # q: (B,W,H,hd); k/v: (B,W,KV,hd)
        positions = starts[:, None] + jnp.arange(w)[None, :]    # (B, W)
        if c.pos_encoding == "rope":
            from ..ops.rope import rope_rotate

            q = rope_rotate(q, positions, c.rope_theta)
            k = rope_rotate(k, positions, c.rope_theta)
        # scatter coordinates: each position resolves its own block via
        # the row's table (chunks straddle block boundaries freely); pad
        # columns land in the sink
        blk = jnp.take_along_axis(tables, positions // bs, axis=1)
        blk = jnp.where(valid[None, :], blk, SINK_BLOCK)
        off = jnp.where(valid[None, :], positions % bs, 0)
        quant = "k_scale" in pool
        if quant:
            k, ks = _quantize_kv(k)
            v, vs = _quantize_kv(v)
            new_ksp = pool["k_scale"].at[blk, off].set(ks)
            new_vsp = pool["v_scale"].at[blk, off].set(vs)
        new_kp = pool["k"].at[blk, off].set(k.astype(pool["k"].dtype))
        new_vp = pool["v"].at[blk, off].set(v.astype(pool["v"].dtype))
        if attn_impl == "fused":
            # the Pallas kernel reads K/V straight from the pool through
            # the tables and reduces over each row's TRUE length — no
            # pool[table] materialization, no max_blocks*bs reduction.
            # int8 scale pools ride in and dequantize on load.
            out = paged_attention(
                q, new_kp, new_vp, tables, lengths, starts,
                k_scale=new_ksp if quant else None,
                v_scale=new_vsp if quant else None).astype(x.dtype)
        else:
            # gather each row's attended window: (B, MB, bs, kv, hd) ->
            # (B, T_cap, kv, hd), positions in ascending order — the
            # same values, same order, as the dense cache's
            # (B, T, kv, hd) slab
            gk = new_kp[tables].reshape(b, t_cap, c.kv_heads, c.head_dim)
            gv = new_vp[tables].reshape(b, t_cap, c.kv_heads, c.head_dim)
            scale = 1.0 / jnp.sqrt(jnp.asarray(c.head_dim, jnp.float32))
            mask = (jnp.arange(t_cap)[None, None, :]
                    <= positions[:, :, None])           # (B, W, T_cap)
            if quant:
                gks = new_ksp[tables].reshape(b, t_cap, c.kv_heads)
                gvs = new_vsp[tables].reshape(b, t_cap, c.kv_heads)
            if c.kv_heads == c.n_heads:
                logits = jnp.einsum("bqhd,bkhd->bhqk",
                                    q.astype(jnp.float32),
                                    gk.astype(jnp.float32)) * scale
                if quant:
                    logits = logits * gks.transpose(0, 2, 1)[:, :, None, :]
                logits = jnp.where(mask[:, None], logits, -1e30)
                probs = jax.nn.softmax(logits, axis=-1)
                if quant:
                    probs = probs * gvs.transpose(0, 2, 1)[:, :, None, :]
                out = jnp.einsum("bhqk,bkhd->bqhd", probs,
                                 gv.astype(jnp.float32)).astype(x.dtype)
            else:
                g = c.n_heads // c.kv_heads
                q5 = q.reshape(b, w, c.kv_heads, g, c.head_dim)
                logits = jnp.einsum("bqcgd,bkcd->bcgqk",
                                    q5.astype(jnp.float32),
                                    gk.astype(jnp.float32)) * scale
                if quant:
                    logits = logits * gks.transpose(0, 2, 1)[:, :, None,
                                                             None, :]
                logits = jnp.where(mask[:, None, None], logits, -1e30)
                probs = jax.nn.softmax(logits, axis=-1)
                if quant:
                    probs = probs * gvs.transpose(0, 2, 1)[:, :, None,
                                                           None, :]
                out = jnp.einsum("bcgqk,bkcd->bqcgd", probs,
                                 gv.astype(jnp.float32)).astype(x.dtype)
                out = out.reshape(b, w, c.n_heads, c.head_dim)
        out = out.reshape(b, w, c.d_model)
        x = x + mods["attn_out"].apply(layer_params["attn_out"], out)
        h = mods["ln2"].apply(layer_params["ln2"], x)
        if c.moe_experts > 0:
            ff, _ = mods["moe"].apply(layer_params["moe"], h)
        else:
            ff = model._ffn(mods, layer_params, h)
        new_pool = {"k": new_kp, "v": new_vp}
        if quant:
            new_pool.update(k_scale=new_ksp, v_scale=new_vsp)
        return x + ff.astype(x.dtype), new_pool

    def forward(params, pools, tables, starts, ids, valid, lengths):
        # clamp pad columns' embedding positions into range (their
        # outputs are discarded; learned positional tables have no row
        # past max_seq_len)
        w = ids.shape[1]
        emb_pos = jnp.minimum(starts[:, None] + jnp.arange(w)[None, :],
                              c.max_seq_len - 1)
        x = model.embed(params, ids, emb_pos)
        new_pools = []
        for layer_params, pool in zip(params["blocks"], pools):
            x, pool = block_fwd(layer_params, pool, tables, starts, x,
                                valid, lengths)
            new_pools.append(pool)
        return model.head_logits(params, x), new_pools

    def prefill(params, pools, table, start, chunk, true_w):
        # chunk (1, W_bucket) int32; logits for ALL columns return and
        # the caller indexes the true last position (same contract as
        # the dense server's bucketed prefill).  attendable keys after
        # this chunk's writes: everything up to start + true_w (pad
        # columns wrote to the sink, which is past every length)
        valid = jnp.arange(chunk.shape[1]) < true_w
        return forward(params, pools, table, start, chunk, valid,
                       start + true_w)

    def step(params, pools, tokens, tables, pos, active, key):
        s = tokens.shape[0]
        cap = tokens.shape[1] - 1
        ids = jnp.take_along_axis(tokens, pos[:, None], axis=1)  # (S, 1)
        # a decode row attends its own fresh write too: pos + 1 keys;
        # inactive lanes carry length 0, so the fused kernel walks ZERO
        # of their blocks (the gathered path computes-and-discards them)
        lengths = jnp.where(active, pos + 1, 0)
        logits, new_pools = forward(params, pools, tables, pos, ids,
                                    jnp.ones((1,), bool), lengths)
        nxt, key = _sample(logits[:, 0], temperature, key, top_k, top_p)
        # frozen slots re-write the token already there (idempotent) and
        # hold position — the dense server's exact bookkeeping
        nxt = jnp.where(active, nxt, jnp.take_along_axis(
            tokens, jnp.minimum(pos + 1, cap)[:, None], axis=1)[:, 0])
        write_at = jnp.minimum(pos + 1, cap)
        tokens = tokens.at[jnp.arange(s), write_at].set(nxt)
        pos = jnp.where(active, jnp.minimum(pos + 1, cap), pos)
        return new_pools, tokens, pos, key

    def cow(pools, src, dst):
        """Copy-on-write fork: duplicate block row ``src`` into the
        stream-owned ``dst`` across every layer's pool tensors (K, V and
        the int8 scale pools alike).  ``src``/``dst`` are TRACED scalars,
        so fork churn reuses one compiled program — the same discipline
        that keeps table churn recompile-free.  The whole block row
        copies (positions past the shared prefix are overwritten by the
        forking stream's own writes before they are ever attended)."""
        return jax.tree_util.tree_map(
            lambda p: p.at[dst].set(p[src]), pools)

    def imp(pools, rows, dst):
        """Block-handoff import: scatter one block row of host-supplied
        K/V content (``rows`` — a pytree matching one pool block row per
        layer, int8 scale pools included) into pool row ``dst``.  Like
        ``cow``, ``dst`` is a TRACED scalar, so importing N blocks
        reuses one compiled program no matter which pool rows the
        allocator handed out."""
        return jax.tree_util.tree_map(
            lambda p, r: p.at[dst].set(r.astype(p.dtype)), pools, rows)

    # compile-ledger seam (utils/compile_ledger): while a ledger is
    # installed every distinct compile of the serve programs is recorded
    # — which is how the "block-table churn never recompiles" invariant
    # becomes a production assertion instead of a test-only cache count
    # (tables/lengths are traced args; only a NEW prefill bucket width
    # may legitimately add an entry).  Cache-hit admissions, CoW forks
    # and shared-block evictions ride the same contract: src/dst/table
    # values are runtime data, so the ledger stays flat.
    from ..utils import compile_ledger as ledger_lib

    tag = (f"bs{bs}x{mb}" + ("/int8" if kv_quant else "")
           + f"/{attn_impl}")
    return (ledger_lib.instrument(jax.jit(prefill, donate_argnums=(1,)),
                                  f"serve_prefill[{tag}]"),
            ledger_lib.instrument(jax.jit(step, donate_argnums=(1, 2, 4)),
                                  f"serve_decode[{tag}]"),
            ledger_lib.instrument(jax.jit(cow, donate_argnums=(0,)),
                                  f"serve_cow[{tag}]"),
            ledger_lib.instrument(jax.jit(imp, donate_argnums=(0,)),
                                  f"serve_import[{tag}]"))


@dataclass
class _Stream:
    """Host bookkeeping for one in-flight request."""
    rid: int
    prompt: List[int]
    max_new: int
    target: int                       # prompt_len + max_new
    blocks: List[int] = field(default_factory=list)
    prefilled: int = 0                # prompt tokens written so far
    # prefix-cache state: the leading n_shared table entries are BORROWED
    # (read-only — owned by the index/another stream); fork_pending is
    # the block reserved at admission for the copy-on-write fork of a
    # borrowed PARTIAL tail (None when the match ended on a block
    # boundary); chain_key/registered_tokens track how far this stream's
    # own prompt blocks have been registered into the prefix index
    n_shared: int = 0
    fork_pending: Optional[int] = None
    chain_key: Any = None
    registered_tokens: int = 0
    shared_at_admit: int = 0          # matched prefix tokens (stats)


class PagedDecodeServer:
    """Slot server over a paged KV pool: same host contract as the dense
    ``DecodeServer`` (submit/step/done/result), plus the paged-runtime
    surface a scheduler drives — partial (chunked) prefill, on-demand
    block growth, eviction, and free-block/slot introspection."""

    def __init__(self, model: Transformer, params: Pytree, *,
                 slots: int = 8, num_blocks: int = 64,
                 block_size: int = 16, max_len: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0,
                 kv_quant: bool = False, attn_impl: str = "gathered",
                 prefix_cache: bool = False):
        c = model.cfg
        self.model, self.params = model, params
        self.slots = int(slots)
        self.block_size = int(block_size)
        self.max_len = int(max_len or c.max_seq_len)
        if self.max_len > c.max_seq_len:
            raise ValueError(f"max_len {self.max_len} exceeds model "
                             f"max_seq_len {c.max_seq_len}")
        self.max_blocks = -(-self.max_len // self.block_size)   # ceil
        self.t_cap = self.max_blocks * self.block_size
        self.num_blocks = int(num_blocks)
        self.prefix_cache = bool(prefix_cache)
        self.prefix = PrefixIndex()
        self.allocator = BlockAllocator(
            self.num_blocks,
            on_cache_evict=self._on_cache_evict if self.prefix_cache
            else None)
        # prefix-cache counters (host arithmetic; the scheduler folds
        # them into kind="serve" telemetry records)
        self.prefix_hits = 0          # admissions with matched_len > 0
        self.prefix_misses = 0
        self.prefix_hit_tokens = 0    # prompt tokens served from cache
        self.prompt_tokens_admitted = 0
        self.cow_forks = 0            # copy-on-write block forks
        self.cache_evictions = 0      # cached-free blocks reclaimed (LRU)
        self.blocks_shared_total = 0  # cumulative matched blocks at admit
        # disaggregated-handoff counters (export happens on the prefill
        # role, import on the decode role)
        self.handoffs_exported = 0
        self.handoffs_imported = 0
        self._lookup_memo = None      # (prompt, index-version) -> walk
        self._sampling = (float(temperature), int(top_k), float(top_p))
        self.kv_quant = bool(kv_quant)
        if attn_impl not in ATTN_IMPLS:
            raise ValueError(f"attn_impl must be one of {ATTN_IMPLS}, "
                             f"got {attn_impl!r}")
        self.attn_impl = attn_impl
        (self._prefill_fn, self._step_fn, self._cow_fn,
         self._import_fn) = _paged_programs(
            model, self.block_size, self.max_blocks, *self._sampling,
            self.kv_quant, self.attn_impl)
        self.pools = init_paged_kv(model, self.num_blocks,
                                   self.block_size, quant=self.kv_quant)
        self.tokens = jnp.zeros((self.slots, self.t_cap), jnp.int32)
        self.pos = jnp.zeros((self.slots,), jnp.int32)
        self.tables = np.zeros((self.slots, self.max_blocks), np.int32)
        self.active = np.zeros((self.slots,), bool)     # decoding slots
        self._pos_host = np.zeros((self.slots,), np.int64)
        self.key = jax.random.PRNGKey(seed)
        self._rid = 0
        self._streams: Dict[int, _Stream] = {}
        self._slot_of: Dict[int, int] = {}
        self._results: Dict[int, List[int]] = {}
        if c.scan_layers:
            params = dict(params)
            stacked = params["blocks"]
            params["blocks"] = [
                jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
                for i in range(c.n_layers)]
            self.params = params

    # ---- geometry ------------------------------------------------------
    def blocks_for(self, length: int) -> int:
        """Blocks needed to hold ``length`` cache positions."""
        return -(-int(length) // self.block_size)

    def free_slots(self) -> int:
        return self.slots - len(self._slot_of)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    def block_utilization(self) -> float:
        cap = self.allocator.capacity
        return self.allocator.used_blocks / cap if cap else 0.0

    def keys_accounting(self) -> Dict[str, int]:
        """Key-position accounting for the NEXT decode step, from host
        state (no device traffic): ``attended_keys`` is what the math
        needs (sum of pos+1 over active lanes), ``kernel_keys`` is what
        the fused kernel touches (whole blocks: ceil((pos+1)/bs)·bs per
        lane), ``padded_keys`` is what the gathered path reduces over
        (t_cap per active lane).  attended/padded is the measurable
        skipped-work ratio the telemetry and BENCH_PAGED_ATTN report."""
        att = kern = n_active = 0
        for rid, slot in self._slot_of.items():
            if not self.active[slot]:
                continue
            ln = int(self._pos_host[slot]) + 1
            att += ln
            kern += -(-ln // self.block_size) * self.block_size
            n_active += 1
        return {"attended_keys": att,
                "kernel_keys": kern,
                "padded_keys": n_active * self.t_cap,
                "active_streams": n_active}

    # ---- prefix cache --------------------------------------------------
    def _on_cache_evict(self, block: int) -> None:
        """Allocator callback: a cached-free block is being reclaimed
        for fresh use — its prefix identity must die with it."""
        self.prefix.invalidate_block(block)
        self.cache_evictions += 1

    def _prefix_lookup(self, prompt_ids: List[int]
                       ) -> Tuple[List[Tuple[int, int]], Any, int]:
        """Longest prefix match of ``prompt_ids`` against the index:
        returns ``(entries, chain_key, matched_len)`` where ``entries``
        is ``[(block, used_tokens), ...]`` (all full ``block_size``
        chunks except possibly a final partial), ``chain_key`` is the
        index key after the FULL matches (the new stream's registration
        resumes there), and ``matched_len <= len(prompt) - 1`` — the
        last prompt token is always left to prefill so its logits can
        seed the first sampled token (the vLLM full-hit rule).

        Memoized on ``(prompt, index version)``: the scheduler's
        ``admit_need`` pre-check, the ``try_admit`` that follows it in
        the same tick, and a queue head re-polled across ticks while
        blocked all reuse one walk instead of re-hashing the prompt.
        Refcount churn cannot stale the cache — it changes how a matched
        block is PINNED (share vs reuse), which both callers read live,
        never which blocks match."""
        key = (tuple(prompt_ids), self.prefix.version)
        if self._lookup_memo is not None and self._lookup_memo[0] == key:
            return self._lookup_memo[1]
        out = self._prefix_walk(prompt_ids)
        self._lookup_memo = (key, out)
        return out

    def _prefix_walk(self, prompt_ids: List[int]
                     ) -> Tuple[List[Tuple[int, int]], Any, int]:
        p = len(prompt_ids)
        cap = p - 1             # never match the final prompt token
        bs = self.block_size
        entries: List[Tuple[int, int]] = []
        chain: Any = None
        off = 0
        while off + bs <= cap:
            key = (chain, tuple(prompt_ids[off:off + bs]))
            b = self.prefix.get(key)
            if b is None:
                break
            entries.append((b, bs))
            chain = key
            off += bs
        # partial tail: the longest registered chunk that prefixes the
        # remaining prompt (a FULL block's entry also serves here when
        # the cap truncates it — the overhang is recomputed after the
        # CoW fork); usable tokens stop at the cap
        for length in range(min(bs, p - off), 0, -1):
            b = self.prefix.get((chain, tuple(prompt_ids[off:off + length])))
            if b is not None:
                usable = min(length, cap - off)
                if usable > 0:
                    entries.append((b, usable))
                    off += usable
                break
        return entries, chain, off

    def admit_need(self, prompt_ids, max_new_tokens: int,
                   full_residency: bool = False) -> int:
        """Free-list consumption :meth:`try_admit` would require right
        now: the raw block count for prompt+1 (or the stream's FULL
        residency when ``full_residency`` — the scheduler's anti-thrash
        gate for previously evicted requests) minus the matched prefix
        blocks that are currently IN USE (shared references consume no
        free block; matched cached-FREE blocks still occupy a free-list
        slot), plus the one reserved CoW fork block when the match ends
        mid-block."""
        prompt_ids = [int(t) for t in prompt_ids]
        p = len(prompt_ids)
        base = self.blocks_for(p + max_new_tokens if full_residency
                               else p + 1)
        if not self.prefix_cache:
            return base
        entries, _, matched_len = self._prefix_lookup(prompt_ids)
        n_in_use = sum(1 for b, _ in entries
                       if self.allocator.refcount(b) > 0)
        fork = 1 if matched_len % self.block_size else 0
        return max(0, base - n_in_use + fork)

    def prefix_stats(self) -> Dict[str, int]:
        """Prefix-cache accounting (host arithmetic, no device traffic):
        cumulative hit/fork/eviction counters plus the instantaneous
        sharing state — ``shared_blocks`` is the number of allocations
        sharing is saving right now (sum of refcount-1 over shared
        blocks), ``cached_free_blocks`` the reusable content parked in
        the allocator's LRU."""
        return {
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prompt_tokens_admitted": self.prompt_tokens_admitted,
            "cow_forks": self.cow_forks,
            "cache_evictions": self.cache_evictions,
            "blocks_saved": self.blocks_shared_total,
            "shared_blocks": self.allocator.shared_extra,
            "cached_free_blocks": self.allocator.cached_free_blocks,
        }

    def shared_token_discount(self) -> int:
        """Upper-bound estimate of committed tokens double-counted by
        refcount sharing (each extra reference of a shared block holds
        at most ``block_size`` token positions once, not once per
        stream) — the scheduler subtracts this from its token-budget
        accounting so shared residency is not double-charged."""
        return self.allocator.shared_extra * self.block_size

    # ---- admission -----------------------------------------------------
    def try_admit(self, prompt_ids, max_new_tokens: int) -> Optional[int]:
        """Reserve a slot + the blocks covering the prompt and the first
        generated token; no model compute happens here (the scheduler
        interleaves the prefill chunks).  Under ``prefix_cache``, the
        longest indexed prefix of the prompt maps onto EXISTING blocks —
        in-use blocks gain a reference, cached-free blocks revive — and
        only the unmatched remainder allocates fresh (plus one reserved
        fork block when the match ends mid-block, so the copy-on-write
        fork can never fail mid-prefill); ``prefilled`` starts at the
        matched length, so the scheduler skips those prefill chunks
        entirely.  Returns a request id, or None when a slot or the
        blocks are unavailable.  Raises for a request this server could
        NEVER hold (over max_len, or more total blocks than the pool
        owns) — returning None there would make a retry loop spin
        forever."""
        prompt_ids = [int(t) for t in prompt_ids]
        p = len(prompt_ids)
        if p == 0:
            raise ValueError("empty prompt: a request needs at least one "
                             "token")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens {max_new_tokens} < 1")
        if p + max_new_tokens > self.max_len:
            raise ValueError(f"prompt {p} + {max_new_tokens} exceeds "
                             f"server max_len {self.max_len}")
        total_need = self.blocks_for(p + max_new_tokens)
        if total_need > self.allocator.capacity:
            raise ValueError(
                f"request needs {total_need} blocks but the pool only "
                f"has {self.allocator.capacity}: unservable at any load")
        if not self.free_slots():
            return None
        entries: List[Tuple[int, int]] = []
        chain: Any = None
        matched_len = 0
        if self.prefix_cache:
            entries, chain, matched_len = self._prefix_lookup(prompt_ids)
        partial = matched_len % self.block_size != 0
        # fresh blocks: the prompt+1 span not covered by the match, plus
        # the reserved CoW fork target for a mid-block match boundary
        need_fresh = (self.blocks_for(p + 1) - len(entries)
                      + (1 if partial else 0))
        n_reuse = sum(1 for b, _ in entries
                      if self.allocator.refcount(b) == 0)
        if need_fresh + n_reuse > self.allocator.free_blocks:
            return None
        # pin the matched blocks FIRST so the fresh allocation's LRU
        # eviction can never reclaim one of them
        for b, _ in entries:
            if self.allocator.refcount(b) > 0:
                self.allocator.share(b)
            else:
                self.allocator.reuse_cached(b)
        fresh = self.allocator.alloc(need_fresh) if need_fresh else []
        assert fresh is not None    # capacity checked above
        fork_reserve = fresh.pop() if partial else None
        if matched_len:
            self.prefix_hits += 1
            self.prefix_hit_tokens += matched_len
            self.blocks_shared_total += len(entries)
        elif self.prefix_cache:
            self.prefix_misses += 1
        self.prompt_tokens_admitted += p
        blocks = [b for b, _ in entries] + fresh
        n_full = len(entries) - (1 if partial else 0)
        slot = next(s for s in range(self.slots)
                    if s not in self._slot_of.values())
        rid = self._rid
        self._rid += 1
        st = _Stream(rid=rid, prompt=prompt_ids,
                     max_new=int(max_new_tokens),
                     target=p + int(max_new_tokens), blocks=blocks,
                     prefilled=matched_len, n_shared=len(entries),
                     fork_pending=fork_reserve, chain_key=chain,
                     registered_tokens=n_full * self.block_size,
                     shared_at_admit=matched_len)
        self._streams[rid] = st
        self._slot_of[rid] = slot
        # reset the slot BEFORE any prefill chunk: the batched step's
        # frozen-lane write for this slot is then the position-0 write
        # prefill itself performs (idempotent — see module docstring)
        self.tables[slot, :] = SINK_BLOCK
        self.tables[slot, :len(blocks)] = blocks
        row = np.zeros((self.t_cap,), np.int32)
        row[:p] = prompt_ids
        self.tokens = self.tokens.at[slot].set(jnp.asarray(row))
        self.pos = self.pos.at[slot].set(0)
        self._pos_host[slot] = 0
        self.active[slot] = False
        return rid

    def prefill_remaining(self, rid: int) -> int:
        """Prompt tokens not yet prefilled (0 = stream is decoding)."""
        st = self._streams[rid]
        return len(st.prompt) - st.prefilled

    def prefill_step(self, rid: int, width: int) -> bool:
        """Advance ``rid``'s prefill by up to ``width`` prompt tokens
        (one chunk, padded to a power-of-two bucket so compiled prefill
        programs stay O(log max_len)).  On the final chunk, samples the
        first output token and activates the stream.  Returns True when
        prefill is complete."""
        st = self._streams[rid]
        slot = self._slot_of[rid]
        p = len(st.prompt)
        # late match: a stream that found nothing at ADMISSION retries
        # the index once at its first prefill chunk — under burst
        # arrivals several shared-prompt requests admit in one tick
        # before any of them has registered a block, but streams prefill
        # FIFO, so by the time this one runs its predecessors' blocks
        # are indexed (the admission-time match alone would miss the
        # whole burst)
        if (self.prefix_cache and st.prefilled == 0
                and st.n_shared == 0):
            self._rematch_prefix(st, slot)
        remaining = p - st.prefilled
        if remaining <= 0:
            return True
        w = min(int(width), remaining)
        if w < 1:
            raise ValueError(f"prefill width {width} < 1")
        # copy-on-write: the FIRST write past the shared boundary lands
        # here when the matched prefix ended mid-block — fork the
        # borrowed partial block (reserved target, one on-device copy,
        # repoint, release the share) BEFORE the chunk writes into it
        if (st.fork_pending is not None
                and st.prefilled // self.block_size < st.n_shared):
            self._cow_fork(st, slot)
        # sink-invariant extension: every block this chunk writes must
        # be OWNED by the stream — a shared block is read-only
        assert st.prefilled // self.block_size >= st.n_shared, (
            f"prefill would write shared block of rid={rid}: "
            f"pos {st.prefilled} inside the first {st.n_shared} "
            "borrowed table entries")
        bucket = prefill_bucket(w)
        chunk = st.prompt[st.prefilled:st.prefilled + w] + [0] * (bucket - w)
        logits, self.pools = self._prefill_fn(
            self.params, self.pools,
            jnp.asarray(self.tables[slot:slot + 1]),
            jnp.asarray([st.prefilled], jnp.int32),
            jnp.asarray([chunk], jnp.int32),
            jnp.asarray(w, jnp.int32))
        st.prefilled += w
        self._register_prefix(st, final=st.prefilled >= p)
        if st.prefilled < p:
            return False
        t, tk, tp = self._sampling
        first_row, self.key = _sample(logits[:, w - 1], t, self.key, tk, tp)
        self.tokens = self.tokens.at[slot, p].set(first_row[0])
        self.pos = self.pos.at[slot].set(p)
        self._pos_host[slot] = p
        self.active[slot] = st.max_new > 1
        if st.max_new <= 1:
            self._finish(rid)
        return True

    def _cow_fork(self, st: _Stream, slot: int) -> None:
        """Fork the stream's borrowed partial tail block: copy the
        shared block's contents into the reserved fresh block on-device
        (traced src/dst — no recompile), repoint the table entry, drop
        the share.  After this the stream owns every block it will ever
        write; positions past the shared prefix inside the copy are
        overwritten by the stream's own prefill/decode writes before
        they are attended."""
        idx = st.n_shared - 1
        src, dst = st.blocks[idx], st.fork_pending
        self.pools = self._cow_fn(self.pools,
                                  jnp.asarray(src, jnp.int32),
                                  jnp.asarray(dst, jnp.int32))
        st.blocks[idx] = dst
        # repoint BEFORE releasing the share: once the table stops
        # naming src, this stream can never touch it again
        self.tables[slot, idx] = dst
        st.fork_pending = None
        st.n_shared = idx
        self.allocator.release([src])
        self.cow_forks += 1

    def _rematch_prefix(self, st: _Stream, slot: int) -> None:
        """Retry the prefix lookup for a stream that matched nothing at
        admission (see :meth:`prefill_step`): point its leading table
        entries at the now-indexed blocks, release the fresh blocks they
        displace (keeping one as the CoW fork reserve when the match
        ends mid-block), and reclassify the admission as a hit."""
        entries, chain, matched_len = self._prefix_lookup(st.prompt)
        if not matched_len:
            return
        partial = matched_len % self.block_size != 0
        n = len(entries)
        # pin the matched blocks before releasing the displaced ones so
        # the release cannot hand a matched cached-free block back out
        for b, _ in entries:
            if self.allocator.refcount(b) > 0:
                self.allocator.share(b)
            else:
                self.allocator.reuse_cached(b)
        displaced = st.blocks[:n]
        st.blocks[:n] = [b for b, _ in entries]
        st.fork_pending = displaced.pop() if partial else None
        self.allocator.release(displaced)
        self.tables[slot, :len(st.blocks)] = st.blocks
        st.n_shared = n
        st.chain_key = chain
        st.prefilled = matched_len
        st.registered_tokens = (n - (1 if partial else 0)) * self.block_size
        st.shared_at_admit = matched_len
        self.prefix_misses -= 1
        self.prefix_hits += 1
        self.prefix_hit_tokens += matched_len
        self.blocks_shared_total += n

    def _register_prefix(self, st: _Stream, final: bool) -> None:
        """Publish this stream's OWNED, fully-written prompt blocks into
        the prefix index (borrowed blocks are already there): every full
        ``block_size`` chunk covered by ``prefilled``, plus — once the
        prompt is complete — the partial tail.  The tail entry claims
        only the prompt positions; decode writes land past them, so the
        entry stays valid while the stream keeps generating."""
        if not self.prefix_cache:
            return
        bs = self.block_size
        p = len(st.prompt)
        while st.registered_tokens + bs <= st.prefilled:
            off = st.registered_tokens
            key = (st.chain_key, tuple(st.prompt[off:off + bs]))
            if off // bs >= st.n_shared:
                b = st.blocks[off // bs]
                if self.prefix.insert(key, b):
                    self.allocator.mark_cached(b)
            st.chain_key = key
            st.registered_tokens = off + bs
        if final and st.registered_tokens < p:
            off = st.registered_tokens
            key = (st.chain_key, tuple(st.prompt[off:p]))
            if off // bs >= st.n_shared:
                b = st.blocks[off // bs]
                if self.prefix.insert(key, b):
                    self.allocator.mark_cached(b)

    # ---- block growth / eviction --------------------------------------
    def needs_block(self) -> List[int]:
        """Rids of active streams whose NEXT decode write crosses into an
        unallocated block."""
        out = []
        for rid, slot in self._slot_of.items():
            if not self.active[slot]:
                continue
            nxt = int(self._pos_host[slot]) + 1
            if nxt < self.t_cap and \
                    nxt // self.block_size >= len(self._streams[rid].blocks):
                out.append(rid)
        return out

    def ensure_blocks(self) -> List[int]:
        """Grow every stream that needs its next block; returns the rids
        the pool could NOT satisfy (the scheduler's eviction trigger)."""
        short = []
        for rid in self.needs_block():
            got = self.allocator.alloc(1)
            if got is None:
                short.append(rid)
                continue
            st = self._streams[rid]
            slot = self._slot_of[rid]
            self.tables[slot, len(st.blocks)] = got[0]
            st.blocks.extend(got)
        return short

    def _release_stream(self, st: _Stream, slot: int) -> None:
        """THE single stream-release path (_finish and evict both land
        here): zero the table to the sink FIRST — the next step's
        frozen-lane write must go to the sink, never into a block
        someone else holds — then drop one reference per block through
        :meth:`BlockAllocator.release`, including the unused CoW fork
        reserve.  A shared block survives at refcount >= 1 for its other
        readers; an owned cached block parks in the cached-free LRU; a
        double release is a hard error by the allocator's contract."""
        self.tables[slot, :] = SINK_BLOCK
        rel = list(st.blocks)
        if st.fork_pending is not None:
            rel.append(st.fork_pending)
            st.fork_pending = None
        st.blocks = []
        self.allocator.release(rel)
        self.active[slot] = False

    def evict(self, rid: int):
        """Preempt ``rid``: release its block references (table zeroed
        to the sink first, so the frozen lane cannot touch live blocks)
        and forget the stream.  Returns ``(prompt_ids,
        max_new_tokens)`` for the caller to requeue; generated tokens
        are discarded and recomputed on re-admission (greedy re-runs
        reproduce them exactly — and under ``prefix_cache`` the re-run
        usually re-matches the very blocks this eviction parked in the
        cached-free LRU)."""
        st = self._streams.pop(rid)
        slot = self._slot_of.pop(rid)
        self._release_stream(st, slot)
        return list(st.prompt), st.max_new

    # ---- block handoff (disaggregated prefill/decode) -----------------
    def _handoff_geometry(self) -> Dict[str, Any]:
        """The pool facts both sides of a handoff must agree on byte-for-
        byte.  Everything here is static server config, so a mismatch is
        a deployment error (raise), never a transient to retry."""
        return {
            "block_size": self.block_size,
            "n_layers": len(self.pools),
            "kv_heads": int(self.model.cfg.kv_heads),
            "head_dim": int(self.model.cfg.head_dim),
            "kv_quant": self.kv_quant,
            "dtype": str(np.dtype(
                np.asarray(jax.device_get(self.pools[0]["k"][:1])).dtype)),
        }

    def export_stream(self, rid: int) -> Dict[str, Any]:
        """Serialize a prefill-complete stream for handoff to a decode
        server: the block CONTENTS covering the written prompt positions
        (per layer, K/V and int8 scale pools alike, base64 of the raw
        device bytes — ``tobytes``/``frombuffer`` round-trips every
        dtype exactly, bf16 included), the prompt, and the first sampled
        token.  Read-only: the stream keeps running here until the
        caller explicitly releases it (``evict``), so a failed handoff
        costs nothing.  Only positions ``0..p-1`` have K/V (the first
        sampled token's K/V is written by its decode step, which runs on
        the importing side) — so exactly ``blocks_for(p)`` block rows
        travel.  Raises for a stream whose prefill is not complete."""
        st = self._streams[rid]
        slot = self._slot_of[rid]
        p = len(st.prompt)
        if st.prefilled < p:
            raise ValueError(
                f"export of rid={rid} with prefill incomplete "
                f"({st.prefilled}/{p}): handoff happens at the "
                "prefill->decode boundary only")
        n_copy = self.blocks_for(p)
        idx = jnp.asarray(np.asarray(st.blocks[:n_copy], np.int64))
        layers = []
        for pool in self.pools:
            rec = {}
            for name, arr in pool.items():
                rows = np.ascontiguousarray(
                    np.asarray(jax.device_get(arr[idx])))
                rec[name] = base64.b64encode(rows.tobytes()).decode("ascii")
            layers.append(rec)
        first_token = int(jax.device_get(self.tokens[slot, p]))
        self.handoffs_exported += 1
        return {
            "v": 1,
            "prompt": list(st.prompt),
            "max_new": int(st.max_new),
            "first_token": first_token,
            "n_blocks": n_copy,
            "geom": self._handoff_geometry(),
            "layers": layers,
        }

    def import_stream(self, payload: Dict[str, Any]) -> Optional[int]:
        """Admit a handed-off stream directly in the DECODING state:
        allocate fresh blocks, scatter the exported block contents into
        them on-device (one traced-dst program — block-id churn never
        recompiles), rebuild the token row (prompt + first sampled
        token), and register the prompt blocks into the local prefix
        index so later arrivals sharing the prompt hit the cache here
        too.  Returns a request id, or None when a slot or the blocks
        are unavailable (nothing consumed — the router retries or falls
        back).  Raises on geometry mismatch or a request this server
        could never hold, mirroring :meth:`try_admit`'s contract."""
        geom = dict(payload["geom"])
        mine = self._handoff_geometry()
        if geom != mine:
            raise ValueError(f"handoff geometry mismatch: exporter "
                             f"{geom} vs importer {mine}")
        prompt_ids = [int(t) for t in payload["prompt"]]
        max_new = int(payload["max_new"])
        p = len(prompt_ids)
        if p == 0:
            raise ValueError("empty prompt in handoff payload")
        if max_new < 1:
            raise ValueError(f"max_new_tokens {max_new} < 1")
        if p + max_new > self.max_len:
            raise ValueError(f"prompt {p} + {max_new} exceeds server "
                             f"max_len {self.max_len}")
        total_need = self.blocks_for(p + max_new)
        if total_need > self.allocator.capacity:
            raise ValueError(
                f"request needs {total_need} blocks but the pool only "
                f"has {self.allocator.capacity}: unservable at any load")
        n_copy = int(payload["n_blocks"])
        if n_copy != self.blocks_for(p):
            raise ValueError(f"handoff carries {n_copy} blocks, prompt "
                             f"of {p} needs {self.blocks_for(p)}")
        if not self.free_slots():
            return None
        need = self.blocks_for(p + 1)
        blocks = self.allocator.alloc(need)
        if blocks is None:
            return None
        # decode the per-layer block rows; shapes are fixed by geometry,
        # so a short buffer is a hard error, not a retry
        bs = self.block_size
        kv, hd = mine["kv_heads"], mine["head_dim"]
        decoded = []
        for li, rec in enumerate(payload["layers"]):
            pool = self.pools[li]
            out = {}
            for name, b64 in rec.items():
                arr = np.asarray(jax.device_get(pool[name][:1]))
                shape = (n_copy, bs, kv) if name.endswith("_scale") \
                    else (n_copy, bs, kv, hd)
                raw = np.frombuffer(base64.b64decode(b64),
                                    dtype=arr.dtype).reshape(shape)
                out[name] = raw
            decoded.append(out)
        for i in range(n_copy):
            rows = [{name: jnp.asarray(lay[name][i])
                     for name in lay} for lay in decoded]
            self.pools = self._import_fn(
                self.pools, rows, jnp.asarray(blocks[i], jnp.int32))
        rid = self._rid
        self._rid += 1
        st = _Stream(rid=rid, prompt=prompt_ids, max_new=max_new,
                     target=p + max_new, blocks=blocks, prefilled=p)
        slot = next(s for s in range(self.slots)
                    if s not in self._slot_of.values())
        self._streams[rid] = st
        self._slot_of[rid] = slot
        self.tables[slot, :] = SINK_BLOCK
        self.tables[slot, :len(blocks)] = blocks
        row = np.zeros((self.t_cap,), np.int32)
        row[:p] = prompt_ids
        row[p] = int(payload["first_token"])
        self.tokens = self.tokens.at[slot].set(jnp.asarray(row))
        self.pos = self.pos.at[slot].set(p)
        self._pos_host[slot] = p
        self.active[slot] = max_new > 1
        self.prompt_tokens_admitted += p
        self.handoffs_imported += 1
        self._register_prefix(st, final=True)
        if max_new <= 1:
            # degenerate single-token request: already complete (the
            # prefill side normally finishes these without a handoff)
            self._finish(rid)
        return rid

    # ---- decode --------------------------------------------------------
    def step(self) -> List[int]:
        """One batched decode step across all slots; returns the rids
        that finished this step.  Completion comes from host-side
        position counters — no device fetch.  Raises
        :class:`BlockExhausted` when a stream's next write has no block
        (call :meth:`ensure_blocks` / evict first)."""
        if not self.active.any():
            return []
        short = self.ensure_blocks()
        if short:
            raise BlockExhausted(short)
        # sink-invariant extension for sharing: an active lane's decode
        # write position must sit in a block the stream OWNS (decode
        # positions start past the prompt, and the CoW fork ran during
        # the suffix prefill — so this can only fire on a bookkeeping
        # bug, which must not silently corrupt a shared block)
        for rid, slot in self._slot_of.items():
            if self.active[slot]:
                st = self._streams[rid]
                assert (int(self._pos_host[slot]) // self.block_size
                        >= st.n_shared), (
                    f"decode would write shared block of rid={rid}")
        # non-active lanes (free, finished, MID-PREFILL) see an all-sink
        # table: their writes land in the sink and their reads gather
        # garbage that is discarded — so live blocks are written ONLY by
        # prefill chunks and active decode lanes, and parity never rests
        # on a frozen lane recomputing bitwise-identical K/V under a
        # different batch shape
        masked = np.where(self.active[:, None], self.tables, SINK_BLOCK)
        self.pools, self.tokens, self.pos, self.key = self._step_fn(
            self.params, self.pools, self.tokens,
            jnp.asarray(masked), self.pos,
            jnp.asarray(self.active), self.key)
        finished = []
        for rid, slot in list(self._slot_of.items()):
            if not self.active[slot]:
                continue
            self._pos_host[slot] += 1
            if self._pos_host[slot] + 1 >= self._streams[rid].target:
                self._finish(rid)
                finished.append(rid)
        return finished

    def _finish(self, rid: int) -> None:
        st = self._streams.pop(rid)
        slot = self._slot_of.pop(rid)
        row = np.asarray(jax.device_get(self.tokens[slot]))
        self._results[rid] = [int(t) for t in row[:st.target]]
        self._release_stream(st, slot)

    # ---- results -------------------------------------------------------
    def done(self, rid: int) -> bool:
        if rid in self._results:
            return True
        if rid in self._streams:
            return False
        raise KeyError(f"request {rid}: unknown or already consumed")

    def result(self, rid: int) -> List[int]:
        """Prompt + generated ids for a finished request (pops it)."""
        return self._results.pop(rid)

    def live(self) -> int:
        return len(self._streams)

    def any_active(self) -> bool:
        return bool(self.active.any())
