"""Paged KV cache: block-allocated pools + static-shape gathered attention.

The dense slot server (``models/serve.py``) reserves ``max_len`` cache
positions per slot the moment a stream is admitted, so device memory is
spent on the WORST-case length of every stream simultaneously — the
classic serving waste paged attention removes (vLLM, Kwon et al. 2023;
the TPU angle is that everything must stay static-shape so one compiled
step serves any mix of lengths).  Here the cache is a pool of fixed-size
blocks:

* **Pools**: per layer, ``k``/``v`` of shape ``(num_blocks, block_size,
  kv_heads, head_dim)`` (plus f32 scale pools under ``kv_quant`` — the
  same int8 scheme as :func:`models.generate.init_kv_cache`, quantized
  per (position, head) so block boundaries never change the numbers).
* **Block tables**: per slot, ``(max_blocks,)`` int32 indices into the
  pool, host-owned (a tiny traced argument each step — never a
  recompile).  Unallocated entries point at the reserved **sink block
  0**, which is never handed to a stream: pad/frozen writes land there
  harmlessly and are never attended.
* **Attention dispatch** (``attn_impl``): the default **gathered** path
  gathers each row's blocks ``pool[table] -> (T_cap, kv_heads,
  head_dim)`` (``T_cap = max_blocks * block_size``) and attends under
  the causal mask ``t <= pos`` — the same reduction, over the same
  values in the same order, as the dense cache path, which is why
  greedy paged decode is token-identical to ``DecodeServer`` /
  ``models.generate.generate`` (pinned by tests/test_serve_paged.py).
  The gather materializes the attended window transiently (what dense
  attention reads anyway); the win is the PERSISTENT allocation, which
  now tracks actual tokens in flight instead of slots x max_len.  The
  **fused** path (``ops.pallas_kernels.paged_attention``) adds the
  FLOPs/bandwidth win on top: the Pallas kernel reads K/V straight from
  the pool through the tables and walks only ``ceil(len/block_size)``
  blocks per stream — token-identical to gathered, pinned by
  tests/test_paged_attn.py.
* **Writes** are scatters at ``(table[pos // block_size], pos %
  block_size)`` — one position per row at decode, a chunk of positions
  at prefill (chunks may straddle block boundaries; each position
  resolves its own block).

Invariant the step relies on (mirrors the dense server's "dead lanes
cost FLOPs, not recompiles" contract): every slot flows through the
batched step every tick, but live blocks are written ONLY by prefill
chunks and ACTIVE decode lanes.  ``step()`` masks every non-active
slot's table row to the sink (free, finished, and mid-prefill slots
alike), so a dead lane's unconditional write lands in the sink and its
gathered read is discarded garbage — parity never rests on a frozen
lane recomputing bitwise-identical K/V, and a finished/evicted slot's
table is additionally zeroed BEFORE its blocks are freed so nothing can
touch a block someone else just allocated.

Completion is detected from HOST-tracked position counters (positions
advance deterministically, one per active slot per step), so the decode
loop performs zero per-token device syncs — the discipline the trainer's
monitor uses, taken to its limit (see the satellite fix in
``models/serve.py``).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import _quantize_kv, _sample
from ..models.transformer import Transformer, split_qkv
from ..ops.pallas_kernels import paged_attention

Pytree = Any

# attention dispatch seam: 'gathered' materializes pool[table] and reduces
# over all max_blocks*block_size key positions per stream (the parity
# reference); 'fused' reads K/V straight from the block pool via the
# Pallas paged-attention kernel and stops at each stream's true length
# (ops.pallas_kernels.paged_attention — token-identical, pinned)
ATTN_IMPLS = ("gathered", "fused")

# block 0 is reserved: pad positions and frozen slots write (and gather)
# here, so a scatter never needs dynamic masking to be allocation-safe
SINK_BLOCK = 0


def prefill_bucket(width: int) -> int:
    """The pow2 bucket a prefill chunk of ``width`` tokens pads to
    (minimum 8) — the rule :meth:`PagedDecodeServer.prefill_step`
    compiles against, shared with ``serve.loadgen.prewarm`` so the
    warmed bucket set can never drift from the compiled set."""
    b = 8
    while b < width:
        b *= 2
    return b


class BlockExhausted(RuntimeError):
    """The pool cannot supply the next block for one or more streams;
    carries the starving request ids so a scheduler can pick a victim."""

    def __init__(self, rids: List[int]):
        super().__init__(f"KV block pool exhausted; streams needing a "
                         f"block: {rids}")
        self.rids = list(rids)


class BlockAllocator:
    """Free-list allocator over block ids ``1..num_blocks-1`` (0 is the
    sink).  Leak-proof by construction: every id is either in the free
    list or in ``in_use``, ``free()`` of a foreign/double-freed id raises,
    and :meth:`assert_drained` pins the balance at zero after a drain
    (the fuzz test's invariant)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"num_blocks {num_blocks} < 2: block 0 is "
                             "the reserved sink, so a usable pool needs "
                             "at least one more")
        self.num_blocks = int(num_blocks)
        # pop from the tail -> ascending ids hand out first (stable tests)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._in_use: set = set()

    @property
    def capacity(self) -> int:
        """Usable blocks (the sink is not allocatable)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._in_use)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` block ids, or None when the pool cannot satisfy the
        request (all-or-nothing: no partial grants to roll back)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._in_use.update(out)
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b not in self._in_use:
                raise ValueError(f"free of block {b} not in use (double "
                                 "free or foreign id)")
            self._in_use.remove(b)
            self._free.append(b)

    def assert_drained(self) -> None:
        if self._in_use:
            raise AssertionError(f"block leak: {sorted(self._in_use)} "
                                 "still in use after drain")
        if len(self._free) != self.capacity:
            raise AssertionError(
                f"free-list balance {len(self._free)} != capacity "
                f"{self.capacity}")


def init_paged_kv(model: Transformer, num_blocks: int, block_size: int,
                  quant: bool = False):
    """Per-layer paged pools ``(num_blocks, block_size, kv_heads,
    head_dim)`` — :func:`models.generate.init_kv_cache` with the length
    axis split into (block, offset).  ``quant=True`` stores int8 codes
    plus one f32 scale per (block, offset, head), the identical scheme
    the dense cache uses (scales are per position, so paging cannot
    change the numbers)."""
    c = model.cfg
    shape = (num_blocks, block_size, c.kv_heads, c.head_dim)
    if quant:
        zeros = lambda: jnp.zeros(shape, jnp.int8)          # noqa: E731
        ones = lambda: jnp.ones(shape[:-1], jnp.float32)    # noqa: E731
        return [{"k": zeros(), "v": zeros(),
                 "k_scale": ones(), "v_scale": ones()}
                for _ in range(c.n_layers)]
    zeros = lambda: jnp.zeros(shape, c.compute_dtype)       # noqa: E731
    return [{"k": zeros(), "v": zeros()} for _ in range(c.n_layers)]


@functools.lru_cache(maxsize=8)
def _paged_programs(model: Transformer, block_size: int, max_blocks: int,
                    temperature: float, top_k: int, top_p: float,
                    kv_quant: bool = False, attn_impl: str = "gathered"):
    """The two jitted programs of a paged server: chunk prefill (one per
    power-of-two chunk bucket, via jit's shape cache) and the batched
    decode step.  Cached per (model, geometry, sampling, attn_impl) so
    several servers compile once.  ``attn_impl='fused'`` swaps the
    gathered attention for the Pallas paged kernel; everything else
    (scatter coordinates, sampling, bookkeeping) is shared, which is what
    makes gathered-vs-fused an attention-only A/B."""
    bs, mb = int(block_size), int(max_blocks)
    t_cap = bs * mb
    c = model.cfg
    if attn_impl not in ATTN_IMPLS:
        raise ValueError(f"attn_impl must be one of {ATTN_IMPLS}, "
                         f"got {attn_impl!r}")

    def block_fwd(layer_params, pool, tables, starts, x, valid, lengths):
        """One transformer block over a chunk ``x`` (B, W, D) whose rows
        sit at per-row start positions, K/V scattered into the paged
        pool and attention read back through the block tables — gathered
        (``pool[table]`` then a full-width masked reduction) or fused
        (the paged kernel walks only ``ceil(lengths/bs)`` live blocks).
        Mirrors ``models.generate._block_chunk`` (the pinned dense
        math) with the cache axis split into (block, offset).  ``valid``
        (W,) masks pad columns of a bucketed prefill chunk: their writes
        divert to the sink block.  ``lengths`` (B,) is each row's
        attendable-key count (0 = inactive lane), traced like the
        tables so length churn never recompiles."""
        mods = model._block_modules()
        h = mods["ln1"].apply(layer_params["ln1"], x)
        qkv = mods["qkv"].apply(layer_params["qkv"], h)
        b, w, _ = qkv.shape
        q, k, v = split_qkv(c, qkv)   # q: (B,W,H,hd); k/v: (B,W,KV,hd)
        positions = starts[:, None] + jnp.arange(w)[None, :]    # (B, W)
        if c.pos_encoding == "rope":
            from ..ops.rope import rope_rotate

            q = rope_rotate(q, positions, c.rope_theta)
            k = rope_rotate(k, positions, c.rope_theta)
        # scatter coordinates: each position resolves its own block via
        # the row's table (chunks straddle block boundaries freely); pad
        # columns land in the sink
        blk = jnp.take_along_axis(tables, positions // bs, axis=1)
        blk = jnp.where(valid[None, :], blk, SINK_BLOCK)
        off = jnp.where(valid[None, :], positions % bs, 0)
        quant = "k_scale" in pool
        if quant:
            k, ks = _quantize_kv(k)
            v, vs = _quantize_kv(v)
            new_ksp = pool["k_scale"].at[blk, off].set(ks)
            new_vsp = pool["v_scale"].at[blk, off].set(vs)
        new_kp = pool["k"].at[blk, off].set(k.astype(pool["k"].dtype))
        new_vp = pool["v"].at[blk, off].set(v.astype(pool["v"].dtype))
        if attn_impl == "fused":
            # the Pallas kernel reads K/V straight from the pool through
            # the tables and reduces over each row's TRUE length — no
            # pool[table] materialization, no max_blocks*bs reduction.
            # int8 scale pools ride in and dequantize on load.
            out = paged_attention(
                q, new_kp, new_vp, tables, lengths, starts,
                k_scale=new_ksp if quant else None,
                v_scale=new_vsp if quant else None).astype(x.dtype)
        else:
            # gather each row's attended window: (B, MB, bs, kv, hd) ->
            # (B, T_cap, kv, hd), positions in ascending order — the
            # same values, same order, as the dense cache's
            # (B, T, kv, hd) slab
            gk = new_kp[tables].reshape(b, t_cap, c.kv_heads, c.head_dim)
            gv = new_vp[tables].reshape(b, t_cap, c.kv_heads, c.head_dim)
            scale = 1.0 / jnp.sqrt(jnp.asarray(c.head_dim, jnp.float32))
            mask = (jnp.arange(t_cap)[None, None, :]
                    <= positions[:, :, None])           # (B, W, T_cap)
            if quant:
                gks = new_ksp[tables].reshape(b, t_cap, c.kv_heads)
                gvs = new_vsp[tables].reshape(b, t_cap, c.kv_heads)
            if c.kv_heads == c.n_heads:
                logits = jnp.einsum("bqhd,bkhd->bhqk",
                                    q.astype(jnp.float32),
                                    gk.astype(jnp.float32)) * scale
                if quant:
                    logits = logits * gks.transpose(0, 2, 1)[:, :, None, :]
                logits = jnp.where(mask[:, None], logits, -1e30)
                probs = jax.nn.softmax(logits, axis=-1)
                if quant:
                    probs = probs * gvs.transpose(0, 2, 1)[:, :, None, :]
                out = jnp.einsum("bhqk,bkhd->bqhd", probs,
                                 gv.astype(jnp.float32)).astype(x.dtype)
            else:
                g = c.n_heads // c.kv_heads
                q5 = q.reshape(b, w, c.kv_heads, g, c.head_dim)
                logits = jnp.einsum("bqcgd,bkcd->bcgqk",
                                    q5.astype(jnp.float32),
                                    gk.astype(jnp.float32)) * scale
                if quant:
                    logits = logits * gks.transpose(0, 2, 1)[:, :, None,
                                                             None, :]
                logits = jnp.where(mask[:, None, None], logits, -1e30)
                probs = jax.nn.softmax(logits, axis=-1)
                if quant:
                    probs = probs * gvs.transpose(0, 2, 1)[:, :, None,
                                                           None, :]
                out = jnp.einsum("bcgqk,bkcd->bqcgd", probs,
                                 gv.astype(jnp.float32)).astype(x.dtype)
                out = out.reshape(b, w, c.n_heads, c.head_dim)
        out = out.reshape(b, w, c.d_model)
        x = x + mods["attn_out"].apply(layer_params["attn_out"], out)
        h = mods["ln2"].apply(layer_params["ln2"], x)
        if c.moe_experts > 0:
            ff, _ = mods["moe"].apply(layer_params["moe"], h)
        else:
            ff = model._ffn(mods, layer_params, h)
        new_pool = {"k": new_kp, "v": new_vp}
        if quant:
            new_pool.update(k_scale=new_ksp, v_scale=new_vsp)
        return x + ff.astype(x.dtype), new_pool

    def forward(params, pools, tables, starts, ids, valid, lengths):
        # clamp pad columns' embedding positions into range (their
        # outputs are discarded; learned positional tables have no row
        # past max_seq_len)
        w = ids.shape[1]
        emb_pos = jnp.minimum(starts[:, None] + jnp.arange(w)[None, :],
                              c.max_seq_len - 1)
        x = model.embed(params, ids, emb_pos)
        new_pools = []
        for layer_params, pool in zip(params["blocks"], pools):
            x, pool = block_fwd(layer_params, pool, tables, starts, x,
                                valid, lengths)
            new_pools.append(pool)
        return model.head_logits(params, x), new_pools

    def prefill(params, pools, table, start, chunk, true_w):
        # chunk (1, W_bucket) int32; logits for ALL columns return and
        # the caller indexes the true last position (same contract as
        # the dense server's bucketed prefill).  attendable keys after
        # this chunk's writes: everything up to start + true_w (pad
        # columns wrote to the sink, which is past every length)
        valid = jnp.arange(chunk.shape[1]) < true_w
        return forward(params, pools, table, start, chunk, valid,
                       start + true_w)

    def step(params, pools, tokens, tables, pos, active, key):
        s = tokens.shape[0]
        cap = tokens.shape[1] - 1
        ids = jnp.take_along_axis(tokens, pos[:, None], axis=1)  # (S, 1)
        # a decode row attends its own fresh write too: pos + 1 keys;
        # inactive lanes carry length 0, so the fused kernel walks ZERO
        # of their blocks (the gathered path computes-and-discards them)
        lengths = jnp.where(active, pos + 1, 0)
        logits, new_pools = forward(params, pools, tables, pos, ids,
                                    jnp.ones((1,), bool), lengths)
        nxt, key = _sample(logits[:, 0], temperature, key, top_k, top_p)
        # frozen slots re-write the token already there (idempotent) and
        # hold position — the dense server's exact bookkeeping
        nxt = jnp.where(active, nxt, jnp.take_along_axis(
            tokens, jnp.minimum(pos + 1, cap)[:, None], axis=1)[:, 0])
        write_at = jnp.minimum(pos + 1, cap)
        tokens = tokens.at[jnp.arange(s), write_at].set(nxt)
        pos = jnp.where(active, jnp.minimum(pos + 1, cap), pos)
        return new_pools, tokens, pos, key

    # compile-ledger seam (utils/compile_ledger): while a ledger is
    # installed every distinct compile of the serve programs is recorded
    # — which is how the "block-table churn never recompiles" invariant
    # becomes a production assertion instead of a test-only cache count
    # (tables/lengths are traced args; only a NEW prefill bucket width
    # may legitimately add an entry)
    from ..utils import compile_ledger as ledger_lib

    tag = (f"bs{bs}x{mb}" + ("/int8" if kv_quant else "")
           + f"/{attn_impl}")
    return (ledger_lib.instrument(jax.jit(prefill, donate_argnums=(1,)),
                                  f"serve_prefill[{tag}]"),
            ledger_lib.instrument(jax.jit(step, donate_argnums=(1, 2, 4)),
                                  f"serve_decode[{tag}]"))


@dataclass
class _Stream:
    """Host bookkeeping for one in-flight request."""
    rid: int
    prompt: List[int]
    max_new: int
    target: int                       # prompt_len + max_new
    blocks: List[int] = field(default_factory=list)
    prefilled: int = 0                # prompt tokens written so far


class PagedDecodeServer:
    """Slot server over a paged KV pool: same host contract as the dense
    ``DecodeServer`` (submit/step/done/result), plus the paged-runtime
    surface a scheduler drives — partial (chunked) prefill, on-demand
    block growth, eviction, and free-block/slot introspection."""

    def __init__(self, model: Transformer, params: Pytree, *,
                 slots: int = 8, num_blocks: int = 64,
                 block_size: int = 16, max_len: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0,
                 kv_quant: bool = False, attn_impl: str = "gathered"):
        c = model.cfg
        self.model, self.params = model, params
        self.slots = int(slots)
        self.block_size = int(block_size)
        self.max_len = int(max_len or c.max_seq_len)
        if self.max_len > c.max_seq_len:
            raise ValueError(f"max_len {self.max_len} exceeds model "
                             f"max_seq_len {c.max_seq_len}")
        self.max_blocks = -(-self.max_len // self.block_size)   # ceil
        self.t_cap = self.max_blocks * self.block_size
        self.num_blocks = int(num_blocks)
        self.allocator = BlockAllocator(self.num_blocks)
        self._sampling = (float(temperature), int(top_k), float(top_p))
        self.kv_quant = bool(kv_quant)
        if attn_impl not in ATTN_IMPLS:
            raise ValueError(f"attn_impl must be one of {ATTN_IMPLS}, "
                             f"got {attn_impl!r}")
        self.attn_impl = attn_impl
        self._prefill_fn, self._step_fn = _paged_programs(
            model, self.block_size, self.max_blocks, *self._sampling,
            self.kv_quant, self.attn_impl)
        self.pools = init_paged_kv(model, self.num_blocks,
                                   self.block_size, quant=self.kv_quant)
        self.tokens = jnp.zeros((self.slots, self.t_cap), jnp.int32)
        self.pos = jnp.zeros((self.slots,), jnp.int32)
        self.tables = np.zeros((self.slots, self.max_blocks), np.int32)
        self.active = np.zeros((self.slots,), bool)     # decoding slots
        self._pos_host = np.zeros((self.slots,), np.int64)
        self.key = jax.random.PRNGKey(seed)
        self._rid = 0
        self._streams: Dict[int, _Stream] = {}
        self._slot_of: Dict[int, int] = {}
        self._results: Dict[int, List[int]] = {}
        if c.scan_layers:
            params = dict(params)
            stacked = params["blocks"]
            params["blocks"] = [
                jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
                for i in range(c.n_layers)]
            self.params = params

    # ---- geometry ------------------------------------------------------
    def blocks_for(self, length: int) -> int:
        """Blocks needed to hold ``length`` cache positions."""
        return -(-int(length) // self.block_size)

    def free_slots(self) -> int:
        return self.slots - len(self._slot_of)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    def block_utilization(self) -> float:
        cap = self.allocator.capacity
        return self.allocator.used_blocks / cap if cap else 0.0

    def keys_accounting(self) -> Dict[str, int]:
        """Key-position accounting for the NEXT decode step, from host
        state (no device traffic): ``attended_keys`` is what the math
        needs (sum of pos+1 over active lanes), ``kernel_keys`` is what
        the fused kernel touches (whole blocks: ceil((pos+1)/bs)·bs per
        lane), ``padded_keys`` is what the gathered path reduces over
        (t_cap per active lane).  attended/padded is the measurable
        skipped-work ratio the telemetry and BENCH_PAGED_ATTN report."""
        att = kern = n_active = 0
        for rid, slot in self._slot_of.items():
            if not self.active[slot]:
                continue
            ln = int(self._pos_host[slot]) + 1
            att += ln
            kern += -(-ln // self.block_size) * self.block_size
            n_active += 1
        return {"attended_keys": att,
                "kernel_keys": kern,
                "padded_keys": n_active * self.t_cap,
                "active_streams": n_active}

    # ---- admission -----------------------------------------------------
    def try_admit(self, prompt_ids, max_new_tokens: int) -> Optional[int]:
        """Reserve a slot + the blocks covering the prompt and the first
        generated token; no model compute happens here (the scheduler
        interleaves the prefill chunks).  Returns a request id, or None
        when a slot or the initial blocks are unavailable.  Raises for a
        request this server could NEVER hold (over max_len, or more
        total blocks than the pool owns) — returning None there would
        make a retry loop spin forever."""
        prompt_ids = [int(t) for t in prompt_ids]
        p = len(prompt_ids)
        if p == 0:
            raise ValueError("empty prompt: a request needs at least one "
                             "token")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens {max_new_tokens} < 1")
        if p + max_new_tokens > self.max_len:
            raise ValueError(f"prompt {p} + {max_new_tokens} exceeds "
                             f"server max_len {self.max_len}")
        total_need = self.blocks_for(p + max_new_tokens)
        if total_need > self.allocator.capacity:
            raise ValueError(
                f"request needs {total_need} blocks but the pool only "
                f"has {self.allocator.capacity}: unservable at any load")
        if not self.free_slots():
            return None
        blocks = self.allocator.alloc(self.blocks_for(p + 1))
        if blocks is None:
            return None
        slot = next(s for s in range(self.slots)
                    if s not in self._slot_of.values())
        rid = self._rid
        self._rid += 1
        st = _Stream(rid=rid, prompt=prompt_ids,
                     max_new=int(max_new_tokens),
                     target=p + int(max_new_tokens), blocks=blocks)
        self._streams[rid] = st
        self._slot_of[rid] = slot
        # reset the slot BEFORE any prefill chunk: the batched step's
        # frozen-lane write for this slot is then the position-0 write
        # prefill itself performs (idempotent — see module docstring)
        self.tables[slot, :] = SINK_BLOCK
        self.tables[slot, :len(blocks)] = blocks
        row = np.zeros((self.t_cap,), np.int32)
        row[:p] = prompt_ids
        self.tokens = self.tokens.at[slot].set(jnp.asarray(row))
        self.pos = self.pos.at[slot].set(0)
        self._pos_host[slot] = 0
        self.active[slot] = False
        return rid

    def prefill_remaining(self, rid: int) -> int:
        """Prompt tokens not yet prefilled (0 = stream is decoding)."""
        st = self._streams[rid]
        return len(st.prompt) - st.prefilled

    def prefill_step(self, rid: int, width: int) -> bool:
        """Advance ``rid``'s prefill by up to ``width`` prompt tokens
        (one chunk, padded to a power-of-two bucket so compiled prefill
        programs stay O(log max_len)).  On the final chunk, samples the
        first output token and activates the stream.  Returns True when
        prefill is complete."""
        st = self._streams[rid]
        slot = self._slot_of[rid]
        p = len(st.prompt)
        remaining = p - st.prefilled
        if remaining <= 0:
            return True
        w = min(int(width), remaining)
        if w < 1:
            raise ValueError(f"prefill width {width} < 1")
        bucket = prefill_bucket(w)
        chunk = st.prompt[st.prefilled:st.prefilled + w] + [0] * (bucket - w)
        logits, self.pools = self._prefill_fn(
            self.params, self.pools,
            jnp.asarray(self.tables[slot:slot + 1]),
            jnp.asarray([st.prefilled], jnp.int32),
            jnp.asarray([chunk], jnp.int32),
            jnp.asarray(w, jnp.int32))
        st.prefilled += w
        if st.prefilled < p:
            return False
        t, tk, tp = self._sampling
        first_row, self.key = _sample(logits[:, w - 1], t, self.key, tk, tp)
        self.tokens = self.tokens.at[slot, p].set(first_row[0])
        self.pos = self.pos.at[slot].set(p)
        self._pos_host[slot] = p
        self.active[slot] = st.max_new > 1
        if st.max_new <= 1:
            self._finish(rid)
        return True

    # ---- block growth / eviction --------------------------------------
    def needs_block(self) -> List[int]:
        """Rids of active streams whose NEXT decode write crosses into an
        unallocated block."""
        out = []
        for rid, slot in self._slot_of.items():
            if not self.active[slot]:
                continue
            nxt = int(self._pos_host[slot]) + 1
            if nxt < self.t_cap and \
                    nxt // self.block_size >= len(self._streams[rid].blocks):
                out.append(rid)
        return out

    def ensure_blocks(self) -> List[int]:
        """Grow every stream that needs its next block; returns the rids
        the pool could NOT satisfy (the scheduler's eviction trigger)."""
        short = []
        for rid in self.needs_block():
            got = self.allocator.alloc(1)
            if got is None:
                short.append(rid)
                continue
            st = self._streams[rid]
            slot = self._slot_of[rid]
            self.tables[slot, len(st.blocks)] = got[0]
            st.blocks.extend(got)
        return short

    def evict(self, rid: int):
        """Preempt ``rid``: free its blocks (table zeroed to the sink
        first, so the frozen lane cannot touch live blocks) and forget
        the stream.  Returns ``(prompt_ids, max_new_tokens)`` for the
        caller to requeue; generated tokens are discarded and recomputed
        on re-admission (greedy re-runs reproduce them exactly)."""
        st = self._streams.pop(rid)
        slot = self._slot_of.pop(rid)
        self.tables[slot, :] = SINK_BLOCK
        self.allocator.free(st.blocks)
        self.active[slot] = False
        return list(st.prompt), st.max_new

    # ---- decode --------------------------------------------------------
    def step(self) -> List[int]:
        """One batched decode step across all slots; returns the rids
        that finished this step.  Completion comes from host-side
        position counters — no device fetch.  Raises
        :class:`BlockExhausted` when a stream's next write has no block
        (call :meth:`ensure_blocks` / evict first)."""
        if not self.active.any():
            return []
        short = self.ensure_blocks()
        if short:
            raise BlockExhausted(short)
        # non-active lanes (free, finished, MID-PREFILL) see an all-sink
        # table: their writes land in the sink and their reads gather
        # garbage that is discarded — so live blocks are written ONLY by
        # prefill chunks and active decode lanes, and parity never rests
        # on a frozen lane recomputing bitwise-identical K/V under a
        # different batch shape
        masked = np.where(self.active[:, None], self.tables, SINK_BLOCK)
        self.pools, self.tokens, self.pos, self.key = self._step_fn(
            self.params, self.pools, self.tokens,
            jnp.asarray(masked), self.pos,
            jnp.asarray(self.active), self.key)
        finished = []
        for rid, slot in list(self._slot_of.items()):
            if not self.active[slot]:
                continue
            self._pos_host[slot] += 1
            if self._pos_host[slot] + 1 >= self._streams[rid].target:
                self._finish(rid)
                finished.append(rid)
        return finished

    def _finish(self, rid: int) -> None:
        st = self._streams.pop(rid)
        slot = self._slot_of.pop(rid)
        # zero the table BEFORE freeing: the next step's frozen-lane
        # write must go to the sink, never into a block someone else
        # just allocated
        self.tables[slot, :] = SINK_BLOCK
        self.allocator.free(st.blocks)
        self.active[slot] = False
        row = np.asarray(jax.device_get(self.tokens[slot]))
        self._results[rid] = [int(t) for t in row[:st.target]]

    # ---- results -------------------------------------------------------
    def done(self, rid: int) -> bool:
        if rid in self._results:
            return True
        if rid in self._streams:
            return False
        raise KeyError(f"request {rid}: unknown or already consumed")

    def result(self, rid: int) -> List[int]:
        """Prompt + generated ids for a finished request (pops it)."""
        return self._results.pop(rid)

    def live(self) -> int:
        return len(self._streams)

    def any_active(self) -> bool:
        return bool(self.active.any())
