"""Closed-loop load generator + latency-percentile measurement.

Closed-loop means each simulated client holds at most ONE outstanding
request and submits its next the moment the previous completes — offered
load is the number of concurrent clients, and the system can never be
driven past saturation into a meaningless unbounded backlog (the
standard serving-bench discipline; open-loop arrival processes measure
queueing theory, closed-loop measures the server).

Per request we record TTFT (submit -> first output token, queue wait
included — that is what a client experiences) and mean ITL (decode span
/ (new_tokens - 1)); the sweep reports p50/p99 of each across requests,
plus aggregate generated tokens/s.  ``bench.py --serve`` drives
:func:`sweep_loads` at >= 3 offered loads into ``BENCH_SERVE.json``.

**Shared-prefix traffic mixes** (``shared_prefix_len`` /
``shared_fraction``): real chat fleets share system prompts, so a
seeded fraction of requests prepend one fixed shared prefix to their
random suffix — the workload the prefix cache (``ServeConfig.
prefix_cache``) exists for.  The request stream is pre-generated
per seed (client-major, independent of queue dynamics), so a cache-off
and a cache-on arm serve BYTE-IDENTICAL requests and the row's
``tokens_sha256`` digest pins greedy output equality across the A/B
(``bench.py --prefix-cache`` -> BENCH_PREFIX_CACHE.json).  TTFT
percentiles split by class (shared-prefix vs unique) and per-tick
blocks-in-use peak/mean expose the two wins: cached-prefix TTFT and
pool residency.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Dict, List, Optional

import numpy as np


def _pct(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))


def prewarm(make_scheduler, *, prompt_lens=(4, 24)) -> None:
    """Pay every compile a load run can draw BEFORE any latency is
    measured: each power-of-two prefill bucket the prompt range can
    produce under the scheduler's ``prefill_chunk``, plus the batched
    decode program — which, under ``attn_impl='fused'``, is where the
    Pallas paged-attention kernel compiles.  Without this, the first
    request to hit a cold bucket (or the cold decode kernel) books XLA /
    Mosaic compile time as a fake TTFT outlier in the p99.

    The bucket set is derived THROUGH ``paged_kv.prefill_bucket`` — the
    same function ``prefill_step`` compiles against — over every chunk
    width the sweep can draw (``w <= min(prefill_chunk, prompt_len)``),
    so the warmed set cannot drift from the compiled set if the bucket
    rule ever changes.  Uses a throwaway scheduler from the same
    factory; the jitted programs are cached per (model, geometry,
    sampling, attn_impl), so the warmth carries to every load point."""
    from .paged_kv import prefill_bucket

    sched = make_scheduler()
    try:
        chunk = max(1, int(sched.cfg.prefill_chunk))
        hi = min(int(prompt_lens[1]), sched.server.max_len - 2)
        w_max = max(1, min(chunk, hi))
        targets = {prefill_bucket(w) for w in range(1, w_max + 1)}
        # a prompt of min(bucket, w_max) tokens prefills in one chunk
        # drawing exactly that bucket (the top bucket via the partial
        # width w_max)
        lens = sorted(min(b, w_max) for b in targets)
        rids = [sched.submit(list(range(1, p + 1)), 2) for p in lens]
        assert all(r is not None for r in rids), "prewarm rejected"
        sched.run_until_drained()
        for r in rids:
            sched.result(r)
    finally:
        sched.close()


# Named traffic presets: one word in a bench flag pins the whole shape
# (prompt/decode ranges + shared-prefix mix), so two arms saying
# ``mix="long_prefill"`` provably serve the same traffic.  Values are
# sized for the bench geometry (seq=128): the longest shared request is
# shared_prefix_len + prompt_lens[1] tokens, inside the seq-2 admission
# budget.
MIXES: Dict[str, Dict[str, Any]] = {
    # prefill-heavy: long prompts, with decodes just long enough that
    # per-stream cadence is a real measurement (a 4-token decode's ITL
    # is all admission noise) — the traffic where a unified pool lets
    # prefill bursts stall decode cadence, and the disaggregated
    # prefill/decode split (DESIGN.md §11) earns its keep.  Half the
    # requests share one 24-token prefix so the shared/unique split
    # prices the prefix cache under the same mix.  Longest request:
    # 24 + 72 prompt + 28 decode = 124 <= the seq-2 admission budget.
    "long_prefill": dict(prompt_lens=(32, 72), max_new=(16, 28),
                         shared_prefix_len=24, shared_fraction=0.5),
}


def resolve_mix(mix: Optional[str], prompt_lens, max_new,
                shared_prefix_len: int, shared_fraction: float):
    """Apply a :data:`MIXES` preset: when ``mix`` is set its values
    REPLACE the four traffic-shape arguments (a preset exists to pin
    the shape; silently merging caller overrides would unpin it)."""
    if mix is None:
        return prompt_lens, max_new, shared_prefix_len, shared_fraction
    if mix not in MIXES:
        raise ValueError(f"unknown mix {mix!r}; have {sorted(MIXES)}")
    m = MIXES[mix]
    return (m["prompt_lens"], m["max_new"], m["shared_prefix_len"],
            m["shared_fraction"])


def make_requests(clients: int, requests_per_client: int, *,
                  vocab_size: int, prompt_lens=(4, 24), max_new=(8, 32),
                  seed: int = 0, shared_prefix_len: int = 0,
                  shared_fraction: float = 0.0, stream: int = 0,
                  mix: Optional[str] = None
                  ) -> List[List[Dict[str, Any]]]:
    """Pre-generate every client's request list (client-major, one RNG
    pass) so the stream is a pure function of the arguments — queue
    dynamics (rejections, completion order) cannot perturb which
    requests get generated, which is what lets two scheduler arms serve
    byte-identical traffic for an A/B.  With ``shared_prefix_len`` > 0,
    a ``shared_fraction`` of requests prepend ONE fixed shared prefix
    (drawn first from the same seed) to their random suffix.

    ``stream`` partitions the request space per DRIVEN REPLICA: N
    loadgens driving N fleet replicas from one operator ``seed`` must
    not replay the identical request stream (colliding flow-trace ids
    on the merged timeline — see the scheduler's ``_flow_prefix`` — and
    N byte-identical ``tokens_sha256`` inputs that would vacuously
    "agree"); ``stream=k`` mixes ``k`` into the RNG seed sequence, while
    ``stream=0`` keeps the historical ``default_rng(seed)`` draws so
    every committed bench artifact's traffic is reproducible."""
    (prompt_lens, max_new, shared_prefix_len,
     shared_fraction) = resolve_mix(mix, prompt_lens, max_new,
                                    shared_prefix_len, shared_fraction)
    rng = (np.random.default_rng(seed) if not stream
           else np.random.default_rng((int(seed), int(stream))))
    shared = (rng.integers(0, vocab_size, (shared_prefix_len,)).tolist()
              if shared_prefix_len > 0 else [])
    out: List[List[Dict[str, Any]]] = []
    for _ in range(int(clients)):
        reqs = []
        for _ in range(int(requests_per_client)):
            p = int(rng.integers(prompt_lens[0], prompt_lens[1] + 1))
            n = int(rng.integers(max_new[0], max_new[1] + 1))
            is_shared = bool(shared
                             and rng.random() < float(shared_fraction))
            if not is_shared:
                p = max(1, p)     # a bare prompt needs >= 1 token; a
                #                   0-suffix SHARED request is legal (a
                #                   regenerated turn: the prompt IS the
                #                   shared prefix — the full-hit + CoW
                #                   path)
            suffix = rng.integers(0, vocab_size, (p,)).tolist()
            reqs.append({"prompt": shared + suffix if is_shared
                         else suffix,
                         "max_new": n, "shared": is_shared})
        out.append(reqs)
    return out


def run_closed_loop(scheduler, clients: int, requests_per_client: int,
                    *, vocab_size: int, prompt_lens=(4, 24),
                    max_new=(8, 32), seed: int = 0,
                    slo_ms: Optional[float] = None,
                    shared_prefix_len: int = 0,
                    shared_fraction: float = 0.0, stream: int = 0,
                    mix: Optional[str] = None,
                    max_ticks: int = 200_000) -> Dict[str, Any]:
    """Drive ``scheduler`` with ``clients`` closed-loop clients until
    each has completed ``requests_per_client`` requests; returns the
    measured row (tokens/s, TTFT/ITL percentiles — split by shared/
    unique class under a shared-prefix mix — per-tick blocks-in-use,
    counters, and a sha256 of every request's output tokens in
    submission order for cross-arm identity pins).

    The request stream comes from :func:`make_requests` — a pure
    function of the arguments — so a sweep's load points (and an A/B's
    arms) serve the same request mix."""
    (prompt_lens, max_new, shared_prefix_len,
     shared_fraction) = resolve_mix(mix, prompt_lens, max_new,
                                    shared_prefix_len, shared_fraction)
    plan = make_requests(clients, requests_per_client,
                         vocab_size=vocab_size, prompt_lens=prompt_lens,
                         max_new=max_new, seed=seed,
                         shared_prefix_len=shared_prefix_len,
                         shared_fraction=shared_fraction, stream=stream)
    next_idx = [0] * int(clients)
    outstanding: List[Optional[int]] = [None] * int(clients)
    finished: List[int] = []
    shared_rids: set = set()
    results: Dict[int, tuple] = {}    # rid -> (client, idx, tokens)
    submit_retries = 0
    blocks_peak = 0
    blocks_sum = 0
    n_ticks = 0
    t0 = time.perf_counter()
    for _ in range(max_ticks):
        for ci in range(clients):
            if outstanding[ci] is not None or \
                    next_idx[ci] >= requests_per_client:
                continue
            req = plan[ci][next_idx[ci]]
            rid = scheduler.submit(req["prompt"], req["max_new"],
                                   slo_ms=slo_ms)
            if rid is None:           # bounded queue full: retry next tick
                submit_retries += 1
                continue
            if req["shared"]:
                shared_rids.add(rid)
            results[rid] = (ci, next_idx[ci], None)
            outstanding[ci] = rid
            next_idx[ci] += 1
        for rid in scheduler.tick():
            ci = outstanding.index(rid)
            outstanding[ci] = None
            finished.append(rid)
            c, i, _ = results[rid]
            results[rid] = (c, i, scheduler.result(rid))
        used = scheduler.server.allocator.used_blocks
        blocks_peak = max(blocks_peak, used)
        blocks_sum += used
        n_ticks += 1
        if all(i >= requests_per_client for i in next_idx) and \
                all(o is None for o in outstanding):
            break
    else:
        raise RuntimeError(f"load run not drained in {max_ticks} ticks")
    wall = time.perf_counter() - t0
    stats = [scheduler.stats(rid) for rid in finished]
    ttft = [s.ttft_ms for s in stats if s.ttft_ms is not None]
    itl = [s.itl_ms for s in stats if s.itl_ms is not None]
    # output-identity digest: every request's tokens in SUBMISSION order
    # (client-major), so two arms serving the same plan hash equal iff
    # every generated token matches
    h = hashlib.sha256()
    if stream:
        # replica-partitioned streams carry their stream tag in the
        # digest preamble: two replicas' digests can then never collide
        # unless someone ALSO collapsed their request streams
        h.update(repr(("stream", int(stream))).encode())
    for ci, i, toks in sorted(results.values()):
        h.update(repr((ci, i, toks)).encode())
    row = {
        "clients": int(clients),
        "requests": len(finished),
        "wall_s": round(wall, 3),
        "tokens_out": scheduler.tokens_out,
        "tokens_per_sec": round(scheduler.tokens_out / wall, 1),
        "ttft_ms_p50": _pct(ttft, 50), "ttft_ms_p99": _pct(ttft, 99),
        "itl_ms_p50": _pct(itl, 50), "itl_ms_p99": _pct(itl, 99),
        "ticks": scheduler.tick_no,
        "admitted": scheduler.admitted,
        "rejected": scheduler.rejected,
        "evicted": scheduler.evicted,
        "submit_retries": submit_retries,
        "deadline_missed": sum(1 for s in stats if s.deadline_missed),
        "blocks_in_use_peak": blocks_peak,
        "blocks_in_use_mean": round(blocks_sum / max(1, n_ticks), 2),
        "tokens_sha256": h.hexdigest(),
    }
    if mix is not None:
        row["mix"] = mix
    if shared_prefix_len > 0:
        row["shared_prefix_len"] = int(shared_prefix_len)
        row["shared_fraction"] = float(shared_fraction)
        row["shared_requests"] = len(shared_rids)
        for cls, rids in (("shared", shared_rids),
                          ("unique", set(finished) - shared_rids)):
            vals = [scheduler.stats(r).ttft_ms for r in rids
                    if scheduler.stats(r).ttft_ms is not None]
            row[f"ttft_ms_p50_{cls}"] = _pct(vals, 50)
            row[f"ttft_ms_p99_{cls}"] = _pct(vals, 99)
            # decode cadence per class: a prefix hit shortens TTFT but
            # must NOT change steady-state ITL — the pair proves it
            ivals = [scheduler.stats(r).itl_ms for r in rids
                     if scheduler.stats(r).itl_ms is not None]
            row[f"itl_ms_p50_{cls}"] = _pct(ivals, 50)
            row[f"itl_ms_p99_{cls}"] = _pct(ivals, 99)
    if getattr(scheduler.cfg, "prefix_cache", False):
        row["prefix_cache"] = scheduler.server.prefix_stats()
    return row


def sweep_loads(make_scheduler, loads: List[int],
                requests_per_client: int, *, vocab_size: int,
                prompt_lens=(4, 24), max_new=(8, 32), seed: int = 0,
                slo_ms: Optional[float] = None,
                shared_prefix_len: int = 0,
                shared_fraction: float = 0.0,
                warm: bool = True) -> List[Dict[str, Any]]:
    """One :func:`run_closed_loop` row per offered load (client count),
    a FRESH scheduler each (``make_scheduler()`` factory) so load points
    don't share warm state beyond compiled programs — which
    :func:`prewarm` populates up front (``warm=False`` opts out for
    callers measuring cold-start itself)."""
    rows = []
    if warm and loads:
        prewarm(make_scheduler, prompt_lens=prompt_lens)
    for c in loads:
        sched = make_scheduler()
        try:
            rows.append(run_closed_loop(
                sched, c, requests_per_client, vocab_size=vocab_size,
                prompt_lens=prompt_lens, max_new=max_new, seed=seed,
                slo_ms=slo_ms, shared_prefix_len=shared_prefix_len,
                shared_fraction=shared_fraction))
        finally:
            sched.close()
    return rows


def run_fleet_closed_loop(router, clients: int,
                          requests_per_client: int, *, vocab_size: int,
                          prompt_lens=(4, 24), max_new=(8, 32),
                          seed: int = 0,
                          classes: Optional[List[Dict[str, Any]]] = None,
                          stream: int = 0,
                          mix: Optional[str] = None,
                          max_wall_s: float = 600.0) -> Dict[str, Any]:
    """The MULTI-REPLICA closed-loop driver: ``clients`` one-outstanding
    clients against a ``serve.fleet.FleetRouter`` instead of one
    scheduler.  Same pre-generated request plan as
    :func:`run_closed_loop` (pure function of seed/stream — fleet arms
    at different replica counts serve byte-identical traffic), plus
    per-CLASS SLOs: ``classes`` is a list of ``{"name", "slo_ms"}``
    dicts assigned client-major (client ``ci`` runs class ``ci % K`` —
    an interactive client and a bulk client are different CLIENTS, not
    different requests of one), and the row reports TTFT percentiles
    per class — the split the router's deadline-aware placement is
    judged on.  Rejections at the ROUTER (fleet queue full / SLO
    infeasible) surface as ``router_rejections`` with clients retrying,
    the closed-loop discipline."""
    classes = classes or [{"name": "all", "slo_ms": None}]
    (prompt_lens, max_new, shared_prefix_len,
     shared_fraction) = resolve_mix(mix, prompt_lens, max_new, 0, 0.0)
    plan = make_requests(clients, requests_per_client,
                         vocab_size=vocab_size, prompt_lens=prompt_lens,
                         max_new=max_new, seed=seed, stream=stream,
                         shared_prefix_len=shared_prefix_len,
                         shared_fraction=shared_fraction)
    cls_of = [classes[ci % len(classes)] for ci in range(int(clients))]
    next_idx = [0] * int(clients)
    outstanding: List[Optional[int]] = [None] * int(clients)
    finished: List[int] = []
    owner: Dict[int, int] = {}          # fleet rid -> client
    tokens_of: Dict[int, tuple] = {}    # fleet rid -> (ci, idx, tokens)
    shared_rids: set = set()
    submit_retries = 0
    t0 = time.perf_counter()
    while True:
        progressed = False
        for ci in range(int(clients)):
            if outstanding[ci] is not None or \
                    next_idx[ci] >= requests_per_client:
                continue
            req = plan[ci][next_idx[ci]]
            # client idempotency key: a pure function of the request's
            # coordinates in the plan (seed/stream/client/index), so a
            # relaunched driver resubmitting after a control-plane
            # death names each request IDENTICALLY and the router's
            # journal dedupes instead of re-executing (serve/wal.py)
            idem = f"{int(seed)}.{int(stream)}.{ci}.{next_idx[ci]}"
            rid = router.submit(req["prompt"], req["max_new"],
                                slo_ms=cls_of[ci]["slo_ms"], idem=idem)
            if rid is None:
                submit_retries += 1
                continue
            owner[rid] = ci
            if req.get("shared"):
                shared_rids.add(rid)
            tokens_of[rid] = (ci, next_idx[ci], None)
            outstanding[ci] = rid
            next_idx[ci] += 1
            progressed = True
        for rid in router.pump():
            ci = owner.get(rid)
            if ci is None:
                # a journal-replayed request can complete before its
                # client re-attaches (recovered router, fresh driver);
                # the idempotency-key resubmit re-announces it
                continue
            outstanding[ci] = None
            finished.append(rid)
            c, i, _ = tokens_of[rid]
            tokens_of[rid] = (c, i, router.result(rid))
            progressed = True
        if all(i >= requests_per_client for i in next_idx) and \
                all(o is None for o in outstanding):
            break
        if time.perf_counter() - t0 > max_wall_s:
            raise RuntimeError(
                f"fleet load run not drained in {max_wall_s}s: "
                f"{len(finished)}/{clients * requests_per_client} done, "
                f"outstanding={[o for o in outstanding if o is not None]}")
        if not progressed:
            # subprocess replicas own the compute; a busy-spinning
            # driver would steal their core
            time.sleep(0.002)
    wall = time.perf_counter() - t0
    stats = [router.stats(rid) for rid in finished]
    h = hashlib.sha256()
    if stream:
        h.update(repr(("stream", int(stream))).encode())
    for ci, i, toks in sorted(tokens_of.values()):
        h.update(repr((ci, i, toks)).encode())
    tokens_out = sum(s.n_generated or 0 for s in stats)
    row: Dict[str, Any] = {
        "clients": int(clients),
        "requests": len(finished),
        "wall_s": round(wall, 3),
        "tokens_out": tokens_out,
        "tokens_per_sec": round(tokens_out / wall, 1),
        "submit_retries": submit_retries,
        "router_rejections": router.rejected,
        "requeued": router.requeued,
        "tokens_sha256": h.hexdigest(),
    }
    ttft_all = [s.ttft_ms for s in stats if s.ttft_ms is not None]
    row["ttft_ms_p50"] = _pct(ttft_all, 50)
    row["ttft_ms_p99"] = _pct(ttft_all, 99)
    # fleet-wide decode cadence: the signal a slow-but-alive replica
    # degrades first (utils/chaos.py's eviction-recovery A/B reads it)
    itl_all = [s.itl_ms for s in stats if s.itl_ms is not None]
    row["itl_ms_p50"] = _pct(itl_all, 50)
    row["itl_ms_p99"] = _pct(itl_all, 99)
    if mix is not None:
        row["mix"] = mix
    if shared_prefix_len > 0:
        # shared/unique split under a prefix mix, TTFT and ITL both:
        # the shared class's TTFT prices prefix reuse, its ITL pins
        # that reuse never taxes decode cadence
        row["shared_prefix_len"] = int(shared_prefix_len)
        row["shared_fraction"] = float(shared_fraction)
        row["shared_requests"] = len(shared_rids)
        for cls, rids in (("shared", shared_rids),
                          ("unique", set(finished) - shared_rids)):
            tv = [s.ttft_ms for rid, s in zip(finished, stats)
                  if rid in rids and s.ttft_ms is not None]
            iv = [s.itl_ms for rid, s in zip(finished, stats)
                  if rid in rids and s.itl_ms is not None]
            row[f"ttft_ms_p50_{cls}"] = _pct(tv, 50)
            row[f"ttft_ms_p99_{cls}"] = _pct(tv, 99)
            row[f"itl_ms_p50_{cls}"] = _pct(iv, 50)
            row[f"itl_ms_p99_{cls}"] = _pct(iv, 99)
    for k in classes:
        vals = [s.ttft_ms for rid, s in zip(finished, stats)
                if cls_of[owner[rid]]["name"] == k["name"]
                and s.ttft_ms is not None]
        row[f"ttft_ms_p50_{k['name']}"] = _pct(vals, 50)
        row[f"ttft_ms_p99_{k['name']}"] = _pct(vals, 99)
        row[f"requests_{k['name']}"] = len(vals)
        if k["slo_ms"] is not None:
            row[f"deadline_missed_{k['name']}"] = sum(
                1 for rid, s in zip(finished, stats)
                if cls_of[owner[rid]]["name"] == k["name"]
                and s.ttft_ms is not None and s.deadline_missed)
    row["per_replica_completed"] = router.per_replica_completed()
    return row
