"""Distributed host-side tracing: Perfetto-ready span timelines.

The telemetry channel (``train/telemetry.py``, DESIGN.md §7) answers
*what* happened — per-step metrics, heartbeat, flight recorder.  This
module answers *where time went*: a lightweight span API
(``with trace.span("dispatch"): ...``) writing a bounded per-process
``trace-p{P}-i{I}.jsonl`` under ``--trace_dir`` with the PR 2 writer
discipline (append + flush, atomic lines).  Every record carries the
cross-process correlation triple:

* ``process_id`` — this host process's rank (``NNPT_PROCESS_ID``, the
  DESIGN §10 world env channel, falling back to ``jax.process_index()``);
* ``run_id`` — one id for the whole JOB, stable across supervisor
  relaunches (``NNPT_RUN_ID``: set by ``train.resilience.supervise`` for
  its children, by the operator for multi-host worlds — like
  ``COORDINATOR_ADDRESS`` — or self-generated for a bare run);
* ``incarnation`` — which supervisor attempt this process is
  (``NNPT_INCARNATION``: 0 for the first launch, k for the k-th
  relaunch).

Because timestamps are unix epoch seconds, ``tools/trace_report.py``
(stdlib-only, like ``ckpt_fsck``) can merge the per-process files of a
supervised multi-process run — including files from DIFFERENT
incarnations after a crash-relaunch — onto ONE Chrome/Perfetto timeline
where the relaunch gap is visible, plus a per-phase time-share summary.

Span taxonomy (the fixed vocabulary the report tool groups by):

==============  ========================================================
``load``        host batch assembly (the loader's ``next()``)
``dispatch``    submitting one compiled step (async — host-side cost)
``fetch``       a ``device_get`` on step output (telemetry/monitor/log)
``eval``        a held-out evaluation pass
``ckpt``        a checkpoint save call (sync write or async staging)
``ckpt_write``  the async writer thread's actual disk write
``rollback``    anomaly/SDC rollback: restore + re-place
``admit`` / ``prefill`` / ``decode`` / ``retire``
                the serving scheduler's tick phases (serve/scheduler.py)
``queue_wait``  serving inter-tick gap with requests queued but no slot
``sched_bubble``
                serving inter-tick gap with decoding streams in flight
                (the scheduler loop, not the model, owned that time)
``compile:<n>`` a ledger-observed XLA compile (utils/compile_ledger.py)
==============  ========================================================

Besides spans, a tracer can emit **flow points** (:func:`flow`): the
Chrome s/t/f arrow chain that links spans by an id.  The serving
scheduler threads each request id through admit -> every prefill chunk
-> every decode tick -> retire, so ``tools/trace_report.py``'s merged
Perfetto timeline draws one request's whole life as a connected arrow
path across the per-tick phase spans (and, once blocks hand off across
replicas, across processes).

Relationship to the XLA profiler (``--xla_trace_dir`` →
``utils.profiling.trace``): the profiler captures *device* activity —
per-op HLO timelines, one heavyweight capture window, leader-gated,
viewed in TensorBoard/XProf.  This module captures *host* phases —
always-on-able, cross-process, crash-surviving.  Run both on a real
chip: host spans say which phase starved the device; the XLA trace says
what the device did inside it.

Everything is zero-cost when no tracer is installed: ``span()`` returns
a shared null context manager and touches one module global.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

RUN_ID_ENV = "NNPT_RUN_ID"
INCARNATION_ENV = "NNPT_INCARNATION"
PROCESS_ID_ENV = "NNPT_PROCESS_ID"  # the DESIGN §10 world env channel

# bounded trace discipline: after this many records the file stops
# growing and the footer reports how many spans were dropped — a
# runaway serving loop must not fill the disk the way an unbounded
# logger would
DEFAULT_MAX_EVENTS = 100_000


def run_identity() -> Dict[str, Any]:
    """The (process_id, run_id, incarnation) triple for THIS process.
    Env-first (the supervisor/operator channel); process_id falls back
    to ``jax.process_index()`` when the env channel is unset (TPU pods
    auto-configure their world), then 0."""
    pid_env = os.environ.get(PROCESS_ID_ENV)
    if pid_env is not None and pid_env != "":
        process_id = int(pid_env)
    else:
        try:
            import jax

            process_id = int(jax.process_index())
        except Exception:
            process_id = 0
    run_id = os.environ.get(RUN_ID_ENV) or ""
    if not run_id:
        run_id = f"run-{int(time.time())}-{os.getpid()}"
    try:
        incarnation = int(os.environ.get(INCARNATION_ENV) or 0)
    except ValueError:
        incarnation = 0
    return {"process_id": process_id, "run_id": run_id,
            "incarnation": incarnation}


class Tracer:
    """Per-process span writer.  One file per (process, incarnation) so
    a supervised relaunch never clobbers its predecessor's timeline;
    thread-safe (the async checkpoint writer emits from its own
    thread)."""

    def __init__(self, dirpath: str, process_id: int, run_id: str,
                 incarnation: int, max_events: int = DEFAULT_MAX_EVENTS):
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self.process_id = int(process_id)
        self.run_id = str(run_id)
        self.incarnation = int(incarnation)
        self.max_events = int(max_events)
        self.path = os.path.join(
            dirpath, f"trace-p{self.process_id}-i{self.incarnation}.jsonl")
        self._ident = {"p": self.process_id, "run": self.run_id,
                       "inc": self.incarnation}
        self._lock = threading.Lock()
        self._f: Optional[Any] = open(self.path, "a")
        self.events = 0
        self.dropped = 0
        self._emit({"kind": "meta", "t": round(time.time(), 6),
                    "pid": os.getpid(), **self._ident})

    def _emit(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if self._f is None:
                return
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()

    def _emit_bounded(self, rec: Dict[str, Any]) -> None:
        # bound check + counter update under the SAME lock as the write:
        # the async checkpoint writer emits from its own thread, and an
        # unsynchronized check-then-increment could overshoot the bound
        # or miscount the footer
        with self._lock:
            if self.events >= self.max_events:
                self.dropped += 1
                return
            self.events += 1
            if self._f is None:
                return
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()

    def record_span(self, name: str, t_unix: float, dur_s: float,
                    attrs: Dict[str, Any]) -> None:
        rec = {"kind": "span", "name": name, "t": round(t_unix, 6),
               "dur": round(dur_s, 6), **self._ident}
        thread = threading.current_thread()
        if thread is not threading.main_thread():
            rec["thread"] = thread.name
        if attrs:
            rec.update(attrs)
        self._emit_bounded(rec)
        if _SPAN_LISTENERS:
            for fn in tuple(_SPAN_LISTENERS):
                try:
                    fn(name, t_unix, dur_s, attrs)
                except Exception:
                    pass

    def instant(self, name: str, **attrs) -> None:
        self._emit_bounded({"kind": "instant", "name": name,
                            "t": round(time.time(), 6), **self._ident,
                            **attrs})

    def flow(self, name: str, flow_id: Any, phase: str, **attrs) -> None:
        """One point of a Perfetto FLOW — an arrow chain linking spans
        across ticks/threads/processes by ``flow_id``.  ``phase``:
        ``"s"`` start, ``"t"`` step, ``"f"`` finish (the Chrome
        trace-event flow vocabulary).  The serving scheduler threads a
        request id through admit -> each prefill chunk -> decode ticks
        -> retire this way, so one request's life is one connected
        arrow path across the per-tick phase spans."""
        if phase not in ("s", "t", "f"):
            raise ValueError(f"flow phase must be s/t/f, got {phase!r}")
        self._emit_bounded({"kind": "flow", "name": name,
                            "id": str(flow_id), "fph": phase,
                            "t": round(time.time(), 6), **self._ident,
                            **attrs})

    def close(self) -> None:
        with self._lock:
            if self._f is None:
                return
            self._f.write(json.dumps(
                {"kind": "meta", "t": round(time.time(), 6),
                 "events": self.events, "dropped": self.dropped,
                 "final": True, **self._ident}) + "\n")
            self._f.close()
            self._f = None


# ---------------------------------------------------------------------------
# module-level active tracer + the cheap span() entrypoint
# ---------------------------------------------------------------------------

_ACTIVE: Optional[Tracer] = None

# span listeners: callables ``fn(name, t_unix, dur_s, attrs)`` invoked for
# every recorded span, from whichever thread recorded it.  This is how
# ``utils/goodput.py``'s in-process meter observes the span stream without
# re-reading the trace file; the disabled-path cost is one empty-list
# truthiness check inside record_span.  Listener exceptions are swallowed —
# accounting must never take down the traced process.
_SPAN_LISTENERS: list = []


def add_listener(fn) -> None:
    """Register a span listener (idempotent)."""
    if fn not in _SPAN_LISTENERS:
        _SPAN_LISTENERS.append(fn)


def remove_listener(fn) -> None:
    """Unregister a span listener; missing listeners are ignored."""
    try:
        _SPAN_LISTENERS.remove(fn)
    except ValueError:
        pass


class _NullSpan:
    """Shared no-op context manager: the disabled-path cost of a span is
    one global read and one attribute call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "_t_unix", "_t0")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._t_unix = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        tracer = _ACTIVE
        if tracer is not None:
            tracer.record_span(self.name, self._t_unix,
                               time.perf_counter() - self._t0, self.attrs)
        return False


def span(name: str, **attrs):
    """``with trace.span("dispatch", step=k): ...`` — no-op (shared null
    object, no allocation) when no tracer is installed."""
    if _ACTIVE is None:
        return _NULL
    return _Span(name, attrs)


def instant(name: str, **attrs) -> None:
    tracer = _ACTIVE
    if tracer is not None:
        tracer.instant(name, **attrs)


def flow(name: str, flow_id: Any, phase: str, **attrs) -> None:
    """Emit one flow point (see :meth:`Tracer.flow`); no-op when no
    tracer is installed — per-request flow tracing costs nothing on an
    untraced serving process."""
    tracer = _ACTIVE
    if tracer is not None:
        tracer.flow(name, flow_id, phase, **attrs)


def active() -> Optional[Tracer]:
    return _ACTIVE


def install(tracer: Optional[Tracer]) -> None:
    global _ACTIVE
    _ACTIVE = tracer


def traced_iter(name: str, it):
    """Wrap an iterator so each ``next()`` is a span (the trainer's
    ``load`` phase).  Returns the iterator UNCHANGED when tracing is off
    at wrap time; the wrapper closes the inner iterator deterministically
    (the loader's prefetch-worker release contract)."""
    if _ACTIVE is None:
        return it

    def gen():
        inner = iter(it)
        try:
            while True:
                with span(name):
                    try:
                        item = next(inner)
                    except StopIteration:
                        return
                yield item
        finally:
            close = getattr(inner, "close", None)
            if close is not None:
                close()

    return gen()


# ---------------------------------------------------------------------------
# run lifecycle: one call installs the tracer AND the compile ledger
# ---------------------------------------------------------------------------

def dir_from_config(cfg) -> Optional[str]:
    """Resolve the effective trace directory from a TrainConfig-shaped
    object: ``--trace_dir`` wins; bare ``--trace`` rides
    ``--telemetry_dir`` (a ``trace/`` subdir, so one run directory holds
    the whole observability bundle)."""
    trace_dir = getattr(cfg, "trace_dir", None)
    if trace_dir:
        return trace_dir
    if getattr(cfg, "trace", False):
        tdir = getattr(cfg, "telemetry_dir", None)
        if not tdir:
            raise ValueError(
                "--trace needs --telemetry_dir (spans land in its trace/ "
                "subdir) or an explicit --trace_dir")
        return os.path.join(tdir, "trace")
    return None


def start_run(dirpath: str, max_events: int = DEFAULT_MAX_EVENTS,
              ledger: bool = True) -> Tracer:
    """Create + install the process tracer for ``dirpath`` and (by
    default) the compile ledger next to it (``compiles-p{P}-i{I}.jsonl``
    in the same directory).  Returns the tracer; ``stop_run()`` closes
    both."""
    ident = run_identity()
    tracer = Tracer(dirpath, ident["process_id"], ident["run_id"],
                    ident["incarnation"], max_events=max_events)
    install(tracer)
    if ledger:
        from ..utils import compile_ledger

        compile_ledger.install(compile_ledger.Ledger(
            os.path.join(dirpath,
                         f"compiles-p{ident['process_id']}"
                         f"-i{ident['incarnation']}.jsonl"),
            **ident))
    return tracer


def stop_run(tracer: Optional[Tracer] = None) -> None:
    """Close + uninstall the tracer (and the compile ledger, if one is
    installed).  With an explicit ``tracer``, only uninstalls when that
    tracer is still the active one — a later ``start_run`` wins."""
    global _ACTIVE
    from ..utils import compile_ledger

    target = tracer if tracer is not None else _ACTIVE
    if target is not None:
        target.close()
    if target is _ACTIVE:
        _ACTIVE = None
        led = compile_ledger.active()
        if led is not None:
            led.close()
            compile_ledger.install(None)
