"""Training telemetry: on-device step metrics, flight recorder, MFU
accounting, and the run-health heartbeat.

The reference's only observable is a per-epoch loss ``print`` (SURVEY.md
§5.5); the previous layer here was a host-side ``StepTimer`` plus a
leader-only JSONL.  Neither can explain *why* a step is slow, what the
skip-guard/rollback machinery (DESIGN.md §6) actually did, or how close a
run sits to hardware peak — the operating metrics of production TPU
training (per-step MFU and compiled-step telemetry; Yoo et al.
arXiv:2204.06514, Hessel et al. arXiv:2104.06272).  Four pillars:

1. **On-device step metrics** — the DP / DP x SP / GSPMD train steps can
   return a small metrics vector next to the loss (``with_metrics=True``):
   global grad norm, param norm, update/param ratio, and the skip-guard
   CUMULATIVE rejection counter (sample-loss-proof), all computed
   inside the jitted step from values the step
   already owns.  The grad norm REUSES the skip-guard's reduction via
   ``Optimizer.update_with_norm`` — one norm pass, not two — and the
   update math is untouched, so params are bitwise-identical with metrics
   on vs off (tests/test_telemetry.py pins this).  Futures are fetched at
   dispatch boundaries at the same lag-2 discipline ``ResilienceMonitor``
   uses, so the async pipeline is never forced to sync; measured overhead
   at the CPU-bench transformer scale (4L/d256/T128/B64, interleaved
   A/B pairs): +0.7% best rep / +1.8% median on the single-core
   8-virtual-device host — an upper bound that serializes every
   replica's norm work onto one core (DESIGN.md §7;
   tests/test_telemetry.py::test_telemetry_happy_path_overhead).
2. **Flight recorder** — a bounded ring of the last N step records and
   events (skips, rollbacks, faults), dumped as ``postmortem.json`` on
   crash (unhandled exception or an injected ``crash`` fault), rollback,
   anomaly abort (exit 44), hang (watchdog), and SIGTERM — so a relaunch
   log can point at WHAT the run was doing when it died
   (``train.resilience.supervise`` prints the pointer).
3. **MFU / FLOPs accounting** — analytic per-step matmul/conv FLOPs from
   the model config (``Module.fwd_flops``: MLP, ConvNet, Transformer incl.
   attention + CE head, GQA-, SwiGLU- and MoE-top-k-aware; ``ce_chunk``
   changes memory, not the analytic FLOPs) against the backend peak-FLOPs
   table below — the single source ``bench.py`` and the sweep tools
   consume.  On CPU the "peak" is a NOMINAL 100 GFLOP/s/device
   (``NNPT_PEAK_FLOPS`` overrides), so the metric stays a comparable
   time-series everywhere while bench.py's headline keeps its strict
   TPU-only MFU semantics.
4. **Run-health heartbeat** — a leader-written, atomically-replaced
   ``heartbeat.json`` (step, dispatch timestamp, steps/sec EMA, last
   metrics snapshot) refreshed per dispatch (throttled to
   ``_HEARTBEAT_MIN_INTERVAL_S``), consumed by
   ``train.resilience.supervise`` for external hang detection (a wedged
   child is killed and retried as exit 42) and rendered by
   ``tools/metrics_summary.py``.

Layout under ``--telemetry_dir``::

    metrics.jsonl     per-step records (step, loss, grad_norm, param_norm,
                      update_ratio, skipped, step_time_ms, samples/sec, mfu)
                      plus kind="rollup" sketch snapshots (serialized
                      utils/sketches.py state on the --rollup_every
                      cadence, merged fleet-wide by tools/obs_agg.py)
                      and kind="alert" records (EMA z-score anomalies on
                      loss/grad_norm/samples-per-sec; observe-and-
                      annotate — nothing acts on them)
    heartbeat-<role>-p<P>.json
                      freshest run-health snapshot (atomic replace), one
                      file per role ("train"/"rl"/"serve") and process —
                      two programs sharing one dir can no longer blind
                      the staleness monitor by last-writer-winning over a
                      single heartbeat.json (readers fall back from the
                      legacy shared name to the freshest qualified file)
    postmortem.json   flight-recorder dump, written on abnormal events

The stream is SHARED with the serving runtime: serve/scheduler.py writes
``kind="serve"`` tick records and ``kind="serve_req"`` per-request
completions into the same metrics.jsonl schema and beats its own
role-qualified heartbeat (through :class:`Heartbeat`), so the
supervisor's stale-heartbeat monitor and tools/metrics_summary.py treat
a serving process exactly like a training run.

Everything is zero-cost when ``telemetry_dir`` is unset, and file writes
are leader-only (multi-host safe).
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.optim import GuardedState, Optimizer, global_norm
from ..utils import goodput as goodput_lib
from ..utils.logging import is_leader, log
from ..utils.sketches import EmaZScore, ErrorBudget, Gauge, QuantileSketch
from . import trace as trace_lib

Pytree = Any

# keys every on-device metrics dict carries (the jitted step returns
# exactly these; ops consumers and tests key off this tuple)
METRIC_KEYS = ("loss", "grad_norm", "param_norm", "update_ratio", "skipped")

# heartbeat writes are throttled: a dispatch-bound micro-model can run
# thousands of dispatches/sec and the heartbeat must never become the
# bottleneck it is meant to watch
_HEARTBEAT_MIN_INTERVAL_S = 0.5

# ---------------------------------------------------------------------------
# Pillar 3: FLOPs / MFU accounting (single source for bench.py + trainer)
# ---------------------------------------------------------------------------

# Peak dense bf16 FLOPs/s per chip by device_kind substring (public specs).
# Moved here from bench.py so the trainer's metrics stream, bench.py's
# headline and tools/big_lm_sweep.py's rows all divide by the same table.
PEAK_FLOPS = (
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12), ("v5e", 197e12), ("v5", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 46e12),
)

# Nominal per-device peak used for the CPU fallback so the telemetry
# stream's ``mfu`` stays a well-defined relative time-series on any
# backend (bench.py's headline MFU stays strict TPU-only).  Overridable
# for exotic hosts via the env var.
NOMINAL_CPU_PEAK_FLOPS = 1e11
PEAK_ENV_VAR = "NNPT_PEAK_FLOPS"


def peak_flops_per_chip(device_kind: str) -> Optional[float]:
    """Accelerator peak dense bf16 FLOPs/s by device-kind substring, or
    None for kinds the table does not know (e.g. a CPU host)."""
    kind = (device_kind or "").lower()
    for key, val in PEAK_FLOPS:
        if key in kind:
            return val
    if "tpu" in kind or "axon" in kind:
        return 197e12  # conservative default: v5e-class
    return None


def telemetry_peak_flops(device_kind: str, platform: str) -> float:
    """The MFU denominator for the telemetry stream: the real chip peak
    where known, else the documented nominal CPU peak (env-overridable) —
    never None, so ``mfu`` is always present in the metrics records."""
    env = os.environ.get(PEAK_ENV_VAR)
    if env:
        return float(env)
    if platform not in ("cpu",):
        peak = peak_flops_per_chip(device_kind)
        if peak is not None:
            return peak
    return NOMINAL_CPU_PEAK_FLOPS


def train_step_flops(model, batch_shape: Tuple[int, ...]) -> Optional[float]:
    """Analytic matmul/conv FLOPs of ONE optimizer step on a batch of
    ``batch_shape``: forward + ~2x forward for the backward (the standard
    convention).  None for unaccounted architectures.  Accounting lives on
    the models themselves (``Module.fwd_flops`` — transformer counts qkv/
    out/FFN/attention scores+values and the CE/LM head, honoring GQA's
    narrower qkv projection, SwiGLU's gate matmul and MoE's top-k experts
    + router; ``ce_chunk`` only changes peak memory, never the math)."""
    fwd = model.fwd_flops(tuple(batch_shape))
    return None if fwd is None else 3.0 * fwd


# ---------------------------------------------------------------------------
# Pillar 1: the on-device metrics vector (called INSIDE the jitted steps)
# ---------------------------------------------------------------------------

def update_with_metrics(optimizer: Optimizer, grads: Pytree,
                        opt_state: Pytree, params: Pytree,
                        loss: jax.Array
                        ) -> Tuple[Pytree, Pytree, Dict[str, jax.Array]]:
    """Apply ``optimizer.update`` AND compute the telemetry metrics vector
    in one pass — pure jax, safe inside ``shard_map`` bodies and GSPMD
    global-view steps alike, PROVIDED ``grads`` are fully reduced (every
    shard holding a leaf sees the identical full gradient; the same
    precondition the skip guard documents).

    The global grad norm is computed once here and handed to the guard via
    ``Optimizer.update_with_norm`` when the optimizer is guarded — the
    guard then skips its own reduction, so metrics + guard together cost
    ONE norm pass.  The update math is byte-identical to the metrics-off
    step (same inputs, same expressions), which is what keeps params
    bitwise-equal with telemetry on vs off.
    """
    gnorm = global_norm(grads)
    if optimizer.update_with_norm is not None:
        new_params, new_opt = optimizer.update_with_norm(
            grads, opt_state, params, gnorm)
    else:
        new_params, new_opt = optimizer.update(grads, opt_state, params)
    return new_params, new_opt, metrics_vector(loss, gnorm, new_params,
                                               params, new_opt)


def metrics_vector(loss: jax.Array, grad_norm: jax.Array,
                   new_params: Pytree, old_params: Pytree,
                   new_opt: Pytree) -> Dict[str, jax.Array]:
    """Assemble the ``METRIC_KEYS`` dict from an already-applied update —
    the single construction point shared by :func:`update_with_metrics`
    (replicated/GSPMD paths, whole-tree grad norm) and the
    sharded-update paths (``parallel.update_sharding``/zero1, grad norm
    from psum'd scattered-shard squares).  ``new_params``/``old_params``
    must be the FULL (gathered) trees so the param/update norms are
    local math, identical on every replica."""
    pnorm = global_norm(new_params)
    unorm = global_norm(jax.tree_util.tree_map(
        lambda n, o: n.astype(jnp.float32) - o.astype(jnp.float32),
        new_params, old_params))
    if isinstance(new_opt, GuardedState):
        # CUMULATIVE rejections, not a per-step delta: the host samples
        # the stream (metrics_every, and k>1 dispatches report only their
        # last step), and a sampled cumulative counter cannot lose fires
        # that happened between samples — the host differences it
        skipped = new_opt.skipped.astype(jnp.float32)
    else:
        skipped = jnp.zeros((), jnp.float32)
    return {
        "loss": loss.astype(jnp.float32),
        "grad_norm": grad_norm,
        "param_norm": pnorm,
        "update_ratio": unorm / jnp.maximum(pnorm, 1e-12),
        "skipped": skipped,
    }


# ---------------------------------------------------------------------------
# Pillar 2: flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded ring of the last N step records + events; dumps
    ``postmortem.json`` on abnormal events.  Recording is cheap (deque
    append of small dicts); dumping is leader-only."""

    def __init__(self, size: int, path: Optional[str]):
        self.size = int(size)
        self.path = path
        self.records: collections.deque = collections.deque(
            maxlen=max(1, self.size))
        self.enabled = bool(path) and self.size > 0
        self.dumps = 0
        self._pending_reason: Optional[str] = None

    def record(self, rec: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        self.records.append(rec)
        if self._pending_reason is not None and rec.get("kind") == "step":
            # a dump armed by an event (rollback) waits for one post-event
            # step record so the postmortem's tail STRADDLES the event
            reason, self._pending_reason = self._pending_reason, None
            self.dump(reason)

    def event(self, kind: str, step: int, **detail) -> None:
        self.record({"kind": "event", "event": kind, "step": int(step),
                     "t_unix": round(time.time(), 3), **detail})

    def arm_dump(self, reason: str) -> None:
        """Dump after the NEXT step record lands (straddling dump); if no
        further record ever lands, close()/abnormal-exit dumps instead."""
        self._pending_reason = reason

    def dump(self, reason: str) -> Optional[str]:
        if not (self.enabled and is_leader()):
            return None
        self._pending_reason = None
        doc = {
            "reason": reason,
            "written_unix": round(time.time(), 3),
            "written_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
            "n_records": len(self.records),
            "records": list(self.records),
        }
        # per-device memory AT DEATH: the number an OOM/hang postmortem
        # is usually missing (best-effort — the runtime may be gone)
        mem = device_memory_summary(full=True)
        if mem:
            doc["device_memory"] = mem
        _atomic_write_json(self.path, doc)
        self.dumps += 1
        log(f"[telemetry] postmortem ({reason}) -> {self.path}")
        return self.path


# ---------------------------------------------------------------------------
# Pillar 4: heartbeat
# ---------------------------------------------------------------------------

def device_memory_summary(full: bool = False) -> Optional[Dict[str, Any]]:
    """Per-device memory snapshot for the heartbeat (compact: live +
    peak bytes) and the flight-recorder postmortem (``full=True``:
    everything the backend reports) — so an OOM/hang postmortem shows
    per-device memory at death.  None where the backend reports nothing
    (XLA:CPU) or the runtime is already too broken to answer."""
    try:
        from ..utils.profiling import device_memory_stats

        stats = device_memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    if full:
        return stats
    return {dev: {k: v for k, v in s.items()
                  if k in ("bytes_in_use", "peak_bytes_in_use")}
            for dev, s in stats.items()}


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)  # readers never observe a torn file


def heartbeat_filename(role: str, process_id: Optional[int] = None) -> str:
    """Per-role/per-process heartbeat file name:
    ``heartbeat-<role>-p<P>.json``.  Two programs sharing one
    ``--telemetry_dir`` (a trainer and a serving replica, or two
    serving replicas with distinct ``NNPT_PROCESS_ID``) used to
    last-writer-win over ONE ``heartbeat.json``, blinding the
    supervisor's staleness monitor to whichever wrote second; now each
    writer owns its file and generic readers (``read_heartbeat``,
    tools/metrics_summary.py, tools/obs_agg.py) fall back from the
    legacy shared name to the freshest qualified one — while the
    supervisor's hang monitor watches exactly its child's file.
    Delegates to the stdlib-only ``resilience.heartbeat_filename``
    (the naming's single source), with the process id resolved through
    ``trace.run_identity`` so the jax fallback applies."""
    if process_id is None:
        process_id = trace_lib.run_identity()["process_id"]
    from .resilience import heartbeat_filename as _hb_name

    return _hb_name(role, process_id)


def read_heartbeat(path: str) -> Optional[Dict[str, Any]]:
    """Load a heartbeat document.  Back-compat: when ``path`` is the
    legacy shared ``heartbeat.json`` (or a telemetry dir) and only
    role-qualified files exist, the FRESHEST of those is returned —
    callers keyed to the old layout keep working against per-role
    writers."""
    from .resilience import find_heartbeats

    candidates = [path] if os.path.isfile(path) else (
        find_heartbeats(path if os.path.isdir(path)
                        else os.path.dirname(path) or "."))
    best: Optional[Dict[str, Any]] = None
    best_m = None
    for p in candidates:
        try:
            m = os.stat(p).st_mtime
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if best_m is None or m > best_m:
            best, best_m = doc, m
    return best


# staleness helper lives in resilience (stdlib-only, so the generic
# supervisor never imports this jax-heavy module); canonical re-export
from .resilience import heartbeat_age_s  # noqa: E402


class Heartbeat:
    """Leader-written run-health snapshot, refreshed per dispatch
    (throttled) with NO device sync — everything in it is host state."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.enabled = bool(path) and is_leader()
        self._last_write = 0.0
        self._final = False
        self.last_step = 0  # newest step ever beaten (alive() reuses it)
        self.ema_steps_per_sec: Optional[float] = None

    def beat(self, step: Optional[int], last_metrics: Optional[Dict[str, Any]],
             force: bool = False, final: bool = False, **extra) -> None:
        """``step=None`` (the out-of-loop ``alive()`` beats) reuses the
        newest step already beaten — checkpoint/eval phases must never
        rewrite the step backwards.  Once the FINAL beat is written,
        later non-final beats only refresh the file's mtime (the
        staleness signal) and leave the final content intact."""
        if not self.enabled:
            return
        now = time.time()
        if not force and now - self._last_write < _HEARTBEAT_MIN_INTERVAL_S:
            return
        self._last_write = now
        if self._final and not final:
            try:
                os.utime(self.path)  # fresh, but the final record stands
            except OSError:
                pass
            return
        step = self.last_step if step is None else int(step)
        self.last_step = step  # plain assignment: a rollback rewinds it
        self._final = self._final or final
        doc = {
            "step": step,
            "t_unix": round(now, 3),
            "t_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now)),
            "pid": os.getpid(),
            "steps_per_sec_ema": self.ema_steps_per_sec,
            "last_metrics": last_metrics,
            **extra,
        }
        # per-device live/peak memory where the backend reports it —
        # writes are already throttled, so this stays off the hot path
        mem = device_memory_summary()
        if mem:
            doc["device_memory"] = mem
        if final:
            doc["final"] = True
        _atomic_write_json(self.path, doc)

    def observe_rate(self, inst_steps_per_sec: float) -> None:
        e = self.ema_steps_per_sec
        self.ema_steps_per_sec = (inst_steps_per_sec if e is None
                                  else 0.9 * e + 0.1 * inst_steps_per_sec)


# ---------------------------------------------------------------------------
# The orchestrating object the Trainer drives
# ---------------------------------------------------------------------------

# process-global active telemetry, so out-of-band failure paths (the
# injected ``crash`` fault's pre-_exit hook, the hang watchdog's timeout
# callback) can dump the flight recorder without threading a reference
_ACTIVE: Optional["Telemetry"] = None


def emergency_dump(reason: str) -> Optional[str]:
    """Best-effort postmortem dump from wherever the process is dying
    (utils.faults' injected crash, the watchdog's hang handler).

    Deliberately does NOT drain the lag queue: on the hang path the queued
    futures are exactly what is stuck, and a ``device_get`` here would
    block the watchdog's exit forever.  The dump carries what was already
    fetched — which under the lag-2 discipline is everything up to ~2
    dispatches before the stall."""
    t = _ACTIVE
    if t is None or not t.enabled:
        return None
    try:
        t.recorder.event(
            "emergency", t._newest_step(),
            detail=reason, unfetched_dispatches=len(t._queue))
        return t.recorder.dump(reason)
    except Exception:
        return None


class Telemetry:
    """Per-run telemetry driver: owns the lag-2 fetch queue, the metrics
    JSONL, the heartbeat and the flight recorder.  All methods are no-ops
    when ``telemetry_dir`` is unset."""

    def __init__(self, cfg, model, feature_shape: Tuple[int, ...],
                 n_devices: int, device_kind: str, platform: str,
                 kind: str = "step",
                 flops_per_row: Optional[float] = None):
        """``kind`` stamps every metrics record (``"step"`` for the LM
        trainer, ``"rl"`` for the Anakin learner — tools/metrics_summary
        renders each kind's view); ``flops_per_row`` overrides the
        per-row MFU numerator for workloads whose step is not one
        fwd+bwd per row (the RL step's T actor forwards + ppo_epochs
        fwd/bwd live in ``rl.anakin.anakin_step_flops``)."""
        global _ACTIVE

        self.enabled = bool(cfg.telemetry_dir)
        self.dir = cfg.telemetry_dir
        self.kind = kind
        # the heartbeat/rollup role tag: "train" for the LM trainer's
        # kind="step" stream, else the kind itself ("rl", "serve")
        self.role = "train" if kind == "step" else kind
        self._flops_override = flops_per_row
        self.metrics_every = max(0, int(cfg.metrics_every))
        self.rollup_every = max(0, int(getattr(cfg, "rollup_every", 0)))
        self.alerts_enabled = bool(getattr(cfg, "alerts", True))
        self._queue: List[tuple] = []  # (step, epoch, out, n_steps, rows, t)
        self._last_t: Optional[float] = None
        self.last_record: Optional[Dict[str, Any]] = None
        self.skipped_total = 0        # newest observed cumulative counter
        self._resync_skips = False    # set on rollback: counter rewound
        self.alerts_fired = 0
        self.rollups_written = 0
        # streaming SLO sketches (utils/sketches.py): cumulative per
        # incarnation, snapshotted into kind="rollup" records so
        # tools/obs_agg.py can merge fleet percentiles without raw
        # samples.  Detectors are the kind="alert" sources: loss /
        # grad-norm spikes (EMA z above) and throughput collapse (below)
        self._sketches = {k: QuantileSketch() for k in (
            "loss", "grad_norm", "step_time_ms", "samples_per_sec",
            "mfu")}
        self._gauges = {k: Gauge() for k in ("steps_per_sec", "mfu")}
        self._detectors = {
            "loss": EmaZScore("loss", direction="above"),
            "grad_norm": EmaZScore("grad_norm", direction="above"),
            "samples_per_sec": EmaZScore("samples_per_sec",
                                         direction="below"),
        }
        self._records_seen = 0
        self._last_rollup_step = 0
        if not self.enabled:
            self.recorder = FlightRecorder(0, None)
            self.heartbeat = Heartbeat(None)
            self._jsonl = None
            self.goodput_meter = None
            self._goodput_budget = None
            return
        if is_leader():
            os.makedirs(self.dir, exist_ok=True)
        self.metrics_path = os.path.join(self.dir, "metrics.jsonl")
        self.heartbeat_path = os.path.join(self.dir,
                                           heartbeat_filename(self.role))
        self.postmortem_path = os.path.join(self.dir, "postmortem.json")
        self.recorder = FlightRecorder(int(cfg.flight_recorder),
                                       self.postmortem_path)
        self.heartbeat = Heartbeat(self.heartbeat_path)
        self._jsonl = (open(self.metrics_path, "a")
                       if is_leader() else None)
        self._t0 = time.perf_counter()
        # per-ROW step FLOPs (every accounted model is linear in batch),
        # so per-dispatch FLOPs = rows * this; workload-specific callers
        # (the RL learner) hand in their own honest accounting instead
        self.flops_per_row = (self._flops_override
                              if self._flops_override is not None
                              else train_step_flops(model, (1,) + tuple(
                                  feature_shape)))
        self.peak_total = (telemetry_peak_flops(device_kind, platform)
                           * max(1, n_devices))
        # goodput accounting (utils/goodput.py): an online meter riding
        # the trace span-listener seam, snapshotted as kind="goodput"
        # records on the rollup cadence, with per-step anatomy joined
        # from the compile ledger's XLA cost analysis.  --goodput 0
        # disables (the bench's A/B arm); no tracer installed = the
        # meter just never hears a span and reports idle.
        self.peak_bw_total = (goodput_lib.peak_bytes_per_s(
            device_kind, platform) * max(1, n_devices))
        self.goodput_meter: Optional[goodput_lib.GoodputMeter] = None
        self._goodput_budget: Optional[ErrorBudget] = None
        self._goodput_frac_min = float(getattr(cfg, "goodput_target", 0.5))
        self._goodput_prev: Optional[Tuple[int, Dict[str, Any]]] = None
        if bool(getattr(cfg, "goodput", True)):
            self.goodput_meter = goodput_lib.GoodputMeter()
            trace_lib.add_listener(self.goodput_meter.on_span)
            if self.alerts_enabled:
                # attainment SLO: >= 90% of rollup windows should meet
                # the goodput-fraction floor; sustained misses burn the
                # budget at >= 2x and fire goodput_burn_rate
                self._goodput_budget = ErrorBudget(
                    "goodput", target=0.9,
                    window=50, min_events=5, cooldown=10)
        _ACTIVE = self

    # ---- hot path --------------------------------------------------------

    def on_dispatch(self, step: int, epoch: int, before: int, out,
                    n_steps: int, rows: int) -> None:
        """Called once per dispatch, right after submission.  ``out`` is
        the dispatch's device future: the on-device metrics dict when the
        step builder carries metrics, else the bare loss scalar.  Fetching
        happens at lag 2 (the monitor's discipline): the ``device_get``
        only ever waits on a dispatch whose successor is already
        submitted, so one dispatch stays in flight."""
        if not self.enabled:
            return
        now = time.perf_counter()
        if self._last_t is not None and now > self._last_t:
            self.heartbeat.observe_rate(n_steps / (now - self._last_t))
        crossed = (self.metrics_every > 0 and
                   step // self.metrics_every > before // self.metrics_every)
        if crossed:
            self._queue.append((step, epoch, out, n_steps, rows,
                                self._last_t, now))
            if len(self._queue) >= 2:
                # the popped entry's successor is already submitted, so
                # this device_get never drains the pipeline (the monitor's
                # lag-2 discipline)
                self._fetch(self._queue.pop(0))
        self._last_t = now
        self.heartbeat.beat(step, self.last_record,
                            skipped_total=self.skipped_total)

    def _fetch(self, entry) -> None:
        step, epoch, out, n_steps, rows, t_prev, t_disp = entry
        with trace_lib.span("fetch", what="metrics", step=int(step)):
            fetched = jax.device_get(out)
        if isinstance(fetched, dict):
            rec = {k: float(v) for k, v in fetched.items()}
        else:
            rec = {"loss": float(fetched)}
        rec.update(step=int(step), epoch=int(epoch),
                   kind=self.kind,
                   t=round(time.perf_counter() - self._t0, 6))
        if t_prev is not None and t_disp > t_prev:
            dt = (t_disp - t_prev) / max(1, n_steps)  # dispatch-to-dispatch
            rec["step_time_ms"] = round(dt * 1e3, 4)
            rec["samples_per_sec"] = round(rows / (t_disp - t_prev), 2)
            if self.flops_per_row is not None:
                rows_per_step = rows / max(1, n_steps)
                rec["mfu"] = (self.flops_per_row * rows_per_step / dt
                              / self.peak_total)
        if "skipped" in rec:
            # 'skipped' is the guard's cumulative rejection counter;
            # difference it against the last observed value so fires
            # between sampled records (metrics_every > 1, mid-dispatch
            # steps of a k>1 scan) surface too.  A rollback restores an
            # OLDER counter — resync the watermark without an event.
            cum = int(rec["skipped"])
            if self._resync_skips or cum < self.skipped_total:
                self._resync_skips = False
            elif cum > self.skipped_total:
                self.recorder.event("skip", step,
                                    fires=cum - self.skipped_total,
                                    grad_norm=rec.get("grad_norm"))
            self.skipped_total = cum
        self.last_record = rec
        self.recorder.record(rec)
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        self._observe(rec, step)

    # ---- streaming sketches, rollups, alerts -----------------------------

    def _observe(self, rec: Dict[str, Any], step: int) -> None:
        """Feed the fetched record into the sketch layer + anomaly
        detectors and emit rollup/alert records on their cadences.
        Host-side arithmetic on already-fetched floats — nothing here
        touches a device."""
        self._records_seen += 1
        for key, sketch in self._sketches.items():
            v = rec.get(key)
            if isinstance(v, (int, float)):
                sketch.add(v)
        ema = self.heartbeat.ema_steps_per_sec
        if ema is not None:
            self._gauges["steps_per_sec"].set(ema)
        if isinstance(rec.get("mfu"), (int, float)):
            self._gauges["mfu"].set(rec["mfu"])
        if self.alerts_enabled:
            for key, det in self._detectors.items():
                v = rec.get(key)
                if isinstance(v, (int, float)):
                    alert = det.observe(v, step=step)
                    if alert:
                        self._emit_alert(alert, step)
        if (self.rollup_every > 0
                and (step // self.rollup_every
                     > self._last_rollup_step // self.rollup_every)):
            self._last_rollup_step = step
            self._write_rollup(step)

    def _emit_alert(self, alert: Dict[str, Any], step: int) -> None:
        """One ``kind="alert"`` record into the metrics stream + a
        flight-recorder event.  Observe-and-annotate only: nothing here
        feeds back into training decisions — the supervisor logs these
        next to its relaunch reasoning, and the rollback/abort policy
        stays ``ResilienceMonitor``'s."""
        self.alerts_fired += 1
        rec = {"kind": "alert", "role": self.role, "step": int(step),
               "t": round(time.perf_counter() - self._t0, 6),
               "t_unix": round(time.time(), 3), **alert}
        self.recorder.event("alert", step, alert=alert.get("alert"),
                            value=alert.get("value"), z=alert.get("z"))
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        log(f"[telemetry] ALERT {alert.get('alert')} at step {step} "
            f"(value {alert.get('value')})")

    def _write_rollup(self, step: int) -> None:
        """Snapshot the SERIALIZED sketch state (not point stats) as a
        ``kind="rollup"`` record, stamped with the (process, run,
        incarnation) identity so ``tools/obs_agg.py`` can pick the
        newest snapshot per writer and merge fleet percentiles.
        Sketches are cumulative over this incarnation — the aggregator
        takes the latest record per identity, never a sum of
        records."""
        if self._jsonl is None:
            return
        ident = trace_lib.run_identity()
        rec = {
            "kind": "rollup", "role": self.role, "step": int(step),
            "t": round(time.perf_counter() - self._t0, 6),
            "t_unix": round(time.time(), 3),
            "p": ident["process_id"], "run": ident["run_id"],
            "inc": ident["incarnation"],
            "sketches": {k: s.to_dict()
                         for k, s in self._sketches.items() if s.n},
            "counters": {"metrics_records": self._records_seen,
                         "skipped_total": int(self.skipped_total),
                         "alerts": self.alerts_fired},
            "gauges": {k: g.to_dict() for k, g in self._gauges.items()
                       if g.last is not None},
        }
        self.rollups_written += 1
        self._jsonl.write(json.dumps(rec) + "\n")
        self._jsonl.flush()
        self._write_goodput(step, ident)

    def _step_anatomy(self) -> Optional[Dict[str, Any]]:
        """Join the compile ledger's XLA cost analysis (flops / bytes
        accessed, recorded at compile time) with the measured step time
        and the meter's host-span seconds into a roofline position +
        MFU-gap breakdown.  None when any leg of the join is missing
        (no ledger, no cost analysis from this backend, no measured
        step yet)."""
        from ..utils import compile_ledger

        led = compile_ledger.active()
        last = self.last_record or {}
        step_ms = last.get("step_time_ms")
        if led is None or not isinstance(step_ms, (int, float)):
            return None
        flops = by = None
        for e in reversed(led.events):
            if e.get("flops"):
                flops, by = e.get("flops"), e.get("bytes_accessed")
                break
        if not flops:
            return None
        # host cost per step: the meter's dispatch/load/fetch span
        # seconds differenced over the steps since the last rollup
        host_s = 0.0
        if self.goodput_meter is not None and self._goodput_prev:
            prev_step, prev_host = self._goodput_prev
            cur = self.goodput_meter.snapshot()["host_seconds"]
            dsteps = max(1, self._last_rollup_step - prev_step)
            host_s = max(0.0, sum(cur.values())
                         - sum(prev_host.values())) / dsteps
        return goodput_lib.step_anatomy(
            flops=flops, bytes_accessed=by, step_s=float(step_ms) / 1e3,
            host_s=host_s, peak_flops=self.peak_total,
            peak_bw=self.peak_bw_total)

    def _write_goodput(self, step: int, ident: Dict[str, Any]) -> None:
        """One ``kind="goodput"`` record next to each rollup: cumulative
        per-category seconds (the aggregator takes the newest per
        identity, like the sketches), plus the step anatomy.  The burn
        alert reuses the PR 14 ErrorBudget: each rollup whose goodput
        fraction is under ``--goodput_target`` consumes error budget."""
        if self.goodput_meter is None or self._jsonl is None:
            return
        snap = self.goodput_meter.snapshot()
        anatomy = self._step_anatomy()
        rec = goodput_lib.goodput_record(snap, role=self.role,
                                         step=step, ident=ident,
                                         anatomy=anatomy)
        self._jsonl.write(json.dumps(rec) + "\n")
        self._jsonl.flush()
        self._goodput_prev = (int(step), snap["host_seconds"])
        # no spans heard = tracing is off: the meter sees only idle and
        # a burn alert would be noise, not signal
        if self._goodput_budget is not None and snap["spans"] > 0:
            frac = snap["goodput_fraction"] or 0.0
            alert = self._goodput_budget.observe(
                frac < self._goodput_frac_min)
            if alert:
                self._emit_alert(
                    {**alert, "goodput_fraction": frac,
                     "goodput_target": self._goodput_frac_min}, step)

    # ---- events ----------------------------------------------------------

    def on_rollback(self, step: int, rollbacks: int) -> None:
        """Flush in-flight records (they belong to the abandoned timeline
        but really executed), log the event, dump now AND arm a second
        dump after the next step record so the postmortem's tail straddles
        the rollback."""
        if not self.enabled:
            return
        self.flush(final=False)
        self.recorder.event("rollback", step, rollbacks=rollbacks)
        self.recorder.dump("rollback")
        self.recorder.arm_dump("rollback")
        self._last_t = None  # the restore stall is not a step time
        # the restored GuardedState carries an older cumulative skip
        # counter; resync the watermark at the next record, no event
        self._resync_skips = True
        # alive() beats between the rollback and the next dispatch must
        # report the restored step, not the abandoned timeline's
        self.heartbeat.last_step = int(step)

    def on_abnormal_exit(self, exc: BaseException) -> None:
        from .resilience import AnomalyAbort

        if not self.enabled:
            return
        reason = ("anomaly_abort" if isinstance(exc, AnomalyAbort)
                  else f"crash: {type(exc).__name__}: {exc}")
        self.recorder.event("abort" if isinstance(exc, AnomalyAbort)
                            else "crash", self._newest_step(), detail=str(exc))
        try:
            # device-side crashes poison the queued futures: draining
            # them re-raises.  This runs inside fit's finally, where a
            # second raise would MASK the original exception and skip the
            # dump — swallow it; the dump below carries what was fetched.
            self.flush(final=False)
        except Exception:
            pass
        self.recorder.dump(reason)

    def on_sdc(self, record: Dict[str, Any]) -> None:
        """A silent-data-corruption incident (train/trainer.py's
        fingerprint monitor): write the full record into the telemetry
        stream (``kind: "sdc"`` in metrics.jsonl — tools/sdc_report.py
        renders these), log a flight-recorder event, and dump a
        postmortem — an SDC is exactly the event class the black box
        exists for, whether or not the run survives it."""
        if not self.enabled:
            return
        rec = {"kind": "sdc",
               "t": round(time.perf_counter() - self._t0, 6), **record}
        self.recorder.event(
            "sdc", int(record.get("step", -1)),
            verdict=record.get("verdict"), action=record.get("action"),
            leaves=record.get("leaves"), devices=record.get("devices"))
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        self.recorder.dump("sdc")
        # straddle: re-dump after the next step record so the postmortem
        # tail shows whether the run kept training past the incident
        self.recorder.arm_dump("sdc")

    def on_topology(self, step: int, change: Dict[str, Any]) -> None:
        """An elastic topology change (train/trainer.py's preflight): the
        run resumed on a different world than the one that saved its
        checkpoint.  Not a failure — no postmortem — but it IS the moment
        the effective batch/accumulation semantics may have changed, so
        the record goes into the metrics stream (``kind: "topology"``,
        rendered by tools/metrics_summary.py) and the flight-recorder
        ring (a later postmortem should show the run was degraded)."""
        if not self.enabled:
            return
        rec = {"kind": "topology", "step": int(step),
               "t": round(time.perf_counter() - self._t0, 6), **change}
        self.recorder.event(
            "topology", int(step),
            from_devices=(change.get("from_world") or {}).get("n_devices"),
            to_devices=(change.get("to_world") or {}).get("n_devices"),
            policy=change.get("policy"),
            batch_size=change.get("batch_size"),
            accum_steps=change.get("accum_steps"))
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()

    def on_preempted(self, signum: int, step: int) -> None:
        if not self.enabled:
            return
        self.recorder.event("sigterm", step, signum=signum)
        self.recorder.dump(f"sigterm (signal {signum})")

    def _newest_step(self) -> int:
        if self._queue:
            return int(self._queue[-1][0])
        return int((self.last_record or {}).get("step", -1))

    def alive(self) -> None:
        """Refresh the heartbeat OUTSIDE the dispatch loop — long
        host-side phases (checkpoint writes, eval passes) emit no
        dispatches, and without these beats the supervisor's external
        stale-heartbeat monitor would kill a healthy run in its tail.
        Throttled like every beat; ``step=None`` keeps the newest step
        already beaten (never rewrites it backwards)."""
        if self.enabled:
            self.heartbeat.beat(None, self.last_record,
                                skipped_total=self.skipped_total)

    # ---- lifecycle -------------------------------------------------------

    def flush(self, final: bool = True, step: Optional[int] = None) -> None:
        """Drain the lag queue (safe: by the time flush runs, the futures
        are either complete or about to be blocked on anyway).  ``step``:
        the trainer's global step for the final heartbeat — needed in the
        heartbeat-only mode (``metrics_every=0``) where no record ever
        carries one."""
        if not self.enabled:
            return
        while self._queue:
            self._fetch(self._queue.pop(0))
        if final:
            if step is None:
                step = int((self.last_record or {}).get("step", 0))
            if self.rollup_every > 0 and self._records_seen:
                # terminal snapshot regardless of cadence: the
                # aggregator must see the run's complete sketches
                self._write_rollup(step)
            self.heartbeat.beat(step, self.last_record, force=True,
                                final=True,
                                skipped_total=self.skipped_total)

    def close(self) -> None:
        global _ACTIVE

        if _ACTIVE is self:
            _ACTIVE = None
        if self.goodput_meter is not None:
            trace_lib.remove_listener(self.goodput_meter.on_span)
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
