"""Training orchestration: state, step builders, trainer loop."""

from .state import TrainState
from .trainer import Trainer
