"""Train state: the one logical copy of params + optimizer state.

The reference's equivalent state is implicit and per-process — N model
replicas kept identical by construction (state-dict bcast at
dataParallelTraining_NN_MPI.py:87-88, identical applied gradients at
:206-211).  Here it is a single pytree whose placement (replicated for DP,
sharded for FSDP/TP) is a sharding annotation.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

Pytree = Any


class TrainState(NamedTuple):
    step: jax.Array          # int32 scalar
    params: Pytree
    opt_state: Pytree

    @classmethod
    def create(cls, model, optimizer, key: jax.Array) -> "TrainState":
        params = model.init(key)
        return cls(step=jnp.zeros((), jnp.int32),
                   params=params,
                   opt_state=optimizer.init(params))
