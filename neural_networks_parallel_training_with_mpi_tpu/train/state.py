"""Train state: the one logical copy of params + optimizer state.

The reference's equivalent state is implicit and per-process — N model
replicas kept identical by construction (state-dict bcast at
dataParallelTraining_NN_MPI.py:87-88, identical applied gradients at
:206-211).  Here it is a single pytree whose placement (replicated for DP,
sharded for FSDP/TP) is a sharding annotation.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

Pytree = Any


class TrainState(NamedTuple):
    step: jax.Array          # int32 scalar
    params: Pytree
    opt_state: Pytree
    # fp8 delayed-scaling calibration state (ops.qmm): per-tensor-role
    # activation amax histories, read at the top of the jitted step and
    # rolled at the bottom.  () — zero leaves — whenever the quantized
    # matmul seam is off, so every pre-seam layout's state flattens to
    # the exact same LEAF LIST (donation audits and the elastic
    # reshard's field-ordered opt-state range unchanged); the treedef
    # itself grows one leafless child, which pre-round-13 snapshots
    # bridge through checkpoint._treedef_compatible (the defaulted-field
    # probe), so old checkpoints still restore.  Replicated everywhere
    # (scalar-ish leaves; observations are pmax'd across replicas before
    # entering, so the histories stay identical).
    qstate: Pytree = ()

    @classmethod
    def create(cls, model, optimizer, key: jax.Array) -> "TrainState":
        from ..ops import qmm

        params = model.init(key)
        return cls(step=jnp.zeros((), jnp.int32),
                   params=params,
                   opt_state=optimizer.init(params),
                   qstate=qmm.init_qstate(model))
