"""Training resilience: anomaly policy, preemption-safe exit, supervisor.

The reference's only failure mode is "hang forever in ``comm.gather``"
(SURVEY.md §5.3).  The watchdog (``utils/watchdog.py``) already converts a
lost peer into a loud exit; this module defends the *state itself* and the
*job*:

* :func:`ops.optim.with_skip_guard` (wired by the Trainer) rejects
  non-finite / over-threshold updates inside the jitted step — a single bad
  batch can no longer poison the replicated params.
* :class:`ResilienceMonitor` is the host-side anomaly policy: it watches
  the (one-step-lagged) loss stream the train loop already fetches, and
  after ``rollback_after`` consecutive bad steps asks for a rollback to the
  last checkpoint; after ``max_rollbacks`` rollbacks it aborts with
  :class:`AnomalyAbort` (exit code :data:`EXIT_ANOMALY`).
* :class:`GracefulShutdown` turns SIGTERM/SIGINT (TPU preemption, scheduler
  eviction) into a flag the step loop checks at the next boundary: final
  checkpoint, exit 0 — an external restart loses at most one step.
* :func:`supervise` is the crash-restart supervisor: relaunch on crash with
  exponential backoff and bounded restarts, interpreting the exit-code
  contract below to decide retry-vs-stop.

* :class:`SDCPolicy` is the silent-data-corruption strike ledger
  (DESIGN.md §9): the trainer's fingerprint monitor charges each
  transient, healed divergence to the device (or peer host) it was
  localized to; a device exceeding the strike budget — or a divergence
  the replay triage proves DETERMINISTIC — raises :class:`SDCAbort`
  (exit code :data:`EXIT_SDC`, no retry: a relaunch would replay a
  software bug, and a chip past its strike budget needs draining, not
  another restart).

Exit-code contract (also consumed by ``tools/supervise.py``):

===========  ============================================  =========
code         meaning                                       supervisor
===========  ============================================  =========
0            run completed (or exited cleanly on SIGTERM)  stop
42           watchdog: no step progress (hang)             retry
43           peer loss: a collective raised                retry
44           anomaly abort: rollback budget exhausted      stop
45           SDC abort: deterministic replica divergence   stop
             or per-device strike budget exhausted
other        crash (segfault, OOM, fault injection, ...)   retry
===========  ============================================  =========
"""

from __future__ import annotations

import math
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

EXIT_OK = 0
EXIT_HANG = 42      # utils.watchdog.HangWatchdog
EXIT_PEER = 43      # a collective raised (see tests/faulty_child.py)
EXIT_ANOMALY = 44   # ResilienceMonitor exhausted its rollback budget
EXIT_SDC = 45       # deterministic replica divergence / SDC strike budget

# exit codes the supervisor must NOT retry: 0 is success; 44 and 45 are
# deterministic training failures that a relaunch would only replay
_NO_RETRY = (EXIT_OK, EXIT_ANOMALY, EXIT_SDC)


class AnomalyAbort(RuntimeError):
    """Training diverged past the rollback budget; maps to exit 44."""


class SDCAbort(RuntimeError):
    """Silent data corruption the run must not survive: the replay triage
    proved the divergence deterministic (a software bug a relaunch would
    replay), or one device blew its transient-strike budget (hardware
    that needs draining).  Maps to exit 45 — the supervisor does not
    retry."""


class SDCPolicy:
    """Per-device strike ledger for TRANSIENT (replay-clean, healed)
    divergences.  ``record(devices)`` charges one strike to each named
    device and returns the devices now over budget (empty == keep going).
    One flaky step is weather; the same chip diverging ``strikes`` times
    is a failing part."""

    def __init__(self, strikes: int = 3):
        if strikes < 1:
            raise ValueError(f"sdc strike budget must be >= 1, got "
                             f"{strikes}")
        self.strikes = strikes
        self.counts: dict = {}
        self.incidents = 0   # fingerprint mismatches observed
        self.healed = 0      # transient incidents healed in-process

    def record(self, devices: Sequence[str]) -> List[str]:
        self.incidents += 1
        for d in devices:
            self.counts[d] = self.counts.get(d, 0) + 1
        return [d for d in devices if self.counts[d] >= self.strikes]


class ResilienceMonitor:
    """Host-side anomaly policy over the step-loss stream.

    A step is *bad* when its loss is non-finite, or — with
    ``spike_factor > 0`` — exceeds ``spike_factor`` times the exponential
    moving average of recent good losses (the EMA warms up over
    ``warmup`` good steps before spike detection arms, so the noisy first
    steps of a fresh init cannot trip it).

    ``observe`` returns ``"ok"``, ``"bad"`` (bad, under the consecutive
    threshold), ``"rollback"`` (restore the last checkpoint and keep
    going) or ``"abort"`` (rollback budget exhausted — raise
    :class:`AnomalyAbort`).  A rollback resets the EMA: the restored
    params re-warm it.
    """

    def __init__(self, rollback_after: int, max_rollbacks: int = 2,
                 spike_factor: float = 0.0, ema_beta: float = 0.9,
                 warmup: int = 5):
        if rollback_after < 1:
            raise ValueError(f"rollback_after must be >= 1, got "
                             f"{rollback_after}")
        self.rollback_after = rollback_after
        self.max_rollbacks = max_rollbacks
        self.spike_factor = spike_factor
        self.ema_beta = ema_beta
        self.warmup = warmup
        self.consecutive = 0   # bad steps since the last good one
        self.rollbacks = 0     # rollbacks performed so far
        self.bad_steps = 0     # total bad steps observed
        self._ema: Optional[float] = None
        self._n_good = 0

    def observe(self, loss: float) -> str:
        bad = not math.isfinite(loss)
        if (not bad and self.spike_factor > 0 and self._ema is not None
                and self._n_good >= self.warmup):
            bad = loss > self.spike_factor * max(self._ema, 1e-12)
        if not bad:
            self.consecutive = 0
            self._ema = (loss if self._ema is None
                         else self.ema_beta * self._ema
                         + (1.0 - self.ema_beta) * loss)
            self._n_good += 1
            return "ok"
        self.bad_steps += 1
        self.consecutive += 1
        if self.consecutive < self.rollback_after:
            return "bad"
        self.consecutive = 0
        if self.rollbacks >= self.max_rollbacks:
            return "abort"
        self.rollbacks += 1
        self._ema = None
        self._n_good = 0
        return "rollback"

    def stats(self) -> dict:
        return {"bad_steps": self.bad_steps, "rollbacks": self.rollbacks}


class GracefulShutdown:
    """SIGTERM/SIGINT -> a flag the step loop polls at dispatch boundaries.

    ``with GracefulShutdown() as stop:`` installs handlers (previous
    handlers are restored on exit); ``stop.requested`` turns True on the
    first signal.  A second signal of the same kind falls through to the
    previous handler semantics via a hard re-raise — so an operator's
    double-Ctrl-C still kills a wedged run.  Signal handlers only exist on
    the main thread; elsewhere the context is an inert no-op (trainers
    driven from worker threads keep working, without preemption safety).
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,
                                                 signal.SIGINT)):
        self._signals = tuple(signals)
        self._previous: dict = {}
        self.requested = False
        self.signum: Optional[int] = None

    def _handler(self, signum, frame):
        if self.requested:
            # second signal: restore + re-raise so the default/previous
            # disposition (usually: die now) takes over
            prev = self._previous.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            signal.raise_signal(signum)
            return
        self.requested = True
        self.signum = signum
        print(f"[resilience] caught signal {signum}: finishing the current "
              "step, writing a final checkpoint, exiting 0", file=sys.stderr,
              flush=True)

    def __enter__(self) -> "GracefulShutdown":
        for s in self._signals:
            try:
                self._previous[s] = signal.signal(s, self._handler)
            except ValueError:  # not the main thread: no handlers, no-op
                self._previous.pop(s, None)
                break
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._previous.clear()


def strip_supervisor_flags(argv: Sequence[str]) -> List[str]:
    """Remove ``--supervise [N]`` / ``--supervise_backoff [S]`` from an argv
    so the supervised child runs the plain training entrypoint (handles
    both ``--flag value`` and ``--flag=value`` forms)."""
    flags = ("--supervise", "--supervise_backoff")
    out: List[str] = []
    skip = False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok in flags:
            skip = True
            continue
        if any(tok.startswith(f + "=") for f in flags):
            continue
        out.append(tok)
    return out


def heartbeat_age_s(path: str, now: Optional[float] = None
                    ) -> Optional[float]:
    """Seconds since the telemetry heartbeat file was last refreshed
    (mtime-based: train.telemetry's atomic replace bumps it on every
    write), or None if absent.  Lives HERE, stdlib-only, because the
    generic supervisor (tools/supervise.py) wraps arbitrary commands on
    hosts that may not even have JAX installed — it must never pull in
    the jax-importing telemetry module; telemetry re-exports this."""
    import os

    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    return max(0.0, (time.time() if now is None else now) - mtime)


_ckpt_manifest_mod = None


def _ckpt_manifest():
    """utils/ckpt_manifest.py loaded BY FILE PATH (cached) — the regular
    relative import would execute utils/__init__, whose prng/logging pull
    jax; this module stays importable on the jax-less ops hosts the
    generic supervisor (tools/supervise.py) is meant for, same trick as
    tools/ckpt_fsck.py."""
    global _ckpt_manifest_mod
    if _ckpt_manifest_mod is None:
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "utils", "ckpt_manifest.py")
        spec = importlib.util.spec_from_file_location(
            "_nnpt_ckpt_manifest", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _ckpt_manifest_mod = mod
    return _ckpt_manifest_mod


def _restore_target(ckpt_dir: str):
    """(step, n_bad): newest snapshot passing FULL manifest verification,
    plus how many NEWER generations fail it — exactly the set the child's
    restore will quarantine on its way down the chain.  Walks newest-first
    and stops hashing at the first verified generation (restore's own
    discipline: with multi-GB snapshots, sha256ing every older generation
    would add minutes of supervisor downtime per relaunch for one log
    line).  The verification itself is utils.ckpt_manifest — stdlib-only,
    same logic tools/ckpt_fsck.py runs — so the supervisor reports what a
    relaunch will actually resume from, not what merely exists on disk."""
    cm = _ckpt_manifest()
    bad = 0
    for step, path in reversed(cm.snapshot_steps(ckpt_dir)):
        if cm.verify(path):
            bad += 1
        else:
            return step, bad
    return None, bad


def _run_child(cmd: Sequence[str], env: Optional[dict],
               heartbeat_path: Optional[str], heartbeat_timeout: float,
               log: Callable[[str], None]) -> int:
    """One child launch.  Without a heartbeat watch this is a plain
    blocking call.  With one, the supervisor polls the telemetry
    ``heartbeat.json`` (train.telemetry writes it atomically per dispatch)
    and a child whose heartbeat goes stale is killed and reported as
    :data:`EXIT_HANG` — the EXTERNAL complement to the in-process
    ``utils.watchdog.HangWatchdog``, covering the failure mode where the
    whole host process (watchdog thread included) is frozen.

    The monitor ARMS at the child's first heartbeat write (mtime newer
    than the launch) — the same discipline as the in-process watchdog's
    first-``pat()`` arming: the first step's XLA/Mosaic compile can take
    arbitrarily long and must never be killed as a hang, and a leftover
    heartbeat from a previous run must not count either.  The symmetric
    cost: a child frozen BEFORE its first dispatch is not caught by this
    monitor (nor by the in-process one)."""
    if not (heartbeat_path and heartbeat_timeout > 0):
        return subprocess.call(list(cmd), env=env)
    child = subprocess.Popen(list(cmd), env=env)
    started = time.time()
    poll_s = max(0.05, min(heartbeat_timeout / 4.0, 5.0))
    armed = False
    while True:
        rc = child.poll()
        if rc is not None:
            return rc
        age = heartbeat_age_s(heartbeat_path)
        if not armed:
            # arm only once THIS child has written the heartbeat
            # (mtime after launch <=> age < runtime)
            if age is not None and age < time.time() - started:
                armed = True
            else:
                time.sleep(poll_s)
                continue
        idle = age if age is not None else time.time() - started
        if idle > heartbeat_timeout:
            log(f"[supervise] heartbeat stale for {idle:.0f}s "
                f"(> {heartbeat_timeout:.0f}s): killing child "
                f"{child.pid} as hung")
            child.terminate()
            try:
                child.wait(timeout=10)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()
            # deliberately EXIT_HANG even when the SIGTERM was absorbed
            # gracefully (the child checkpoints and exits 0): that 0
            # means "clean final snapshot", NOT "job finished" — a
            # stalled-but-signal-responsive child must be retried, not
            # reported complete.  A healthy tail phase is protected by
            # Telemetry.alive() beats during checkpoint/eval, and a
            # spuriously killed near-done run converges in one resumed
            # relaunch.
            return EXIT_HANG
        time.sleep(poll_s)


def supervise(cmd: Sequence[str], max_restarts: int,
              backoff: float = 1.0, backoff_cap: float = 60.0,
              env: Optional[dict] = None,
              log: Callable[[str], None] = None,
              heartbeat_path: Optional[str] = None,
              heartbeat_timeout: float = 0.0,
              postmortem_path: Optional[str] = None,
              ckpt_dir: Optional[str] = None,
              _sleep: Callable[[float], None] = time.sleep) -> int:
    """Run ``cmd`` under the crash-restart policy; return the final exit
    code.

    ``max_restarts`` bounds RELAUNCHES (the initial launch is free).  Exit
    0 and exit 44 stop immediately (see the module exit-code contract);
    anything else — watchdog 42, peer-loss 43, crashes, signal deaths
    (negative returncodes) — is retried with exponential backoff
    ``backoff * 2^k`` capped at ``backoff_cap`` seconds.  The relaunched
    command is identical; resume-from-newest-snapshot is the child's job
    (``cli`` appends ``--resume`` when a checkpoint dir is configured).

    ``heartbeat_path`` + ``heartbeat_timeout`` arm the external hang
    detector (see :func:`_run_child`).  ``postmortem_path``: when a child
    dies abnormally and the telemetry flight recorder dumped a postmortem
    during THIS child's lifetime, the relaunch log points at it.
    ``ckpt_dir``: before each relaunch, log the newest VERIFIED snapshot
    (full manifest-checksum pass, utils.ckpt_manifest) the child's
    ``--resume`` will land on — so an operator tailing the supervisor sees
    immediately whether a crash mid-checkpoint cost a generation.
    """
    if log is None:
        log = lambda m: print(m, file=sys.stderr, flush=True)
    attempt = 0
    while True:
        attempt += 1
        log(f"[supervise] attempt {attempt}: {' '.join(cmd)}")
        launched = time.time()
        rc = _run_child(cmd, env, heartbeat_path, heartbeat_timeout, log)
        # any ABNORMAL exit — including the no-retry anomaly abort (44),
        # whose dump is the flagship black-box case — gets the pointer
        if rc != EXIT_OK and postmortem_path:
            try:
                import os as _os

                if _os.stat(postmortem_path).st_mtime >= launched - 1.0:
                    log(f"[supervise] child left a postmortem: "
                        f"{postmortem_path}")
            except OSError:
                pass
        if rc in _NO_RETRY:
            if rc == EXIT_ANOMALY:
                log("[supervise] child exited 44 (anomaly abort): "
                    "deterministic training failure — not retrying")
            elif rc == EXIT_SDC:
                log("[supervise] child exited 45 (SDC abort): "
                    "deterministic replica divergence or device strike "
                    "budget exhausted — not retrying")
            else:
                log("[supervise] child completed (exit 0)")
            return rc
        restarts_used = attempt - 1
        if restarts_used >= max_restarts:
            log(f"[supervise] giving up: {max_restarts} restarts exhausted "
                f"(last exit {rc})")
            return rc
        delay = min(backoff * (2.0 ** restarts_used), backoff_cap)
        reason = {EXIT_HANG: "watchdog hang",
                  EXIT_PEER: "peer loss"}.get(rc, "crash")
        log(f"[supervise] child exit {rc} ({reason}); relaunching in "
            f"{delay:.1f}s ({restarts_used + 1}/{max_restarts})")
        if ckpt_dir:
            step, bad = _restore_target(ckpt_dir)
            if step is not None:
                log(f"[supervise] relaunch resumes from verified snapshot "
                    f"step {step}"
                    + (f" ({bad} unverified generation(s) will be "
                       "quarantined on restore)" if bad else ""))
            else:
                cm = _ckpt_manifest()
                legacy = any(
                    (p / "meta.json").exists()
                    and not (p / cm.MANIFEST).exists()
                    for _, p in cm.snapshot_steps(ckpt_dir))
                if legacy:
                    # the child's restore REFUSES on pre-durability dirs
                    # rather than silently restarting from step 0 — say
                    # so instead of promising a from-scratch run
                    log("[supervise] no verified snapshot in "
                        f"{ckpt_dir} but pre-manifest snapshot(s) exist: "
                        "the relaunch will refuse to start — run "
                        "tools/ckpt_fsck.py --adopt to trust them")
                else:
                    log("[supervise] no verified snapshot in "
                        f"{ckpt_dir}: relaunch restarts from scratch"
                        + (f" ({bad} unverified generation(s) — "
                           "tools/ckpt_fsck.py)" if bad else ""))
        _sleep(delay)
