"""Training resilience: anomaly policy, preemption-safe exit, supervisor.

The reference's only failure mode is "hang forever in ``comm.gather``"
(SURVEY.md §5.3).  The watchdog (``utils/watchdog.py``) already converts a
lost peer into a loud exit; this module defends the *state itself* and the
*job*:

* :func:`ops.optim.with_skip_guard` (wired by the Trainer) rejects
  non-finite / over-threshold updates inside the jitted step — a single bad
  batch can no longer poison the replicated params.
* :class:`ResilienceMonitor` is the host-side anomaly policy: it watches
  the (one-step-lagged) loss stream the train loop already fetches, and
  after ``rollback_after`` consecutive bad steps asks for a rollback to the
  last checkpoint; after ``max_rollbacks`` rollbacks it aborts with
  :class:`AnomalyAbort` (exit code :data:`EXIT_ANOMALY`).
* :class:`GracefulShutdown` turns SIGTERM/SIGINT (TPU preemption, scheduler
  eviction) into a flag the step loop checks at the next boundary: final
  checkpoint, exit 0 — an external restart loses at most one step.
* :func:`supervise` is the crash-restart supervisor: relaunch on crash with
  exponential backoff and bounded restarts, interpreting the exit-code
  contract below to decide retry-vs-stop.

* :class:`SDCPolicy` is the silent-data-corruption strike ledger
  (DESIGN.md §9): the trainer's fingerprint monitor charges each
  transient, healed divergence to the device (or peer host) it was
  localized to; a device exceeding the strike budget — or a divergence
  the replay triage proves DETERMINISTIC — raises :class:`SDCAbort`
  (exit code :data:`EXIT_SDC`, no retry: a relaunch would replay a
  software bug, and a chip past its strike budget needs draining, not
  another restart).

* Elastic degraded-capacity restart (DESIGN.md §10): with
  ``elastic=True``, :func:`supervise` reacts to REPEATED peer-loss exits
  (43, and hangs-after-peer-loss 42) by probing the surviving topology
  (bounded — ``parallel.mesh.probe_world`` or :func:`default_probe`) and
  relaunching the child at the probed, shrunken world instead of looping
  forever through a ``world_setup`` that can never re-form the old one.
  When the probe finds fewer than ``min_devices``, the supervisor parks
  and re-polls with backoff until either capacity returns or the restart
  budget runs out, then exits :data:`EXIT_CAPACITY` (46, no-retry).

Exit-code contract (also consumed by ``tools/supervise.py``):

===========  ============================================  =========
code         meaning                                       supervisor
===========  ============================================  =========
0            run completed (or exited cleanly on SIGTERM)  stop
42           watchdog: no step progress (hang)             retry
43           peer loss: a collective raised/timed out or   retry
             world formation failed (typed, mesh errors)
44           anomaly abort: rollback budget exhausted      stop
45           SDC abort: deterministic replica divergence   stop
             or per-device strike budget exhausted
46           capacity abort: healthy devices stayed below  stop
             --min_devices for the whole restart budget
47           intentional decommission: the autopilot (or   stop
             an operator) drained and retired this child
             on purpose — relaunching would undo the
             scale-in, and the exit must not burn the
             restart budget
other        crash (segfault, OOM, fault injection, ...)   retry
===========  ============================================  =========
"""

from __future__ import annotations

import math
import os
import random
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence, Tuple

EXIT_OK = 0
EXIT_HANG = 42      # utils.watchdog.HangWatchdog
EXIT_PEER = 43      # a collective raised/timed out, or world formation
                    # failed (parallel.mesh typed errors)
EXIT_ANOMALY = 44   # ResilienceMonitor exhausted its rollback budget
EXIT_SDC = 45       # deterministic replica divergence / SDC strike budget
EXIT_CAPACITY = 46  # healthy capacity stayed below --min_devices
EXIT_DECOMMISSION = 47  # intentional decommission: drained + retired on
                        # purpose (serve.autopilot scale-in / rollout)

# exit codes the supervisor must NOT retry: 0 is success; 44 and 45 are
# deterministic training failures that a relaunch would only replay; 46
# means the hardware floor cannot be met — relaunching cannot create
# chips; 47 is a decommission the control plane ASKED for — a relaunch
# would undo the scale-in and burn budget on a healthy exit
_NO_RETRY = (EXIT_OK, EXIT_ANOMALY, EXIT_SDC, EXIT_CAPACITY,
             EXIT_DECOMMISSION)

# exit codes that count toward the elastic peer-loss streak: explicit
# peer loss, and hangs (a dead peer often presents as a stalled
# collective killed by the watchdog/heartbeat monitor, exit 42)
_PEER_LOSS_CODES = (EXIT_PEER, EXIT_HANG)


class AnomalyAbort(RuntimeError):
    """Training diverged past the rollback budget; maps to exit 44."""


class CapacityAbort(RuntimeError):
    """The healthy world is smaller than ``--min_devices`` and cannot be
    relaunched into compliance; maps to exit 46 — the supervisor does not
    retry (a relaunch cannot create chips; an operator/autoscaler must)."""


# substrings that mark a raised exception as peer/transport loss — the
# failure class the CLI converts to EXIT_PEER so (a) the supervisor's
# exit-code contract sees 43 instead of an anonymous crash and (b) the
# elastic streak counts it.  Name-based plus message-based: the concrete
# types (XlaRuntimeError, gloo's RuntimeError) live in jaxlib and vary by
# version, and this module must not import them.
_PEER_ERROR_TYPES = ("XlaRuntimeError", "CollectiveTimeout",
                     "WorldFormationError", "CoordinatorUnreachable",
                     "PeerMissing")
# multi-word / suffixed phrases only: a bare "peer"/"connection"/
# "unavailable" would misread ordinary crashes (a FileNotFoundError whose
# path contains "peer", a "CUDA unavailable" backend error) as peer loss
# and burn the restart budget — or worse, the elastic shrink streak — on
# a bug a relaunch can never fix
_PEER_ERROR_MARKERS = ("gloo", "all-reduce", "allreduce",
                       "broken pipe", "connection reset",
                       "connection refused", "connection closed",
                       "closed by peer", "lost peer", "connect failed",
                       "failed to connect", "recv failure", "recv error",
                       "deadline exceeded", "unavailable:",
                       "socket closed", "socket timeout",
                       "barrier timed out", "heartbeat timed out",
                       "coordinator unreachable", "peer down")
# ...and statuses that are NEVER transport, checked first: an OOM also
# arrives as XlaRuntimeError, and reading it as peer loss feeds the
# elastic shrink streak — where the default global-batch policy then
# GROWS per-device rows, making the relaunch OOM harder, in a loop
_NON_PEER_MARKERS = ("resource_exhausted", "out of memory",
                     "out-of-memory", "invalid_argument",
                     "failed_precondition", "permission_denied")


def is_peer_error(exc: BaseException) -> bool:
    """Does this exception look like a lost/unreachable peer rather than
    a software crash?  Used by the CLI to map an escaped collective/
    world-formation failure to exit 43.  Deliberately biased toward
    classifying AS peer loss: both classes are retried, and the only
    behavioral difference is that 43 counts toward the elastic
    probe-and-shrink streak — the correct reaction to a repeated
    ambiguous transport failure anyway.  Non-transport statuses
    (RESOURCE_EXHAUSTED, INVALID_ARGUMENT, ...) beat the type match:
    they name a deterministic local failure even when the carrier type
    is the same XlaRuntimeError a dead peer raises."""
    msg = str(exc).lower()
    if any(m in msg for m in _NON_PEER_MARKERS):
        return False
    for klass in type(exc).__mro__:
        if klass.__name__ in _PEER_ERROR_TYPES:
            return True
    return any(m in msg for m in _PEER_ERROR_MARKERS)


class SDCAbort(RuntimeError):
    """Silent data corruption the run must not survive: the replay triage
    proved the divergence deterministic (a software bug a relaunch would
    replay), or one device blew its transient-strike budget (hardware
    that needs draining).  Maps to exit 45 — the supervisor does not
    retry."""


class SDCPolicy:
    """Per-device strike ledger for TRANSIENT (replay-clean, healed)
    divergences.  ``record(devices)`` charges one strike to each named
    device and returns the devices now over budget (empty == keep going).
    One flaky step is weather; the same chip diverging ``strikes`` times
    is a failing part."""

    def __init__(self, strikes: int = 3):
        if strikes < 1:
            raise ValueError(f"sdc strike budget must be >= 1, got "
                             f"{strikes}")
        self.strikes = strikes
        self.counts: dict = {}
        self.incidents = 0   # fingerprint mismatches observed
        self.healed = 0      # transient incidents healed in-process

    def record(self, devices: Sequence[str]) -> List[str]:
        self.incidents += 1
        for d in devices:
            self.counts[d] = self.counts.get(d, 0) + 1
        return [d for d in devices if self.counts[d] >= self.strikes]


class ResilienceMonitor:
    """Host-side anomaly policy over the step-loss stream.

    A step is *bad* when its loss is non-finite, or — with
    ``spike_factor > 0`` — exceeds ``spike_factor`` times the exponential
    moving average of recent good losses (the EMA warms up over
    ``warmup`` good steps before spike detection arms, so the noisy first
    steps of a fresh init cannot trip it).

    ``observe`` returns ``"ok"``, ``"bad"`` (bad, under the consecutive
    threshold), ``"rollback"`` (restore the last checkpoint and keep
    going) or ``"abort"`` (rollback budget exhausted — raise
    :class:`AnomalyAbort`).  A rollback resets the EMA: the restored
    params re-warm it.
    """

    def __init__(self, rollback_after: int, max_rollbacks: int = 2,
                 spike_factor: float = 0.0, ema_beta: float = 0.9,
                 warmup: int = 5):
        if rollback_after < 1:
            raise ValueError(f"rollback_after must be >= 1, got "
                             f"{rollback_after}")
        self.rollback_after = rollback_after
        self.max_rollbacks = max_rollbacks
        self.spike_factor = spike_factor
        self.ema_beta = ema_beta
        self.warmup = warmup
        self.consecutive = 0   # bad steps since the last good one
        self.rollbacks = 0     # rollbacks performed so far
        self.bad_steps = 0     # total bad steps observed
        self._ema: Optional[float] = None
        self._n_good = 0

    def observe(self, loss: float) -> str:
        bad = not math.isfinite(loss)
        if (not bad and self.spike_factor > 0 and self._ema is not None
                and self._n_good >= self.warmup):
            bad = loss > self.spike_factor * max(self._ema, 1e-12)
        if not bad:
            self.consecutive = 0
            self._ema = (loss if self._ema is None
                         else self.ema_beta * self._ema
                         + (1.0 - self.ema_beta) * loss)
            self._n_good += 1
            return "ok"
        self.bad_steps += 1
        self.consecutive += 1
        if self.consecutive < self.rollback_after:
            return "bad"
        self.consecutive = 0
        if self.rollbacks >= self.max_rollbacks:
            return "abort"
        self.rollbacks += 1
        self._ema = None
        self._n_good = 0
        return "rollback"

    def stats(self) -> dict:
        return {"bad_steps": self.bad_steps, "rollbacks": self.rollbacks}


class GracefulShutdown:
    """SIGTERM/SIGINT -> a flag the step loop polls at dispatch boundaries.

    ``with GracefulShutdown() as stop:`` installs handlers (previous
    handlers are restored on exit); ``stop.requested`` turns True on the
    first signal.  A second TERMINATION signal falls through to the
    previous handler semantics via a hard re-raise — so an operator's
    double-Ctrl-C still kills a wedged run.  Signal handlers only exist on
    the main thread; elsewhere the context is an inert no-op (trainers
    driven from worker threads keep working, without preemption safety).

    ``notice_signals`` (default SIGUSR1, :data:`PREEMPT_SIGNAL`) are the
    ADVANCE-NOTICE channel: a cloud maintenance event or the supervisor's
    :meth:`GroupSupervisor.notify_preempt` announces the preemption
    ``grace_s`` seconds before the platform would hard-kill.  A notice
    sets ``requested`` (same dispatch-boundary checkpoint path) plus
    ``noticed``, and reads the grace window from the notice file
    (:func:`read_preempt_notice`) or :data:`PREEMPT_GRACE_ENV`.  The
    owner exits :data:`EXIT_DECOMMISSION` instead of 0 — terminal at the
    supervisor, priced as ``drain`` by the goodput ledger — because the
    capacity is GOING AWAY: a relaunch would land on a doomed node, and
    "job finished" would be a lie.  Notices are idempotent (a repeated
    SIGUSR1 never escalates to a kill)."""

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,
                                                 signal.SIGINT),
                 notice_signals: Sequence[int] = (signal.SIGUSR1,)):
        self._signals = tuple(signals) + tuple(
            s for s in notice_signals if s not in signals)
        self._notice = frozenset(notice_signals)
        self._previous: dict = {}
        self.requested = False
        self.noticed = False
        self.grace_s: Optional[float] = None
        self.signum: Optional[int] = None
        self._escalated = False

    def _handler(self, signum, frame):
        if signum in self._notice:
            first = not self.noticed
            self.noticed = True
            self.requested = True
            if self.signum is None:
                self.signum = signum
            if first:
                rec = read_preempt_notice() or {}
                try:
                    self.grace_s = float(
                        rec.get("grace_s")
                        or os.environ.get(PREEMPT_GRACE_ENV) or 2.0)
                except (TypeError, ValueError):
                    self.grace_s = 2.0
                print(f"[resilience] preemption notice (signal {signum}, "
                      f"grace {self.grace_s:.1f}s): finishing the current "
                      "step, writing a final checkpoint, exiting "
                      f"{EXIT_DECOMMISSION} (decommission)",
                      file=sys.stderr, flush=True)
            return
        if self._escalated:
            # second termination signal: restore + re-raise so the
            # default/previous disposition (usually: die now) takes over
            prev = self._previous.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            signal.raise_signal(signum)
            return
        self._escalated = True
        self.requested = True
        self.signum = signum
        print(f"[resilience] caught signal {signum}: finishing the current "
              "step, writing a final checkpoint, exiting 0", file=sys.stderr,
              flush=True)

    def __enter__(self) -> "GracefulShutdown":
        for s in self._signals:
            try:
                self._previous[s] = signal.signal(s, self._handler)
            except ValueError:  # not the main thread: no handlers, no-op
                self._previous.pop(s, None)
                break
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._previous.clear()


# ---------------------------------------------------------------------------
# the advance-notice preemption channel (PR 18)
# ---------------------------------------------------------------------------
# Real platforms announce most capacity loss: a maintenance event or spot
# preemption arrives with a grace window before the hard kill.  The seam
# is deliberately dumb — a signal plus an optional notice file — so the
# injected twin (utils/faults.py kind ``preempt``) and the real thing
# (an operator or node agent running ``kill -USR1``) are byte-identical
# from the victim's point of view.

PREEMPT_SIGNAL = signal.SIGUSR1
# where the machine-readable half of the notice lands (JSON: t_unix,
# grace_s); a supervisor stamps this into the child env so both ends
# agree on the path
PREEMPT_NOTICE_ENV = "NNPT_PREEMPT_NOTICE"
# fallback grace window (seconds) when the signal arrives with no file
PREEMPT_GRACE_ENV = "NNPT_PREEMPT_GRACE_S"


def preempt_notice_path(env: Optional[dict] = None) -> Optional[str]:
    return (env if env is not None else os.environ).get(PREEMPT_NOTICE_ENV)


def write_preempt_notice(path: Optional[str] = None, *,
                         grace_s: float = 2.0) -> Optional[str]:
    """Write the notice file (``{"t_unix", "grace_s"}``) — the sender's
    half of the advance-notice channel.  ``path`` defaults to this
    process's own :data:`PREEMPT_NOTICE_ENV`; best-effort and silent when
    no path is configured (the signal alone still carries the notice,
    with :data:`PREEMPT_GRACE_ENV` / the 2 s default as the window)."""
    import json

    path = path or preempt_notice_path()
    if not path:
        return None
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps({"t_unix": round(time.time(), 3),
                                "grace_s": float(grace_s)}) + "\n")
        os.replace(tmp, path)
    except OSError:
        return None
    return path


def read_preempt_notice(path: Optional[str] = None) -> Optional[dict]:
    """The receiver's half: parse the notice file, or None when absent /
    unreadable (a signal with no file is still a valid notice)."""
    import json

    path = path or preempt_notice_path()
    if not path:
        return None
    try:
        with open(path) as f:
            rec = json.loads(f.read())
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


def strip_supervisor_flags(argv: Sequence[str]) -> List[str]:
    """Remove the supervisor-only flags (``--supervise [N]``,
    ``--supervise_backoff [S]``, ``--supervise_backoff_max [S]``) from an
    argv so the supervised child runs the plain training entrypoint
    (handles both ``--flag value`` and ``--flag=value`` forms).  The
    elastic flags (``--elastic``, ``--min_devices``) deliberately STAY:
    the child enforces the capacity floor itself (exit 46) even when its
    supervisor is a dumb generic wrapper."""
    flags = ("--supervise", "--supervise_backoff", "--supervise_backoff_max")
    out: List[str] = []
    skip = False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok in flags:
            skip = True
            continue
        if any(tok.startswith(f + "=") for f in flags):
            continue
        out.append(tok)
    return out


# world-configuration env keys the degraded relaunch rewrites (mirrors
# parallel/mesh.py's channel; duplicated as STRINGS so this module stays
# importable on jax-less ops hosts)
_COORD_ENV_KEYS = ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS")
_NUM_PROCESSES_ENV = "NNPT_NUM_PROCESSES"
_PROCESS_ID_ENV = "NNPT_PROCESS_ID"
DEGRADED_ENV = "NNPT_ELASTIC_DEGRADED"  # marks a shrunken-world child
# trace correlation channel (train/trace.py; duplicated as strings so
# this module stays importable on jax-less ops hosts): the supervisor
# stamps every child with ONE job-stable run id and its attempt number,
# so tools/trace_report.py can merge the per-incarnation trace files of
# a crashed-and-relaunched run onto one timeline
RUN_ID_ENV = "NNPT_RUN_ID"
INCARNATION_ENV = "NNPT_INCARNATION"


def _append_event(path: Optional[str], rec: dict) -> None:
    """Append one supervisor lifecycle record to the ``events_path``
    JSONL (launch / exit / hang_kill / relaunch / stopped / gave_up).
    This is the goodput layer's join key for inter-incarnation time:
    ``utils/goodput.py`` prices the gap between an exit event and the
    next incarnation's first span as ``relaunch_gap`` (or ``drain`` on
    a terminal exit 47).  Best-effort: accounting must never take down
    the supervisor."""
    if not path:
        return
    import json

    try:
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
    except OSError:
        pass


def degrade_env(env: dict, probe: dict) -> dict:
    """Rewrite a child environment to the probed (shrunken) world: the
    coordinator rendezvous is dropped entirely and the child forms a
    single-process local world.  Returns the same dict, mutated.

    Only collapse-to-single-process is supported — every shipped probe
    (``probe_world``'s local fallback, :func:`default_probe`) reports
    ``n_processes=1`` when degraded; a degraded but still-multi-process
    world would need surviving-rank reassignment no local probe can
    answer (which rank dropped out?), so that case raises instead of
    relaunching a child with a stale, possibly out-of-range
    ``NNPT_PROCESS_ID``."""
    n_proc = int(probe.get("n_processes", 1))
    if n_proc > 1:
        raise ValueError(
            "degraded multi-process worlds are unsupported (probe "
            f"reported n_processes={n_proc}): surviving peer ranks "
            "cannot be reassigned from a local probe")
    for k in _COORD_ENV_KEYS:
        env.pop(k, None)
    env[_NUM_PROCESSES_ENV] = "1"
    env[_PROCESS_ID_ENV] = "0"
    env[DEGRADED_ENV] = str(int(probe.get("n_devices", 0)))
    return env


_PROBE_LOCAL_SRC = (
    "import jax, json; print('PROBE_WORLD|' + json.dumps("
    "{'n_processes': jax.process_count(), "
    "'n_devices': jax.device_count(), "
    "'local_devices': jax.local_device_count()}))"
)


def default_probe(timeout_s: float = 60.0,
                  env: Optional[dict] = None) -> Optional[dict]:
    """LOCAL capacity probe for the generic supervisor: a subprocess (jax
    only there — this module stays importable without it) reports this
    host's healthy device count under a hard timeout.  Coordinator env
    keys are stripped so the probe can never block on a dead rendezvous;
    the coordinator-aware probe is ``parallel.mesh.probe_world`` (the
    integrated CLI wires that one).  Returns the probe dict or None.

    A local probe of a formerly-multi-process world is by definition a
    DEGRADED view (mirroring ``probe_world``'s ``degraded =
    bool(coordinator_address)``): it reports ``degraded=True`` whenever
    the environment had configured a bigger world, so the supervisor's
    elastic path actually rewrites the child env instead of looping the
    dead rendezvous forever."""
    import os

    env = dict(os.environ if env is None else env)
    had_world = (any(k in env for k in _COORD_ENV_KEYS)
                 or int(env.get(_NUM_PROCESSES_ENV) or 1) > 1)
    for k in _COORD_ENV_KEYS:
        env.pop(k, None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        out = subprocess.run([sys.executable, "-c", _PROBE_LOCAL_SRC],
                             capture_output=True, text=True, env=env,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    for line in out.stdout.splitlines():
        if line.startswith("PROBE_WORLD|"):
            import json

            res = json.loads(line.split("|", 1)[1])
            res["degraded"] = had_world
            return res
    return None


def heartbeat_filename(role: str, process_id: Optional[int] = None
                       ) -> str:
    """Per-role/per-process heartbeat file name:
    ``heartbeat-<role>-p<P>.json`` (see ``train.telemetry``'s module
    docstring for the collision this naming fixes).  Lives HERE,
    stdlib-only, so the supervisor can derive its child's exact watch
    target without importing the jax-heavy telemetry module;
    ``process_id`` defaults to the DESIGN §10 world env channel."""
    import os

    if process_id is None:
        try:
            process_id = int(os.environ.get(_PROCESS_ID_ENV) or 0)
        except ValueError:
            process_id = 0
    return f"heartbeat-{role}-p{int(process_id)}.json"


def find_heartbeats(dirpath: str) -> List[str]:
    """Every heartbeat file in a telemetry dir: the legacy shared
    ``heartbeat.json`` plus the per-role/process
    ``heartbeat-<role>-p<P>.json`` forms ``train.telemetry`` writes
    since the fleet observability plane (two programs sharing one dir
    used to last-writer-win over one file)."""
    import glob
    import os

    return sorted(glob.glob(os.path.join(dirpath, "heartbeat*.json")))


def heartbeat_age_s(path: str, now: Optional[float] = None
                    ) -> Optional[float]:
    """Seconds since the telemetry heartbeat was last refreshed
    (mtime-based: train.telemetry's atomic replace bumps it on every
    write), or None if absent.  ``path`` may be an exact heartbeat
    file, a telemetry DIRECTORY (freshest of all heartbeats within), or
    the legacy GENERIC ``<dir>/heartbeat.json`` — only that generic
    name falls back to the freshest ``heartbeat*.json`` sibling, so a
    supervisor configured against the pre-fleet layout keeps watching a
    child that writes the per-role name.  A missing ROLE-QUALIFIED
    path deliberately does NOT fall back: the external hang monitor
    must watch its own child's file, and answering with a co-resident
    process's fresher heartbeat would mask exactly the hung-writer case
    the per-role naming exists to expose.  Lives HERE, stdlib-only,
    because the generic supervisor (tools/supervise.py) wraps arbitrary
    commands on hosts that may not even have JAX installed — it must
    never pull in the jax-importing telemetry module; telemetry
    re-exports this."""
    import os

    candidates = [path]
    if os.path.isdir(path):
        candidates = find_heartbeats(path)
    elif (not os.path.exists(path)
          and os.path.basename(path) == "heartbeat.json"):
        candidates = find_heartbeats(os.path.dirname(path) or ".")
    best: Optional[float] = None
    for p in candidates:
        try:
            mtime = os.stat(p).st_mtime
        except OSError:
            continue
        best = mtime if best is None else max(best, mtime)
    if best is None:
        return None
    return max(0.0, (time.time() if now is None else now) - best)


_ckpt_manifest_mod = None


def _ckpt_manifest():
    """utils/ckpt_manifest.py loaded BY FILE PATH (cached) — the regular
    relative import would execute utils/__init__, whose prng/logging pull
    jax; this module stays importable on the jax-less ops hosts the
    generic supervisor (tools/supervise.py) is meant for, same trick as
    tools/ckpt_fsck.py."""
    global _ckpt_manifest_mod
    if _ckpt_manifest_mod is None:
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "utils", "ckpt_manifest.py")
        spec = importlib.util.spec_from_file_location(
            "_nnpt_ckpt_manifest", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _ckpt_manifest_mod = mod
    return _ckpt_manifest_mod


def _restore_target(ckpt_dir: str):
    """(step, n_bad, path): newest snapshot passing FULL manifest
    verification, plus how many NEWER generations fail it — exactly the
    set the child's restore will quarantine on its way down the chain.
    Walks newest-first and stops hashing at the first verified generation
    (restore's own discipline: with multi-GB snapshots, sha256ing every
    older generation would add minutes of supervisor downtime per
    relaunch for one log line).  The verification itself is
    utils.ckpt_manifest — stdlib-only, same logic tools/ckpt_fsck.py runs
    — so the supervisor reports what a relaunch will actually resume
    from, not what merely exists on disk."""
    cm = _ckpt_manifest()
    bad = 0
    for step, path in reversed(cm.snapshot_steps(ckpt_dir)):
        if cm.verify(path):
            bad += 1
        else:
            return step, bad, path
    return None, bad, None


def alerts_between(path: Optional[str], start_pos: int
                   ) -> Tuple[List[dict], int]:
    """``kind="alert"`` records appended to a metrics JSONL past byte
    ``start_pos`` (the supervisor remembers the size before each launch,
    so the scan covers exactly one child's lifetime), plus the new end
    position.  Stdlib-only and bounded: reads only the appended tail.
    A file that SHRANK (fresh dir reused) rescans from 0."""
    import os

    if not path:
        return [], start_pos
    try:
        size = os.path.getsize(path)
    except OSError:
        return [], start_pos
    if size < start_pos:
        start_pos = 0
    if size == start_pos:
        return [], size
    out: List[dict] = []
    try:
        with open(path) as f:
            f.seek(start_pos)
            for line in f:
                line = line.strip()
                if not line or '"alert"' not in line:
                    continue
                try:
                    import json

                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line of a live run
                if isinstance(rec, dict) and rec.get("kind") == "alert":
                    out.append(rec)
    except OSError:
        return [], start_pos
    return out, size


def _run_child(cmd: Sequence[str], env: Optional[dict],
               heartbeat_path: Optional[str], heartbeat_timeout: float,
               log: Callable[[str], None],
               forward_signals: Sequence[int] = ()) -> int:
    """One child launch.  Without a heartbeat watch (or signals to
    forward) this is a plain blocking call.  With a heartbeat, the
    supervisor polls the telemetry ``heartbeat.json`` (train.telemetry
    writes it atomically per dispatch) and a child whose heartbeat goes
    stale is killed and reported as :data:`EXIT_HANG` — the EXTERNAL
    complement to the in-process ``utils.watchdog.HangWatchdog``,
    covering the failure mode where the whole host process (watchdog
    thread included) is frozen.

    ``forward_signals`` (the advance-notice seam): while the child runs,
    each listed signal delivered to the SUPERVISOR is re-sent to the
    child — a platform's preemption notice usually lands on the
    top-level pid, and the doomed child is the one that must checkpoint.

    The monitor ARMS at the child's first heartbeat write (mtime newer
    than the launch) — the same discipline as the in-process watchdog's
    first-``pat()`` arming: the first step's XLA/Mosaic compile can take
    arbitrarily long and must never be killed as a hang, and a leftover
    heartbeat from a previous run must not count either.  The symmetric
    cost: a child frozen BEFORE its first dispatch is not caught by this
    monitor (nor by the in-process one)."""
    hb = bool(heartbeat_path and heartbeat_timeout > 0)
    if not hb and not forward_signals:
        return subprocess.call(list(cmd), env=env)
    child = subprocess.Popen(list(cmd), env=env)
    restore: dict = {}

    def _forward(signum, frame):
        log(f"[supervise] forwarding signal {signum} (preemption "
            f"notice) to child {child.pid}")
        try:
            child.send_signal(signum)
        except OSError:
            pass

    for s in forward_signals:
        try:
            restore[s] = signal.signal(s, _forward)
        except ValueError:   # not the main thread: no forwarding
            break
    try:
        started = time.time()
        poll_s = (max(0.05, min(heartbeat_timeout / 4.0, 5.0))
                  if hb else 0.1)
        armed = False
        while True:
            rc = child.poll()
            if rc is not None:
                return rc
            if not hb:
                time.sleep(poll_s)
                continue
            age = heartbeat_age_s(heartbeat_path)
            if not armed:
                # arm only once THIS child has written the heartbeat
                # (mtime after launch <=> age < runtime)
                if age is not None and age < time.time() - started:
                    armed = True
                else:
                    time.sleep(poll_s)
                    continue
            idle = age if age is not None else time.time() - started
            if idle > heartbeat_timeout:
                log(f"[supervise] heartbeat stale for {idle:.0f}s "
                    f"(> {heartbeat_timeout:.0f}s): killing child "
                    f"{child.pid} as hung")
                child.terminate()
                try:
                    child.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    child.kill()
                    child.wait()
                # deliberately EXIT_HANG even when the SIGTERM was
                # absorbed gracefully (the child checkpoints and exits
                # 0): that 0 means "clean final snapshot", NOT "job
                # finished" — a stalled-but-signal-responsive child must
                # be retried, not reported complete.  A healthy tail
                # phase is protected by Telemetry.alive() beats during
                # checkpoint/eval, and a spuriously killed near-done run
                # converges in one resumed relaunch.
                return EXIT_HANG
            time.sleep(poll_s)
    finally:
        for s, prev in restore.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass


def supervise(cmd: Sequence[str], max_restarts: int,
              backoff: float = 1.0, backoff_cap: float = 60.0,
              env: Optional[dict] = None,
              log: Callable[[str], None] = None,
              heartbeat_path: Optional[str] = None,
              heartbeat_timeout: float = 0.0,
              postmortem_path: Optional[str] = None,
              ckpt_dir: Optional[str] = None,
              alerts_path: Optional[str] = None,
              jitter: float = 0.5,
              elastic: bool = False,
              min_devices: int = 0,
              probe: Optional[Callable[[], Optional[dict]]] = None,
              elastic_after: int = 2,
              events_path: Optional[str] = None,
              forward_preempt: bool = False,
              _sleep: Callable[[float], None] = time.sleep,
              _rand: Callable[[], float] = random.random) -> int:
    """Run ``cmd`` under the crash-restart policy; return the final exit
    code.

    ``max_restarts`` bounds RELAUNCHES (the initial launch is free).  Exit
    0, 44, 45 and 46 stop immediately (see the module exit-code
    contract); anything else — watchdog 42, peer-loss 43, crashes, signal
    deaths (negative returncodes) — is retried with exponential backoff
    ``backoff * 2^k`` capped at ``backoff_cap`` seconds and multiplied by
    a uniform jitter in ``[1-jitter, 1]`` — downward only, so
    ``backoff_cap`` stays a HARD upper bound an operator can size against
    a preemption-notice window, and the spread survives at the cap (an
    upward jitter clamped to the cap re-synchronizes every host at
    exactly ``backoff_cap`` once the doubling saturates): every host of a
    pod relaunches after the same failure, and pure deterministic
    doubling would hammer a recovering coordinator with a thundering herd
    at the exact same instants.  The relaunched command is identical;
    resume-from-newest-snapshot is the child's job (``cli`` appends
    ``--resume`` when a checkpoint dir is configured).

    ``elastic``: after ``elastic_after`` CONSECUTIVE peer-loss exits
    (43/42 — a world that keeps failing to re-form), run ``probe`` (a
    bounded topology discovery, e.g. ``parallel.mesh.probe_world``;
    defaults to the local :func:`default_probe`) and relaunch at the
    probed world: a degraded probe rewrites the child's world env
    (:func:`degrade_env`) so the child forms the SMALLER world and rides
    its elastic restore path.  A probe below ``min_devices`` parks and
    re-polls with the same backoff, consuming the restart budget;
    exhausting it returns :data:`EXIT_CAPACITY` (46, no-retry).  Only
    the supervisor of the original rank 0 ever degrades: two partition
    survivors independently relaunching as single-process leaders would
    split-brain the shared checkpoint dir, so non-zero ranks are fenced
    to same-world retries.

    ``heartbeat_path`` + ``heartbeat_timeout`` arm the external hang
    detector (see :func:`_run_child`).  ``postmortem_path``: when a child
    dies abnormally and the telemetry flight recorder dumped a postmortem
    during THIS child's lifetime, the relaunch log points at it.
    ``alerts_path`` (the child's metrics.jsonl): ``kind="alert"``
    records the child emitted during its lifetime — SLO burn-rate, EMA
    z-score anomalies — are summarized next to each exit, so the
    relaunch log shows what the telemetry plane SAW before the death.
    Observe-and-annotate only: alerts never change the retry decision
    (the exit-code contract owns that).
    ``ckpt_dir``: before each relaunch, log the newest VERIFIED snapshot
    (full manifest-checksum pass, utils.ckpt_manifest) the child's
    ``--resume`` will land on — so an operator tailing the supervisor sees
    immediately whether a crash mid-checkpoint cost a generation.
    ``events_path``: append machine-readable lifecycle records (launch /
    exit / relaunch, with wall-clock, run id, incarnation, rc) as JSONL —
    the supervisor half of the goodput join (``utils/goodput.py``).
    ``forward_preempt``: re-send :data:`PREEMPT_SIGNAL` (SIGUSR1) to the
    running child — a platform's advance notice lands on the top-level
    supervisor pid, and the child is the process that must answer with a
    final checkpoint + exit 47 (the :class:`GracefulShutdown` notice
    path, priced as ``drain`` instead of rollback+replay).
    """
    if log is None:
        log = lambda m: print(m, file=sys.stderr, flush=True)

    def next_delay(restarts_used: int) -> float:
        d = min(backoff * (2.0 ** restarts_used), backoff_cap)
        if jitter > 0:
            d *= 1.0 - jitter * _rand()
        return d

    attempt = 0
    peer_streak = 0
    child_env = dict(env) if env is not None else None
    # run identity for the trace channel: one run_id for the whole
    # supervised job (inherited when the operator set it — e.g. shared
    # across a multi-host world like COORDINATOR_ADDRESS — else
    # generated once here), plus the attempt number as the incarnation
    import os as _os

    _base = env if env is not None else _os.environ
    run_id = _base.get(RUN_ID_ENV) or (
        f"run-{int(time.time())}-{_os.getpid()}")
    # original world configuration, for grow-back: a degraded relaunch
    # rewrites child_env, and a LATER probe that finds the full world
    # healthy again must restore these keys — otherwise the child keeps
    # forming the small world while the log reports the full topology
    _world_keys = _COORD_ENV_KEYS + (_NUM_PROCESSES_ENV, _PROCESS_ID_ENV)
    orig_world = {k: (env if env is not None else _os.environ).get(k)
                  for k in _world_keys}
    while True:
        attempt += 1
        if child_env is None:
            child_env = dict(_os.environ)
        child_env[RUN_ID_ENV] = run_id
        child_env[INCARNATION_ENV] = str(attempt - 1)
        if not child_env.get(PREEMPT_NOTICE_ENV):
            # give the notice file somewhere to land: without a path the
            # signal still arrives but the grace window degrades to the
            # 2 s default — an in-child fault injection or an operator's
            # write_preempt_notice() must agree with the child on where
            import tempfile as _tempfile
            child_env[PREEMPT_NOTICE_ENV] = _os.path.join(
                _tempfile.gettempdir(),
                f"nnpt-preempt-{_os.getpid()}.json")
        log(f"[supervise] attempt {attempt}: {' '.join(cmd)}")
        launched = time.time()
        _append_event(events_path, {
            "kind": "supervisor", "event": "launch",
            "t": round(launched, 6), "run": run_id, "inc": attempt - 1})
        alert_pos = 0
        if alerts_path:
            try:
                alert_pos = _os.path.getsize(alerts_path)
            except OSError:
                alert_pos = 0
        rc = _run_child(cmd, child_env, heartbeat_path, heartbeat_timeout,
                        log, forward_signals=((PREEMPT_SIGNAL,)
                                              if forward_preempt else ()))
        _append_event(events_path, {
            "kind": "supervisor", "event": "exit",
            "t": round(time.time(), 6), "run": run_id,
            "inc": attempt - 1, "rc": rc})
        if alerts_path:
            alerts, _ = alerts_between(alerts_path, alert_pos)
            if alerts:
                by_name: dict = {}
                for a in alerts:
                    key = str(a.get("alert"))
                    by_name[key] = by_name.get(key, 0) + 1
                rendered = ", ".join(f"{k} x{v}"
                                     for k, v in sorted(by_name.items()))
                log(f"[supervise] {len(alerts)} telemetry alert(s) "
                    f"during this child: {rendered} (observe-only; the "
                    "exit code decides the relaunch)")
        # any ABNORMAL exit — including the no-retry anomaly abort (44),
        # whose dump is the flagship black-box case — gets the pointer
        if rc != EXIT_OK and postmortem_path:
            try:
                if _os.stat(postmortem_path).st_mtime >= launched - 1.0:
                    log(f"[supervise] child left a postmortem: "
                        f"{postmortem_path}")
            except OSError:
                pass
        if rc in _NO_RETRY:
            if rc == EXIT_ANOMALY:
                log("[supervise] child exited 44 (anomaly abort): "
                    "deterministic training failure — not retrying")
            elif rc == EXIT_SDC:
                log("[supervise] child exited 45 (SDC abort): "
                    "deterministic replica divergence or device strike "
                    "budget exhausted — not retrying")
            elif rc == EXIT_CAPACITY:
                log("[supervise] child exited 46 (capacity abort): the "
                    "healthy world is below --min_devices — not retrying "
                    "(a relaunch cannot create chips)")
            elif rc == EXIT_DECOMMISSION:
                log("[supervise] child exited 47 (decommission): drained "
                    "and retired on purpose — not retrying (no restart "
                    "budget burned)")
            else:
                log("[supervise] child completed (exit 0)")
            return rc
        peer_streak = peer_streak + 1 if rc in _PEER_LOSS_CODES else 0
        restarts_used = attempt - 1
        if restarts_used >= max_restarts:
            log(f"[supervise] giving up: {max_restarts} restarts exhausted "
                f"(last exit {rc})")
            return rc
        delay = next_delay(restarts_used)
        reason = {EXIT_HANG: "watchdog hang",
                  EXIT_PEER: "peer loss"}.get(rc, "crash")
        log(f"[supervise] child exit {rc} ({reason}); relaunching in "
            f"{delay:.1f}s ({restarts_used + 1}/{max_restarts})")
        _append_event(events_path, {
            "kind": "supervisor", "event": "relaunch",
            "t": round(time.time(), 6), "run": run_id,
            "inc": attempt, "delay_s": round(delay, 3), "reason": reason})
        if ckpt_dir:
            step, bad, path = _restore_target(ckpt_dir)
            if step is not None:
                cm = _ckpt_manifest()
                world = cm.world_line(cm.snapshot_meta(path))
                log(f"[supervise] relaunch resumes from verified snapshot "
                    f"step {step}"
                    + (f" [{world}]" if world else "")
                    + (f" ({bad} unverified generation(s) will be "
                       "quarantined on restore)" if bad else ""))
            else:
                cm = _ckpt_manifest()
                legacy = any(
                    (p / "meta.json").exists()
                    and not (p / cm.MANIFEST).exists()
                    for _, p in cm.snapshot_steps(ckpt_dir))
                if legacy:
                    # the child's restore REFUSES on pre-durability dirs
                    # rather than silently restarting from step 0 — say
                    # so instead of promising a from-scratch run
                    log("[supervise] no verified snapshot in "
                        f"{ckpt_dir} but pre-manifest snapshot(s) exist: "
                        "the relaunch will refuse to start — run "
                        "tools/ckpt_fsck.py --adopt to trust them")
                else:
                    log("[supervise] no verified snapshot in "
                        f"{ckpt_dir}: relaunch restarts from scratch"
                        + (f" ({bad} unverified generation(s) — "
                           "tools/ckpt_fsck.py)" if bad else ""))
        _sleep(delay)
        # ---- elastic probe-and-shrink (DESIGN.md §10) --------------------
        # only after REPEATED peer loss: one 43 can be a transient blip a
        # same-world retry absorbs; a streak means the old world cannot
        # re-form and looping the relaunch through world_setup forever is
        # the exact failure mode this policy exists to break.
        if not (elastic and peer_streak >= elastic_after):
            continue
        # split-brain fence: during a partition EVERY surviving host's
        # supervisor reaches this point, and each local probe reports a
        # degraded single-process world — if all of them relaunched as
        # process 0, two divergent leaders would interleave writes over
        # the same shared checkpoint dir.  Only the supervisor of the
        # ORIGINAL rank 0 may continue alone, and rank 0 must be
        # POSITIVELY identified: a multi-process world whose rank came
        # from some other channel (no NNPT_PROCESS_ID) fences too —
        # "every host assumes it is rank 0" is exactly the split brain.
        # The others retry at the current world until their budget runs
        # out (an operator, or the healed rank 0, owns the next move).
        orig_multi = (any(orig_world.get(k) for k in _COORD_ENV_KEYS)
                      or int(orig_world.get(_NUM_PROCESSES_ENV) or 1) > 1)
        pid_raw = orig_world.get(_PROCESS_ID_ENV)
        if orig_multi and (pid_raw is None or int(pid_raw) != 0):
            log("[supervise] elastic: original rank "
                f"{'unknown (no ' + _PROCESS_ID_ENV + ')' if pid_raw is None else pid_raw}"
                " is fenced from degraded relaunch (only a positively-"
                "identified rank 0 may continue as a shrunken world — "
                "two partition survivors must not both become single-"
                "process leaders over the same checkpoint dir); "
                "retrying at the current world")
            continue
        prober = probe if probe is not None else default_probe
        floor = max(1, int(min_devices))
        parked = False
        while True:
            res = prober()
            if res is None and not parked:
                # no topology answer and no evidence of a shortfall:
                # retrying at the current world is the conservative move
                # (the streak is kept, so the next loss re-probes)
                log("[supervise] elastic probe failed (no topology "
                    "answer); retrying at the current world")
                break
            n = int(res.get("n_devices", 0)) if res is not None else -1
            if res is not None and n >= floor:
                if res.get("degraded"):
                    try:
                        child_env = degrade_env(
                            dict(child_env if child_env is not None
                                 else _os.environ), res)
                    except ValueError as e:
                        # keep the streak (like the probe-failure path):
                        # the next peer loss re-probes immediately
                        log(f"[supervise] {e}; retrying at the current "
                            "world")
                        break
                    log(f"[supervise] topology probe: {n} healthy "
                        f"device(s) across "
                        f"{res.get('n_processes', '?')} process(es) — "
                        "relaunching at the DEGRADED world")
                else:
                    log(f"[supervise] topology probe: {n} healthy "
                        f"device(s) across "
                        f"{res.get('n_processes', '?')} process(es)")
                    if (child_env is not None
                            and DEGRADED_ENV in child_env):
                        # grow-back: the probe formed the FULL world
                        # again after a degraded relaunch — restore the
                        # original world configuration so the child
                        # actually rejoins it (the elastic restore path
                        # reshards 2->4 too)
                        for k, v in orig_world.items():
                            if v is None:
                                child_env.pop(k, None)
                            else:
                                child_env[k] = v
                        child_env.pop(DEGRADED_ENV, None)
                        log("[supervise] probe reports the full world "
                            "healthy: restoring the original topology "
                            "for the relaunch (grow-back)")
                peer_streak = 0
                break
            # capacity below the floor — or, once PARKED, a transient
            # probe failure (relaunching on it would let the child's own
            # floor check convert a known shortfall into a permanent
            # no-retry exit 46): park and re-poll with backoff,
            # consuming the restart budget so a floor that can never be
            # met terminates as a typed no-retry exit instead of an
            # infinite poll loop
            parked = True
            shown = (f"{n} healthy device(s)" if res is not None
                     else "no topology answer (probe failed)")
            attempt += 1
            restarts_used = attempt - 1
            if restarts_used >= max_restarts:
                log(f"[supervise] capacity shortfall: probe found "
                    f"{shown} < --min_devices {floor} and the "
                    f"restart budget is exhausted — exiting "
                    f"{EXIT_CAPACITY} (capacity abort)")
                return EXIT_CAPACITY
            delay = next_delay(restarts_used)
            log(f"[supervise] capacity shortfall: {shown} "
                f"< --min_devices {floor}; re-probing in {delay:.1f}s "
                f"({restarts_used + 1}/{max_restarts})")
            _sleep(delay)


# ---------------------------------------------------------------------------
# process-group supervision (DESIGN.md §11 "Serving fleet")
# ---------------------------------------------------------------------------

from dataclasses import dataclass, field as _field  # noqa: E402  (grouped
#   with the subsystem it serves; the module above predates dataclasses)


@dataclass
class ChildSpec:
    """One supervised child of a :class:`GroupSupervisor`: its command,
    role, and PER-CHILD contracts — heartbeat staleness bound, restart
    budget/backoff, and the exit codes that stop it for good.  ``spawn``
    overrides process creation (the fleet router passes a callable that
    wires stdio pipes and hands the Popen back); ``on_spawn`` fires
    after every (re)launch so the owner can re-attach to the fresh
    process."""
    name: str
    cmd: Sequence[str] = ()
    role: str = "worker"
    env: Optional[dict] = None
    heartbeat_path: Optional[str] = None
    heartbeat_timeout: float = 0.0
    max_restarts: int = 3
    backoff: float = 0.5
    backoff_cap: float = 30.0
    no_retry: Tuple[int, ...] = _NO_RETRY
    spawn: Optional[Callable] = None      # (spec, env) -> Popen-like
    on_spawn: Optional[Callable] = None   # (spec, proc, incarnation)


@dataclass
class _ChildState:
    spec: ChildSpec
    proc: Any = None
    incarnation: int = -1          # attempts - 1 (stamped into the env)
    restarts_used: int = 0
    launched_at: float = 0.0
    hb_armed: bool = False
    relaunch_at: Optional[float] = None   # pending backoff deadline
    final_rc: Optional[int] = None        # set once the child is done
    gave_up: bool = False
    retired: bool = False          # next exit is TERMINAL whatever its rc
    last_rc: Optional[int] = None  # most recent reaped rc (retire() uses
                                   # it to finalize a pending relaunch)
    events: List[dict] = _field(default_factory=list)


class GroupSupervisor:
    """Role-aware supervision of a PROCESS GROUP — the multi-child
    generalization of :func:`supervise`, which babysits exactly one
    child.  N children (serving replicas, a prefill tier, a router
    sidecar, ...) each carry their own :class:`ChildSpec` contract, and
    one failing child is relaunched with ITS backoff/budget without
    disturbing its siblings — the fleet property a serving tier needs
    (kill one replica: the others keep serving while it restarts).

    Deliberately NON-BLOCKING: :meth:`poll` reaps exits, kills
    stale-heartbeat children (reported as :data:`EXIT_HANG`, the same
    external-hang contract as :func:`_run_child`), executes due
    relaunches, and returns the events since the previous poll — so the
    owner (a fleet router pumping request traffic, a test) stays in
    control of the loop instead of parking inside a blocking
    ``supervise()`` call.  Exit-code handling is per child:
    ``spec.no_retry`` stops that child for good (``stopped`` event),
    anything else relaunches under ``backoff * 2^k`` (downward-jittered,
    capped — the :func:`supervise` policy) until ``max_restarts`` is
    spent (``gave_up``).  Every launch stamps the shared
    :data:`RUN_ID_ENV` plus the child's :data:`INCARNATION_ENV`, so
    trace/telemetry merging works exactly as under the single-child
    supervisor.  Stdlib-only, like everything else in this module."""

    def __init__(self, specs: Sequence[ChildSpec],
                 log: Optional[Callable[[str], None]] = None,
                 jitter: float = 0.5,
                 env: Optional[dict] = None,
                 events_path: Optional[str] = None,
                 _rand: Callable[[], float] = random.random,
                 now_fn: Callable[[], float] = time.time):
        import os as _os

        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate child names: {names}")
        self._log = log or (lambda m: print(m, file=sys.stderr,
                                            flush=True))
        self._jitter = float(jitter)
        self._rand = _rand
        self._now = now_fn
        self._base_env = dict(env if env is not None else _os.environ)
        self.run_id = self._base_env.get(RUN_ID_ENV) or (
            f"run-{int(time.time())}-{_os.getpid()}")
        self._children = {s.name: _ChildState(spec=s) for s in specs}
        self._started = False
        # lifecycle JSONL for the goodput join (see supervise()'s
        # events_path); wall-clock stamped even under a virtual now_fn —
        # the ledger correlates against trace timestamps, which are real
        self._events_path = events_path

    def _emit_event(self, st: _ChildState, kind: str, **extra) -> None:
        spec = st.spec
        rec = {"kind": "supervisor", "event": kind,
               "t": round(time.time(), 6), "run": self.run_id,
               "child": spec.name, "role": spec.role,
               "inc": st.incarnation, **extra}
        pid = (spec.env or {}).get(_PROCESS_ID_ENV)
        if pid is not None:
            try:
                rec["p"] = int(pid)
            except (TypeError, ValueError):
                pass
        _append_event(self._events_path, rec)

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._started = True
        for st in self._children.values():
            self._launch(st)

    def add_child(self, spec: ChildSpec) -> None:
        """Register (and, once :meth:`start` has run, immediately launch)
        a NEW child at runtime — the scale-out half of the autopilot
        contract.  Names stay unique for the supervisor's lifetime."""
        if spec.name in self._children:
            raise ValueError(f"duplicate child name: {spec.name!r}")
        st = _ChildState(spec=spec)
        self._children[spec.name] = st
        if self._started:
            self._launch(st)

    def retire(self, name: str) -> None:
        """Mark a child so its NEXT exit is terminal regardless of rc —
        no relaunch, no backoff burn.  The scale-in half of the autopilot
        contract: retire first, then ask the child to drain and exit
        (:data:`EXIT_DECOMMISSION`); if the drain stalls and the owner
        has to SIGKILL, the signal death still must not relaunch the
        replica the control plane just removed.  A retire that lands
        while a relaunch backoff is pending cancels it and finalizes the
        child at its last reaped rc."""
        st = self._children[name]
        st.retired = True
        if st.relaunch_at is not None:
            st.relaunch_at = None
            st.final_rc = st.last_rc
            self._log(f"[group] {st.spec.role}/{name}: retired while a "
                      "relaunch was pending — relaunch cancelled")
        else:
            self._log(f"[group] {st.spec.role}/{name}: retired (next "
                      "exit is terminal)")

    def notify_preempt(self, name: str, grace_s: float = 2.0) -> bool:
        """Propagate an advance preemption notice to a live child: write
        the notice file (when the child's env names one via
        :data:`PREEMPT_NOTICE_ENV`) and send :data:`PREEMPT_SIGNAL`.
        The child answers per its own contract — a trainer checkpoints
        and exits 47, a serving worker stops admitting, finishes
        in-flight work inside the grace window and exits 47 — and 47 is
        already in ``no_retry``, so the exit is terminal without an
        explicit :meth:`retire`.  Returns whether the notice was
        delivered (False: the child is already dead or unreachable —
        the crash path owns what happens next)."""
        st = self._children[name]
        if st.proc is None or st.proc.poll() is not None:
            return False
        env = dict(self._base_env)
        env.update(st.spec.env or {})
        path = env.get(PREEMPT_NOTICE_ENV)
        if path:
            write_preempt_notice(path, grace_s=grace_s)
        try:
            st.proc.send_signal(PREEMPT_SIGNAL)
        except OSError:
            return False
        self._log(f"[group] {st.spec.role}/{name}: preemption notice "
                  f"delivered (grace {float(grace_s):.1f}s)")
        self._emit_event(st, "preempt_notice",
                         grace_s=round(float(grace_s), 3))
        return True

    def remove_child(self, name: str) -> None:
        """Forget a TERMINAL child (stopped / gave up) so long-lived
        fleets don't accrue bookkeeping for every replica ever retired.
        Refuses to drop a child that could still run."""
        st = self._children[name]
        if st.final_rc is None and not st.gave_up:
            raise ValueError(f"child {name!r} is not terminal")
        del self._children[name]

    def _launch(self, st: _ChildState) -> None:
        spec = st.spec
        env = dict(self._base_env)
        if spec.env:
            env.update(spec.env)
        st.incarnation += 1
        env[RUN_ID_ENV] = self.run_id
        env[INCARNATION_ENV] = str(st.incarnation)
        if spec.spawn is not None:
            st.proc = spec.spawn(spec, env)
        else:
            st.proc = subprocess.Popen(list(spec.cmd), env=env)
        st.launched_at = self._now()
        st.hb_armed = False
        st.relaunch_at = None
        self._log(f"[group] {spec.role}/{spec.name} inc "
                  f"{st.incarnation}: pid {st.proc.pid}")
        self._emit_event(st, "launch", pid=getattr(st.proc, "pid", None))
        if spec.on_spawn is not None:
            spec.on_spawn(spec, st.proc, st.incarnation)

    def _next_delay(self, st: _ChildState) -> float:
        d = min(st.spec.backoff * (2.0 ** st.restarts_used),
                st.spec.backoff_cap)
        if self._jitter > 0:
            d *= 1.0 - self._jitter * self._rand()
        return d

    def _check_heartbeat(self, st: _ChildState) -> bool:
        """True when the child was just killed as hung (rc handled by
        the caller's reap on the next lines)."""
        spec = st.spec
        if not (spec.heartbeat_path and spec.heartbeat_timeout > 0):
            return False
        age = heartbeat_age_s(spec.heartbeat_path)
        now = self._now()
        if not st.hb_armed:
            # arm at THIS incarnation's first write (same discipline as
            # _run_child: first-compile must never be killed as a hang,
            # and a previous incarnation's file must not count)
            if age is not None and age < now - st.launched_at:
                st.hb_armed = True
            return False
        if age is not None and age > spec.heartbeat_timeout:
            self._log(f"[group] {spec.role}/{spec.name}: heartbeat "
                      f"stale for {age:.0f}s "
                      f"(> {spec.heartbeat_timeout:.0f}s): killing as "
                      "hung")
            st.proc.terminate()
            try:
                st.proc.wait(timeout=10)
            except Exception:
                st.proc.kill()
                st.proc.wait()
            return True
        return False

    def poll(self) -> List[dict]:
        """One non-blocking supervision pass; returns the events since
        the last poll: ``exit`` (rc, relaunch decision), ``hang_kill``,
        ``relaunch``, ``stopped`` (no-retry exit), ``gave_up`` (budget
        spent)."""
        events: List[dict] = []

        def ev(st: _ChildState, kind: str, **extra) -> None:
            e = {"event": kind, "child": st.spec.name,
                 "role": st.spec.role, "incarnation": st.incarnation,
                 **extra}
            st.events.append(e)
            events.append(e)
            self._emit_event(st, kind, **extra)

        now = self._now()
        for st in self._children.values():
            if st.final_rc is not None or st.gave_up:
                continue
            if st.proc is not None and st.proc.poll() is None:
                if self._check_heartbeat(st):
                    rc = st.proc.poll()
                    ev(st, "hang_kill", rc=rc)
                    # treat as EXIT_HANG for the retry contract, like
                    # _run_child: a graceful SIGTERM exit 0 here still
                    # means "stalled but signal-responsive", not done
                    self._after_exit(st, EXIT_HANG, ev)
                continue
            if st.proc is not None and st.relaunch_at is None:
                rc = st.proc.poll()
                ev(st, "exit", rc=rc)
                self._after_exit(st, rc, ev)
                continue
            if st.relaunch_at is not None and now >= st.relaunch_at:
                st.restarts_used += 1
                self._launch(st)
                ev(st, "relaunch", restarts_used=st.restarts_used,
                   max_restarts=st.spec.max_restarts)
        return events

    def _after_exit(self, st: _ChildState, rc: int, ev) -> None:
        spec = st.spec
        st.last_rc = rc
        if st.retired or rc in spec.no_retry:
            st.final_rc = rc
            ev(st, "stopped", rc=rc)
            why = ("retired" if st.retired and rc not in spec.no_retry
                   else "no-retry contract")
            self._log(f"[group] {spec.role}/{spec.name} exited {rc} "
                      f"({why}): stopped")
            return
        if st.restarts_used >= spec.max_restarts:
            st.gave_up = True
            st.final_rc = rc
            ev(st, "gave_up", rc=rc,
               max_restarts=spec.max_restarts)
            self._log(f"[group] {spec.role}/{spec.name}: "
                      f"{spec.max_restarts} restarts exhausted "
                      f"(last exit {rc}) — giving up on this child")
            return
        delay = self._next_delay(st)
        st.relaunch_at = self._now() + delay
        self._log(f"[group] {spec.role}/{spec.name} exit {rc}; "
                  f"relaunching in {delay:.1f}s "
                  f"({st.restarts_used + 1}/{spec.max_restarts}); "
                  "siblings undisturbed")

    # ---- introspection -------------------------------------------------
    def proc(self, name: str):
        return self._children[name].proc

    def incarnation(self, name: str) -> int:
        return self._children[name].incarnation

    def alive(self, name: str) -> bool:
        st = self._children[name]
        return (st.proc is not None and st.relaunch_at is None
                and st.final_rc is None and not st.gave_up
                and st.proc.poll() is None)

    def pending_relaunch(self, name: str) -> bool:
        return self._children[name].relaunch_at is not None

    def done(self, name: str) -> Optional[int]:
        """Final rc once the child will never run again, else None."""
        st = self._children[name]
        return st.final_rc if (st.final_rc is not None or st.gave_up) \
            else None

    def running(self) -> bool:
        """Any child not yet in a TERMINAL state (stopped/gave up)?  A
        child whose process has exited but whose exit has not been
        reaped by :meth:`poll` still counts — its retry decision is
        pending, so the owner must keep polling."""
        return any(st.final_rc is None and not st.gave_up
                   for st in self._children.values())

    def terminate_all(self, grace_s: float = 10.0) -> None:
        for st in self._children.values():
            st.relaunch_at = None
            if st.proc is not None and st.proc.poll() is None:
                st.proc.terminate()
        deadline = time.time() + grace_s
        for st in self._children.values():
            if st.proc is None:
                continue
            try:
                st.proc.wait(timeout=max(0.1, deadline - time.time()))
            except Exception:
                st.proc.kill()
                try:
                    st.proc.wait(timeout=5)
                except Exception:
                    pass
