"""Trainer: the orchestration layer.

TPU-native replacement for the reference's ``dist_train(args)``
(dataParallelTraining_NN_MPI.py:56-236, SURVEY.md C2): world/mesh formation,
dataset build, deterministic replicated init, sharded loading, the jitted
epoch/step loop, and per-epoch loss reporting — with checkpoint/resume,
structured metrics and profiling as extensions (SURVEY.md §5 notes all of
those are absent in the reference).
"""

from __future__ import annotations

import sys
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..config import TrainConfig
from ..data.datasets import build_dataset
from ..data.loader import ShardedLoader
from ..models.registry import build_model
from ..ops import optim as optim_lib
from ..ops import schedules
from ..parallel import data_parallel as dp
from ..parallel.mesh import describe, make_mesh, world_setup
from ..utils import compile_ledger as ledger_lib
from ..utils import profiling, prng
from ..utils.logging import MetricsLogger, Throughput, is_leader, log
from . import telemetry as telemetry_lib
from . import trace as trace_lib
from .state import TrainState


class Trainer:
    def __init__(self, cfg: TrainConfig, mesh=None, data=None):
        # --param_dtype: the training-job spelling of the model's param
        # storage dtype (bf16 params halve HBM + the sharded-update
        # all-gather bytes; pair with --master_weights for f32 update
        # math).  Applied to the model config HERE so every downstream
        # consumer — model init, checkpoint templates, FLOPs accounting —
        # sees one consistent dtype.
        if cfg.param_dtype:
            import dataclasses as _dc

            if cfg.param_dtype not in ("float32", "bfloat16", "float16"):
                raise ValueError(
                    f"unknown --param_dtype {cfg.param_dtype!r} "
                    "(choices: float32, bfloat16, float16)")
            cfg = _dc.replace(cfg, model=_dc.replace(
                cfg.model, dtype=cfg.param_dtype))
        self.cfg = cfg
        world_setup()
        # capacity floor (DESIGN.md §10): a world below --min_devices must
        # not train at all — exit 46 (no-retry) instead of running a
        # degraded job the operator said is too small to be useful
        if cfg.min_devices and jax.device_count() < cfg.min_devices:
            from .resilience import CapacityAbort

            raise CapacityAbort(
                f"{jax.device_count()} healthy device(s) < --min_devices "
                f"{cfg.min_devices}: refusing to train below the capacity "
                "floor (exit 46; raise capacity or lower --min_devices)")
        if cfg.collective_timeout > 0:
            from ..parallel import distributed

            distributed.set_collective_timeout(cfg.collective_timeout)
        self.mesh = mesh if mesh is not None else make_mesh(cfg.mesh)
        self.seq_parallel = self.mesh.shape.get("seq", 1) > 1
        self.pipeline = self.mesh.shape.get("pipe", 1) > 1
        self.expert = self.mesh.shape.get("expert", 1) > 1
        self.tensor = self.mesh.shape.get("tensor", 1) > 1
        # strategy -> step builder:
        #   pipe (x tensor)      -> parallel.pipeline shard_map (explicit
        #                           Megatron TP inside the stages, DP x TP x PP)
        #   tensor/fsdp (no pipe)-> parallel.gspmd (jit + annotations)
        #   seq                  -> parallel.spmd shard_map (ring attention)
        #   expert               -> parallel.expert shard_map (all_to_all)
        #   seq x tensor        -> parallel.spmd sp_tp shard_map (Megatron
        #                          matmuls + ring/ulysses attention)
        #   expert x tensor     -> parallel.expert moe_tp shard_map (Megatron
        #                          attention + tensor-sharded experts);
        #                          x seq runs seq-sharded attention too, and
        #                          seq x tensor with an MoE FFN rides the
        #                          same step with the expert axis at 1
        #   seq x expert        -> parallel.expert shard_map with seq_axis
        #                          (ring attention + all_to_all experts)
        fsdp_on = self.mesh.shape.get("fsdp", 1) > 1
        moe_model = cfg.model.moe_experts > 0
        self.sp_tp = (self.seq_parallel and self.tensor
                      and not (self.pipeline or self.expert or fsdp_on
                               or moe_model))
        # (SP x) EP x TP: Megatron attention + tensor-sharded experts,
        # optionally with seq-sharded attention over 'seq'.  SP x TP with
        # an MoE FFN rides this path too, with the expert axis at 1
        # (experts held whole, hidden dim tensor-sharded — no all_to_all).
        self.ep_tp = (self.tensor and not (self.pipeline or fsdp_on)
                      and (self.expert
                           or (self.seq_parallel and moe_model)))
        self.sp_ep = (self.seq_parallel and self.expert
                      and not (self.pipeline or self.tensor or fsdp_on))
        # DP x PP x EP (x SP x TP): the pipeline step threads the MoE aux
        # loss through the tick carry and runs the all_to_all dispatch
        # inside each stage (tensor > 1 additionally Megatron-shards
        # attention heads and each expert's hidden dim — GShard in the
        # pipeline; seq > 1 seq-shards each stage's attention)
        self.pp_ep = (self.pipeline and self.expert and not fsdp_on)
        # DP x PP x SP (x TP/EP): each stage's attention rings over 'seq'
        # while activations rotate over 'pipe' — long-context pipelining,
        # composing with Megatron TP and expert parallelism (round 4)
        self.pp_sp = (self.pipeline and self.seq_parallel and not fsdp_on)
        self.gspmd = (not self.pipeline and not self.sp_tp and not self.ep_tp
                      and (self.tensor or fsdp_on))
        unwired = [name for name, on in
                   (("seq", self.seq_parallel and not self.pp_sp),
                    ("fsdp", fsdp_on),
                    ("expert", self.expert and not self.pp_ep)) if on]
        if self.pipeline and unwired:
            raise NotImplementedError(
                f"pipe composes with the data, tensor, expert (MoE), and "
                f"seq (seq-sharded attention) axes in any mix; got pipe x "
                f"{unwired} — fsdp's parameter sharding is the GSPMD "
                "path's job (compose parallel.* step builders directly)")
        exclusive = [name for name, on in
                     (("seq", self.seq_parallel and not self.sp_tp
                       and not self.sp_ep and not self.ep_tp
                       and not self.pp_sp),
                      ("tensor/fsdp", self.gspmd),
                      ("expert", self.expert and not self.ep_tp
                       and not self.sp_ep and not self.pp_ep)) if on]
        if len(exclusive) > 1:
            raise NotImplementedError(
                f"wired combinations: one of seq/tensor/fsdp/expert alone, "
                f"pipe x tensor, seq x tensor, seq x expert, expert x "
                f"tensor, or seq x expert x tensor (all x data); got "
                f"{exclusive} — compose parallel.* step builders directly "
                "for other mixes")
        if self.pipeline and cfg.model.arch != "transformer":
            raise ValueError("pipe axis > 1 requires the transformer model")
        if self.expert and (cfg.model.arch != "transformer"
                            or cfg.model.moe_experts <= 0):
            raise ValueError("expert axis > 1 requires a transformer with "
                             "moe_experts > 0 (--moe_experts)")
        if cfg.grad_reduction not in ("global_mean", "per_shard_mean"):
            # 'local' exists in data_parallel.make_train_step ONLY as
            # bench.py's collective-cost ablation — replicas silently
            # diverge; it must never reach a training job (the CLI choices
            # already exclude it; this guards programmatic configs too)
            raise ValueError(
                f"grad_reduction={cfg.grad_reduction!r} is not a training "
                "semantic (choices: global_mean, per_shard_mean)")
        if ((self.pipeline or self.expert or self.sp_tp or self.ep_tp)
                and cfg.grad_reduction != "global_mean"):
            raise ValueError("pipeline/expert/seq-x-tensor steps always use "
                             "global_mean gradient semantics")
        # (expert x tensor's attention/divisibility invariants live in
        # parallel.expert._validate_moe_tp — the single consult point,
        # called by both step builders)
        if cfg.vocab_parallel and not self.sp_tp:
            raise ValueError(
                "--vocab_parallel shards the embedding/head over 'tensor' "
                "on the seq x tensor path (--sp > 1 and --tp > 1); other "
                "layouts keep them replicated")
        if cfg.model.ce_chunk > 0:
            # only data_parallel.make_loss_fn consults the model's
            # fused_loss_sum hook; anywhere it cannot fire the flag would
            # be silently ignored and the full (B, T, vocab) logits
            # materialized anyway — fail loudly instead (the TP paths get
            # the same memory relief from --vocab_parallel's sharded head)
            if not self.pipeline and (self.tensor or self.expert
                                      or fsdp_on):
                # wired: pure DP/ZeRO-1, DP x SP, and every pipeline
                # layout (the pipeline head is replicated, so its last
                # stage fuses the same way).  Not wired: the non-pipeline
                # tensor/expert/fsdp step builders — there the head is
                # (or may be) sharded and --vocab_parallel is the
                # equivalent relief.
                raise ValueError(
                    "--ce_chunk (fused chunked cross-entropy) is wired on "
                    "the data-parallel/ZeRO-1, sequence-parallel, and "
                    "pipeline step paths; with non-pipeline tp/ep/fsdp "
                    "axes use --vocab_parallel (seq x tensor) or drop "
                    "--ce_chunk")
            if (cfg.model.arch != "transformer"
                    or cfg.loss.partition("@")[0] != "cross_entropy"):
                raise ValueError(
                    "--ce_chunk fuses the transformer LM head into "
                    "cross-entropy; it does nothing for "
                    f"arch={cfg.model.arch!r} loss={cfg.loss!r} — drop it")
        if (cfg.optimizer == "adafactor"
                and (self.pipeline or self.sp_tp or self.expert
                     or self.ep_tp
                     or cfg.update_sharding in ("zero1", "sharded"))):
            raise ValueError(
                "adafactor's stats are exact only where every leaf sees its "
                "full matrix: DP/SP shard_map layouts and GSPMD global-view. "
                "Layouts that slice inside matrices (pipe, seq x tensor, "
                "expert x tensor) make the factor means shard-local; the "
                "expert axis slices the stacked-expert leaves, so the "
                "update-RMS clip / parameter-scale RMS(p) (whole-leaf "
                "means) and the (E, f) bias column factor become "
                "EP-degree-dependent; zero1's flat state cannot carry "
                "factored stats at all, and the per-leaf sharded update "
                "scatters inside matrices the same way. Use "
                "adam/adamw/lion/sgd there")
        from ..parallel.sequence import SEQ_SHARDED_IMPLS

        if (cfg.model.arch == "transformer"
                and cfg.model.attention in SEQ_SHARDED_IMPLS
                and not self.seq_parallel):
            raise ValueError(
                f"attention={cfg.model.attention!r} needs the 'seq' mesh "
                "axis > 1 (--sp); use dense or flash on an unsharded "
                "sequence")
        self.zero1 = cfg.update_sharding == "zero1"
        self.sharded = cfg.update_sharding == "sharded"
        if self.zero1 and (self.gspmd or self.pipeline or self.expert
                           or self.sp_tp or self.ep_tp):
            raise NotImplementedError(
                "update_sharding='zero1' is the flat-buffer shard_map DP "
                "and DP x seq layout; the automatic per-leaf form "
                "(update_sharding='sharded') covers the GSPMD path too")
        if self.sharded and (self.pipeline or self.expert or self.sp_tp
                             or self.ep_tp):
            raise NotImplementedError(
                "update_sharding='sharded' is wired into the shard_map DP "
                "/ DP x seq and GSPMD (tensor/fsdp) layouts; the "
                "pipe/expert/seq-x-tensor layouts own their slicing")
        if (self.zero1 or self.sharded) and cfg.grad_reduction != "global_mean":
            raise ValueError(f"update_sharding={cfg.update_sharding!r} "
                             "implies global_mean gradient semantics")
        if cfg.master_weights and not self.sharded:
            raise ValueError(
                "--master_weights keeps the f32 master copy in the SHARDED "
                "optimizer state (1/N per replica); it requires "
                "update_sharding='sharded' — a replicated master would "
                "duplicate param memory instead of saving it")
        mm = cfg.model.matmul_dtype
        if mm not in ("bf16", "int8", "fp8"):
            raise ValueError(f"unknown --matmul_dtype {mm!r} "
                             "(choices: bf16, int8, fp8)")
        if mm != "bf16":
            # quantized-matmul seam (ops.qmm, DESIGN.md §14): wired where
            # the model's own forward runs whole matmuls — the DP /
            # DP x seq shard_map and GSPMD layouts (all update_sharding
            # forms; the global-norm/guard/metrics seam rides unchanged).
            # The explicit-TP layouts (pipe, seq x tensor, expert x
            # tensor) slice matmuls in their own block code and would
            # silently bypass the seam — refuse instead.
            if cfg.model.arch != "transformer":
                raise ValueError(
                    f"--matmul_dtype {mm} is the transformer's quantized "
                    "dense-projection seam; it does nothing for "
                    f"arch={cfg.model.arch!r}")
            if (self.pipeline or self.expert or self.sp_tp or self.ep_tp):
                raise NotImplementedError(
                    f"--matmul_dtype {mm} is wired on the DP, DP x seq "
                    "and GSPMD (tensor/fsdp) layouts; the pipe/expert/"
                    "seq-x-tensor layouts run their own sliced matmuls "
                    "outside the ops.qmm seam")
            if cfg.model.moe_experts > 0:
                raise ValueError(
                    f"--matmul_dtype {mm} covers the dense projections "
                    "(qkv/attn_out/ffn/head); the MoE expert einsums are "
                    "not routed through the seam — drop --moe_experts")
        if mm == "fp8" and cfg.model.ce_chunk > 0:
            raise ValueError(
                "--matmul_dtype fp8 needs the delayed-scaling amax "
                "observations, which do not thread the --ce_chunk fused "
                "scan; use int8/bf16 with --ce_chunk, or drop it")
        if cfg.pp_interleave > 1 and not self.pipeline:
            raise ValueError("--pp_interleave needs the pipeline layout "
                             "(--pp > 1); it schedules virtual stage-slices "
                             "per pipeline device")
        if cfg.hang_timeout and not cfg.log_every:
            raise ValueError(
                "--hang_timeout needs log_every > 0: the periodic loss "
                "device_get is the loop's only blocking point, and without "
                "it async dispatch would keep patting the watchdog while "
                "the device is wedged")
        if self.gspmd and cfg.grad_reduction != "global_mean":
            raise ValueError(
                "grad_reduction='per_shard_mean' (the reference's :188-197 "
                "semantics) is only available on the pure-DP shard_map path; "
                "GSPMD global semantics always compute the exact global mean")
        if cfg.model.scan_layers and (self.pipeline or self.gspmd
                                      or self.expert):
            raise ValueError(
                "scan_layers stacks blocks for a depth-independent compile "
                "on the plain DP / DP x seq / seq x tensor paths; the "
                "pipeline/GSPMD/expert layouts own their own stacking and "
                "sharding")
        self.model = build_model(cfg.model)
        if self.seq_parallel and cfg.model.arch != "transformer":
            raise ValueError("seq axis > 1 requires the transformer model")
        self.data = data if data is not None else build_dataset(cfg.data)
        self.val_data: Optional[Dict[str, np.ndarray]] = None
        if cfg.data.val_fraction > 0:
            from ..data.datasets import train_val_split

            self.data, val = train_val_split(self.data,
                                             cfg.data.val_fraction, cfg.seed)
            self.val_data = val or None
        # the expert axis carries batch rows too (parallel.expert layout);
        # the ep_tp path's step specs always include it (size-1 is free)
        self.batch_axes = (("data", "fsdp", "expert")
                           if (self.expert or self.ep_tp)
                           else ("data", "fsdp"))
        # elastic preflight (DESIGN.md §10): an elastic resume whose
        # checkpoint was saved by a DIFFERENT dp width applies the batch
        # policy BEFORE the loader/schedule/step builders are constructed,
        # so every downstream consumer sees the adjusted config
        self._topology_change = None
        self._restored_world = None
        # maps the CONTINUING global step counter onto this loader's
        # (epoch, in-epoch) position after an elastic batch-size change:
        # position_steps = step + _step_offset (0 except on that path);
        # _resume_plan keeps the (epoch, in-epoch step) the offset maps to
        self._step_offset = 0
        self._resume_plan = None
        # newest step this process has committed a snapshot for (gates
        # the redundant final re-save of an end-of-run boundary step)
        self._last_saved_step = None
        cfg = self.cfg = self._elastic_preflight(cfg)
        # striped attention: tokens reorder round-robin over the seq shards
        # (balanced causal blocks — parallel.sequence.striped_permutation);
        # the loader applies the permutation to inputs AND targets, so the
        # per-token training loss is identical to the contiguous layout
        self.seq_permutation = None
        if (self.seq_parallel and cfg.model.arch == "transformer"
                and cfg.model.attention in ("striped", "striped_flash")):
            from ..parallel.sequence import striped_permutation

            self.seq_permutation = striped_permutation(
                cfg.data.seq_len, int(self.mesh.shape["seq"]))
        self.loader = ShardedLoader(
            self.mesh, self.data, cfg.batch_size, shuffle=cfg.shuffle,
            seed=cfg.seed, full_batch=cfg.full_batch,
            remainder=cfg.data.remainder,
            seq_axis="seq" if self.seq_parallel else None,
            batch_axes=self.batch_axes,
            backend=cfg.data.backend,
            seq_permutation=self.seq_permutation)
        if int(cfg.steps_per_dispatch) > 1 and self.loader.multi_host:
            # fail here, not lazily on the first epoch_groups iteration
            # after step-builder compilation (ADVICE r5)
            raise NotImplementedError(
                "steps_per_dispatch > 1 is single-host for now: the "
                "stacked group would need a make_global_batch variant "
                "assembling per-process rows under the scan axis")
        # schedule domain: optimizer steps = train steps (accumulation is
        # inside the step), known once the loader fixes steps-per-epoch
        lr = schedules.make(
            cfg.lr_schedule, cfg.lr,
            total_steps=cfg.nepochs * max(self.loader.steps_per_epoch, 1),
            warmup_steps=cfg.warmup_steps, min_lr=cfg.min_lr)
        # pipeline/expert/zero1 steps clip inside the step (their grad
        # leaves are axis-sharded; optim.with_clipping's shard-local norm
        # would be wrong there — see make_pipeline_train_step /
        # make_moe_train_step / zero1_shard_update)
        if cfg.label_smoothing and cfg.loss != "cross_entropy":
            raise ValueError("--label_smoothing applies to cross_entropy "
                             f"only, not {cfg.loss!r}")
        if not 0.0 <= cfg.label_smoothing < 1.0:
            raise ValueError(
                f"label_smoothing must be in [0, 1), got "
                f"{cfg.label_smoothing} (s >= 1 puts non-positive weight "
                "on the gold class; s < 0 silently disables smoothing)")
        # smoothing applies to the TRAIN loss only; eval reports the
        # unsmoothed loss (ops.losses.get's "@s" suffix form keeps every
        # step builder a plain loss_name consumer)
        train_loss = (f"{cfg.loss}@{cfg.label_smoothing}"
                      if cfg.label_smoothing else cfg.loss)
        step_clips = (self.pipeline or self.expert or self.zero1
                      or self.sp_tp or self.ep_tp
                      or (self.sharded and not self.gspmd))
        self.optimizer = optim_lib.make(
            cfg.optimizer, lr, cfg.momentum, cfg.weight_decay,
            grad_clip=0.0 if step_clips else cfg.grad_clip)
        # mixed-precision master weights (ops.optim.with_master_weights):
        # wrapped INSIDE the guard so a skipped step is a no-op on the
        # master too; the f32 master lands in the sharded opt state, 1/N
        # per replica (validated sharded-only above)
        if cfg.master_weights:
            self.optimizer = optim_lib.with_master_weights(self.optimizer)
        # guarded update (train.resilience / DESIGN.md §6): reject
        # non-finite or over-threshold steps inside the jitted step.  Wired
        # wherever the skip predicate is identical on every replica: the
        # plain DP, DP x SP, and GSPMD layouts (fully-reduced or
        # global-view gradients) AND the sharded-update layouts
        # (zero1/'sharded'), which psum the shard squares into the global
        # norm inside the step and hand it to the guard via
        # Optimizer.update_with_norm.  The remaining sliced layouts
        # (pipeline stages, expert/tensor slicing) have no such norm seam
        # and stay refused.
        self.guarded = cfg.skip_nonfinite or cfg.skip_threshold > 0
        if self.guarded:
            if self.pipeline or self.expert or self.sp_tp or self.ep_tp:
                raise NotImplementedError(
                    "--skip-nonfinite/--skip_threshold (the guarded "
                    "update) is wired into the plain DP, DP x seq, GSPMD "
                    "and sharded-update (zero1/'sharded') layouts; "
                    "pipe/expert/seq-x-tensor updates run on gradient "
                    "slices where a shard-local norm would desynchronize "
                    "the skip decision")
            self.optimizer = optim_lib.with_skip_guard(
                self.optimizer, cfg.skip_threshold)
        # on-device telemetry metrics (train.telemetry, DESIGN.md §7):
        # wired exactly where the skip guard is wired — fully-reduced
        # (DP / DP x SP shard_map), global-view (GSPMD), or sharded-update
        # (zero1/'sharded', one extra scalar psum for the global grad
        # norm).  The remaining sliced layouts (pipe/expert/seq-x-tensor)
        # fall back to the loss-only telemetry stream.
        self.telemetry_metrics = bool(
            cfg.telemetry_dir and cfg.metrics_every > 0
            and not (self.pipeline or self.expert or self.sp_tp
                     or self.ep_tp))
        # per-leaf update-sharding plan (parallel.update_sharding): shape-
        # only, derived once from the model's abstract init — the shard_map
        # step builders need it for their opt-state specs (the GSPMD path
        # derives its own NamedShardings from the param specs instead)
        self.update_plan = None
        if self.sharded and not self.gspmd:
            from ..parallel import update_sharding as us_lib

            dummy = jax.eval_shape(
                lambda: self.model.init(prng.init_key(cfg.seed)))
            self.update_plan = us_lib.plan_updates(
                dummy, dp.data_axis_size(self.mesh))
        if self.pipeline:
            from ..parallel import pipeline as pp

            # accumulation folds into the GPipe schedule: accum_steps x
            # more microbatches per step (smaller microbatches, same
            # single optimizer update — and a smaller bubble fraction)
            n_stages = int(self.mesh.shape["pipe"])
            self.train_step = pp.make_pipeline_train_step(
                self.model, self.optimizer, self.mesh, loss_name=train_loss,
                n_microbatches=n_stages * cfg.accum_steps,
                grad_clip=cfg.grad_clip, interleave=cfg.pp_interleave)
            # eval runs the ring schedule forward-only on the pipe-sharded
            # params in place — multi-host safe, no host gather
            # natural microbatch count: accumulation is a gradient-only
            # concept — folding accum_steps in here would only add padding
            # waste on small validation batches
            self.eval_step = pp.make_pipeline_eval_step(
                self.model, self.mesh, loss_name=cfg.loss,
                with_accuracy=(cfg.loss == "cross_entropy"),
                interleave=cfg.pp_interleave)
        elif self.ep_tp:
            from ..parallel import expert as ep_lib

            moe_seq = "seq" if self.seq_parallel else None
            # the ledger seam wraps the INNER jitted program (the outer
            # train_step is a plain closure the seam cannot lower)
            moe_step = ledger_lib.instrument(
                ep_lib.make_moe_tp_train_step(
                    self.model, self.optimizer, self.mesh,
                    loss_name=train_loss, grad_clip=cfg.grad_clip,
                    accum_steps=cfg.accum_steps, seq_axis=moe_seq),
                "train_step[ep_tp]")

            def train_step(state, batch):
                state, metrics = moe_step(state, batch)
                return state, metrics["loss"]

            self.train_step = train_step
            self.eval_step = ep_lib.make_moe_tp_eval_step(
                self.model, self.mesh, loss_name=cfg.loss,
                with_accuracy=(cfg.loss == "cross_entropy"),
                seq_axis=moe_seq)
        elif self.expert:
            from ..parallel import expert as ep_lib

            moe_seq = "seq" if self.sp_ep else None
            moe_step = ledger_lib.instrument(
                ep_lib.make_moe_train_step(
                    self.model, self.optimizer, self.mesh,
                    loss_name=train_loss, grad_clip=cfg.grad_clip,
                    accum_steps=cfg.accum_steps, seq_axis=moe_seq),
                "train_step[expert]")

            def train_step(state, batch):
                state, metrics = moe_step(state, batch)
                return state, metrics["loss"]

            self.train_step = train_step
            self.eval_step = ep_lib.make_moe_eval_step(
                self.model, self.mesh, loss_name=cfg.loss,
                with_accuracy=(cfg.loss == "cross_entropy"),
                seq_axis=moe_seq)
        elif self.sp_tp:
            from ..parallel import spmd

            example = next(iter(self.loader.epoch(0)))
            self.train_step = spmd.make_sp_tp_train_step(
                self.model, self.optimizer, self.mesh, loss_name=train_loss,
                seq_axis="seq", attention_impl=cfg.model.attention,
                example_batch=example, accum_steps=cfg.accum_steps,
                grad_clip=cfg.grad_clip,
                vocab_parallel=cfg.vocab_parallel)
            self.eval_step = spmd.make_sp_tp_eval_step(
                self.model, self.mesh, loss_name=cfg.loss,
                with_accuracy=(cfg.loss == "cross_entropy"),
                seq_axis="seq", attention_impl=cfg.model.attention,
                example_batch=example,
                vocab_parallel=cfg.vocab_parallel)
        elif self.seq_parallel:
            from ..parallel import spmd

            example = next(iter(self.loader.epoch(0)))
            self.train_step = spmd.make_spmd_train_step(
                self.model, self.optimizer, self.mesh, loss_name=train_loss,
                seq_axis="seq", example_batch=example,
                accum_steps=cfg.accum_steps,
                update_sharding=cfg.update_sharding,
                grad_clip=cfg.grad_clip if step_clips else 0.0,
                with_metrics=self.telemetry_metrics,
                update_plan=self.update_plan)
            self.eval_step = dp.make_eval_step(
                self.model, self.mesh, loss_name=cfg.loss,
                with_accuracy=(cfg.loss == "cross_entropy"),
                seq_axis="seq")
        elif self.gspmd:
            from ..parallel import gspmd

            example = next(iter(self.loader.epoch(0)))
            self.train_step = gspmd.make_gspmd_train_step(
                self.model, self.optimizer, self.mesh, loss_name=train_loss,
                example_batch=example, accum_steps=cfg.accum_steps,
                with_metrics=self.telemetry_metrics,
                update_sharding=cfg.update_sharding)
            self.eval_step = gspmd.make_gspmd_eval_step(
                self.model, self.mesh, loss_name=cfg.loss,
                with_accuracy=(cfg.loss == "cross_entropy"),
                example_batch=example)
        else:
            self.train_step = dp.make_train_step(
                self.model, self.optimizer, self.mesh, loss_name=train_loss,
                grad_reduction=cfg.grad_reduction,
                accum_steps=cfg.accum_steps,
                update_sharding=cfg.update_sharding,
                grad_clip=cfg.grad_clip if step_clips else 0.0,
                with_metrics=self.telemetry_metrics,
                update_plan=self.update_plan)
            self.eval_step = dp.make_eval_step(
                self.model, self.mesh, loss_name=cfg.loss,
                with_accuracy=(cfg.loss == "cross_entropy"))
        # fault schedule parsed once (utils.faults; fit reuses it so
        # max=/once= counters survive across epochs).  The deterministic
        # desync (desync@N?det) is consumed HERE, at step-build time: it
        # wraps the jitted step so one replica drifts inside the program
        # itself — the software-bug stand-in the SDC replay triage must
        # prove deterministic (DESIGN.md §9)
        from ..utils.faults import FaultPlan

        self.fault_plan = FaultPlan.from_config(cfg.faults)
        det = self.fault_plan.det_desync() if self.fault_plan else None
        if det is not None:
            if (self.pipeline or self.expert or self.sp_tp or self.ep_tp
                    or self.gspmd or self.zero1 or self.sharded):
                raise NotImplementedError(
                    "desync?det perturbs the fully-replicated train state "
                    "inside the step; it is wired on the plain DP and "
                    "DP x seq layouts (replicated update)")
            from ..utils.faults import wrap_step_with_desync

            self.train_step = wrap_step_with_desync(
                self.train_step, self.mesh, det.start, det.eps)
        # compile-event ledger seam (utils/compile_ledger, DESIGN.md §7):
        # every layout's train/eval program goes through ONE
        # instrumentation point — while a ledger is installed
        # (--trace/--trace_dir) each new arg-shape/dtype signature is
        # compiled exactly once with wall time, HLO fingerprint, cost
        # analysis and recompile attribution recorded; with no ledger
        # the wrappers are pass-throughs.  The expert/ep_tp branches
        # instrumented their inner jitted program above.
        self.layout_tag = ("pipe" if self.pipeline else
                           "ep_tp" if self.ep_tp else
                           "expert" if self.expert else
                           "sp_tp" if self.sp_tp else
                           "sp" if self.seq_parallel else
                           "gspmd" if self.gspmd else "dp")
        if cfg.update_sharding != "replicated":
            self.layout_tag += f"+{cfg.update_sharding}"
        if cfg.model.matmul_dtype != "bf16":
            # the ledger names each (layout, matmul_dtype) pair's program:
            # a format change is a NEW named compile event; flipping the
            # calibration state (amax values, shapes fixed) is not
            self.layout_tag += f"+matmul_dtype={cfg.model.matmul_dtype}"
        if not (self.expert or self.ep_tp):
            self.train_step = ledger_lib.instrument(
                self.train_step, f"train_step[{self.layout_tag}]")
        self.eval_step = ledger_lib.instrument(
            self.eval_step, f"eval_step[{self.layout_tag}]")
        # silent-data-corruption defense (utils.consistency, DESIGN.md
        # §9): --sdc_check_every fingerprints the replicated state at
        # this cadence and heals transient divergence; the legacy
        # --check_replicas_every rides the same fingerprint path (same
        # lag-2 fetch discipline — the old host-side full-state fetch
        # stalled the async pipeline exactly the way DESIGN §7 warns
        # against) but stays detect-only: no healing, a divergence
        # localizes, triages and raises.
        self.sdc_every = (int(cfg.sdc_check_every)
                          or int(cfg.check_replicas_every))
        self.sdc_heal = bool(cfg.sdc_heal) and int(cfg.sdc_check_every) > 0
        self._fp = None           # consistency.Fingerprinter, built in fit
        self._sdc_policy = None   # resilience.SDCPolicy
        self._sdc_batch = None    # last dispatched batch, for replay triage
        # multi-step dispatch (--steps_per_dispatch k, VERDICT r4 item 6):
        # one jitted lax.scan runs k optimizer steps over a device-staged
        # batch stack, amortizing the per-step host dispatch that dominates
        # small models (the reference pays a gather-average-send round trip
        # EVERY step, :149-211; MNIST MLP measured dispatch-bound at 0.011
        # MFU).  The scan replays the identical batches in the identical
        # order: bitwise-identical to k=1 on the plain-DP shard_map path,
        # same-math-within-compile-noise on the scanned GSPMD/SP bodies
        # (tests/test_dispatch.py bounds the drift).
        self.k_dispatch = max(1, int(cfg.steps_per_dispatch))
        if self.k_dispatch > 1:
            from jax import lax

            inner = self.train_step

            def multi(state, stacked):
                return lax.scan(lambda s, b: inner(s, b), state, stacked)

            # donate the carried state: the caller always discards the old
            # one, and k>1 exists to cut overhead, not add copies
            self.multi_step = ledger_lib.instrument(
                jax.jit(multi, donate_argnums=0),
                f"multi_step[{self.layout_tag},k={self.k_dispatch}]")
        # distributed tracing (train/trace.py): install the span tracer
        # + compile ledger for this process.  Validates the flag combo
        # (--trace needs --telemetry_dir or --trace_dir) eagerly.
        self.tracer = None
        trace_dir = trace_lib.dir_from_config(cfg)
        if trace_dir:
            self.tracer = trace_lib.start_run(trace_dir)
        self.metrics = MetricsLogger(cfg.metrics_jsonl)
        dev = self.mesh.devices.flat[0]
        self.telemetry = telemetry_lib.Telemetry(
            cfg, self.model, tuple(self.data["x"].shape[1:]),
            n_devices=int(self.mesh.devices.size),
            device_kind=dev.device_kind, platform=dev.platform)
        self.state: Optional[TrainState] = None

    # ---- state lifecycle -------------------------------------------------
    def init_state(self) -> TrainState:
        """Deterministic init — every host derives identical params from the
        job seed (replaces the reference's rank-0 state-dict bcast, :87-88);
        placement is replicated for DP/SP or TP/FSDP-sharded for GSPMD."""
        if self.pipeline:
            from ..parallel import pipeline as pp

            state = pp.init_pipeline_state(
                self.model, self.optimizer, prng.init_key(self.cfg.seed),
                int(self.mesh.shape["pipe"]),
                tp=int(self.mesh.shape.get("tensor", 1)),
                interleave=self.cfg.pp_interleave)
            self.state = pp.shard_pipeline_state(
                state, self.mesh, self.optimizer,
                interleave=self.cfg.pp_interleave)
            return self.state
        from ..ops import qmm

        if self.zero1:
            import jax.numpy as jnp

            params = self.model.init(prng.init_key(self.cfg.seed))
            host = TrainState(
                step=jnp.zeros((), jnp.int32), params=params,
                opt_state=dp.zero1_opt_state(self.optimizer, params,
                                             self.mesh, place=False),
                qstate=qmm.init_qstate(self.model))
            self.state = dp.place_zero1_state(host, self.mesh,
                                              self.optimizer)
            return self.state
        if self.sharded and not self.gspmd:
            import jax.numpy as jnp

            from ..parallel import update_sharding as us_lib

            params = self.model.init(prng.init_key(self.cfg.seed))
            host = TrainState(
                step=jnp.zeros((), jnp.int32), params=params,
                opt_state=us_lib.init_opt_state(self.optimizer, params,
                                                self.update_plan),
                qstate=qmm.init_qstate(self.model))
            self.state = us_lib.place_state(host, self.mesh,
                                            self.optimizer,
                                            self.update_plan)
            return self.state
        if self.sp_tp:
            from ..parallel import spmd

            state = spmd.init_sp_tp_state(
                self.model, self.optimizer, prng.init_key(self.cfg.seed),
                int(self.mesh.shape["tensor"]))
            self.state = spmd.shard_sp_tp_state(
                state, self.mesh, self.optimizer,
                vocab_parallel=self.cfg.vocab_parallel)
            return self.state
        if self.ep_tp:
            from ..parallel import expert as ep_lib

            state = ep_lib.init_moe_tp_state(
                self.model, self.optimizer, prng.init_key(self.cfg.seed),
                int(self.mesh.shape["tensor"]))
            self.state = ep_lib.shard_moe_tp_state(state, self.mesh,
                                                   self.optimizer)
            return self.state
        state = TrainState.create(self.model, self.optimizer,
                                  prng.init_key(self.cfg.seed))
        if self.expert:
            from ..parallel import expert as ep_lib

            self.state = ep_lib.shard_moe_state(state, self.mesh,
                                                self.optimizer)
        elif self.gspmd:
            from ..parallel import gspmd

            self.state = gspmd.shard_state(
                self.model, state, self.optimizer, self.mesh,
                update_sharding=self.cfg.update_sharding)
        else:
            self.state = dp.replicate_state(state, self.mesh)
        return self.state

    def _elastic_preflight(self, cfg: TrainConfig) -> TrainConfig:
        """Detect a cross-world elastic resume BEFORE the loader and step
        builders exist, and apply the ``--elastic_batch`` policy
        (DESIGN.md §10).  Keyed to the newest VERIFIED generation — the
        one restore() will actually land on — not merely the newest
        committed one: a corrupt newest generation saved by a
        different-sized world (say a degraded dp=2 save above healthy
        dp=8 history) would otherwise derive the policy from metadata of
        a snapshot that restore quarantines and falls back past.  The
        extra checksum pass happens once per process start, on the same
        chain restore re-verifies moments later."""
        if not (cfg.elastic and cfg.resume and cfg.checkpoint_dir):
            return cfg
        import dataclasses
        import math

        from ..utils import checkpoint as ckpt

        step = ckpt.newest_verified_step(cfg.checkpoint_dir)
        meta = (ckpt.read_meta(cfg.checkpoint_dir, step=step)
                if step is not None else None) or {}
        saved = meta.get("saved_world") or {}
        saved_dp = int(saved.get("dp") or 0)
        new_dp = int(np.prod([self.mesh.shape[a]
                              for a in self.batch_axes]))
        if not saved_dp or saved_dp == new_dp:
            return cfg
        change = {
            "from_world": saved,
            "to_world": {"n_devices": jax.device_count(),
                         "n_processes": jax.process_count(),
                         "dp": new_dp},
            "policy": cfg.elastic_batch,
            "batch_size": [cfg.batch_size, cfg.batch_size],
            "accum_steps": [cfg.accum_steps, cfg.accum_steps],
        }
        if cfg.elastic_batch == "per_device" and not cfg.full_batch:
            # keep per-device rows: shrink/grow the global batch with the
            # world; round to a multiple of the new dp so padding stays
            # padding, never a silent second batch-size change
            new_bs = max(new_dp,
                         (round(cfg.batch_size * new_dp / saved_dp)
                          // new_dp) * new_dp or new_dp)
            change["batch_size"][1] = new_bs
            cfg = dataclasses.replace(cfg, batch_size=new_bs)
        elif cfg.elastic_batch == "global" and saved_dp > new_dp:
            # keep the global batch: per-device rows grow by
            # saved_dp/new_dp — raise grad accumulation by the same
            # factor to bound per-device microbatch memory, but only
            # when the per-shard rows stay divisible (accumulation
            # reshapes the local shard into microbatches)
            factor = math.ceil(saved_dp / new_dp)
            new_accum = cfg.accum_steps * factor
            bs = (self.data["x"].shape[0] if cfg.full_batch
                  else cfg.batch_size)
            per_shard = math.ceil(bs / new_dp)
            if per_shard % new_accum == 0:
                change["accum_steps"][1] = new_accum
                cfg = dataclasses.replace(cfg, accum_steps=new_accum)
        self._topology_change = change
        log(f"[elastic] resuming a dp={saved_dp} checkpoint on dp="
            f"{new_dp} ({saved.get('n_devices', '?')} -> "
            f"{jax.device_count()} devices), policy="
            f"{cfg.elastic_batch}: batch {change['batch_size'][0]} -> "
            f"{change['batch_size'][1]}, accum "
            f"{change['accum_steps'][0]} -> {change['accum_steps'][1]}")
        return cfg

    def maybe_resume(self) -> int:
        """Restores state and returns the exact global step to resume from
        (checkpoint extension).  Mid-epoch checkpoints resume at the right
        batch within the epoch — no step is replayed.  Elastic resumes
        onto a different world ride the reshard path (utils.checkpoint)
        and, when the batch size changed with the world, re-derive the
        (epoch, in-epoch step) start from the world-size-independent
        ``consumed_samples`` meta so the sample stream stays a permutation
        of the original epoch."""
        if not (self.cfg.resume and self.cfg.checkpoint_dir):
            return 0
        from ..utils import checkpoint as ckpt

        restored = ckpt.restore(self.cfg.checkpoint_dir, self.state,
                                elastic=self.cfg.elastic)
        if restored is None:
            return 0
        restored = self._reconcile_qkv_tp(ckpt, restored)
        self._place_restored(restored)
        # restore the anomaly-rollback order salt: a relaunch after a
        # rollback must keep the re-drawn data order, not replay the
        # poison window and re-spend the rollback budget on it.  Read the
        # meta of the generation restore ACTUALLY loaded (its step) — the
        # newest committed dir can be a different, corrupt generation when
        # quarantine failed (read-only fs) or this is a non-leader process
        meta = ckpt.read_meta(self.cfg.checkpoint_dir,
                              step=int(jax.device_get(self.state.step))) or {}
        self.loader.order_salt = int(meta.get("order_salt", 0))
        if self.cfg.elastic:
            # topology lineage: a shrunken world's own saves must carry
            # the ORIGINAL topology forward, not shadow it — propagate
            # the oldest restored_world on record, else the saving world
            self._restored_world = (meta.get("restored_world")
                                    or meta.get("saved_world"))
        start_step = int(jax.device_get(self.state.step))
        self._remap_step_offset(meta, start_step)
        return start_step

    def _remap_step_offset(self, meta: dict, start_step: int) -> None:
        """After a batch-size-changing elastic resume, map the restored
        generation's step counter onto THIS loader's (epoch, in-epoch)
        position via the world-size-independent ``consumed_samples``
        meta.  Keyed to the generation actually restored — an anomaly
        rollback that falls back to an older (possibly old-world)
        snapshot must recompute the offset for THAT step, not keep the
        one derived for the generation the run originally resumed."""
        self._step_offset = 0
        self._resume_plan = None
        if (self._topology_change is None
                or self._topology_change["batch_size"][0]
                == self._topology_change["batch_size"][1]
                or meta.get("consumed_samples") is None):
            return
        plan = self.loader.start_for_samples(
            int(meta["consumed_samples"]))
        spe = max(self.loader.steps_per_epoch, 1)
        self._resume_plan = plan
        self._step_offset = plan[0] * spe + plan[1] - start_step
        log(f"[elastic] batch size changed with the world: resuming "
            f"at epoch {plan[0]}, in-epoch step {plan[1]} from "
            f"consumed_samples={meta['consumed_samples']}")

    def _place_restored(self, restored: TrainState) -> None:
        """Place a host-side restored state per this trainer's layout
        (shared by resume and anomaly rollback)."""
        if self.pipeline:
            from ..parallel import pipeline as pp

            self.state = pp.shard_pipeline_state(
                restored, self.mesh, self.optimizer,
                interleave=self.cfg.pp_interleave)
        elif self.sp_tp:
            from ..parallel import spmd

            self.state = spmd.shard_sp_tp_state(
                restored, self.mesh, self.optimizer,
                vocab_parallel=self.cfg.vocab_parallel)
        elif self.ep_tp:
            from ..parallel import expert as ep_lib

            self.state = ep_lib.shard_moe_tp_state(restored, self.mesh,
                                                   self.optimizer)
        elif self.expert:
            from ..parallel import expert as ep_lib

            self.state = ep_lib.shard_moe_state(restored, self.mesh,
                                                self.optimizer)
        elif self.gspmd:
            from ..parallel import gspmd

            self.state = gspmd.shard_state(
                self.model, restored, self.optimizer, self.mesh,
                update_sharding=self.cfg.update_sharding)
        elif self.zero1:
            self.state = dp.place_zero1_state(restored, self.mesh,
                                              self.optimizer)
        elif self.sharded:
            from ..parallel import update_sharding as us_lib

            self.state = us_lib.place_state(restored, self.mesh,
                                            self.optimizer,
                                            self.update_plan)
        else:
            self.state = dp.replicate_state(restored, self.mesh)

    def _rollback(self) -> int:
        """Anomaly rollback (train.resilience): restore the newest
        checkpoint (or the deterministic init when none exists yet) and
        re-draw the subsequent data order so the poison window is not
        replayed verbatim.  Returns the global step to resume from."""
        from ..utils import checkpoint as ckpt

        restored = None
        if self.cfg.checkpoint_dir:
            ckpt.wait_pending()  # an in-flight async write may be newest
            # elastic rides along: right after a degraded relaunch the
            # newest verified snapshot can still be the OLD world's
            restored = ckpt.restore(self.cfg.checkpoint_dir, self.state,
                                    elastic=self.cfg.elastic)
        if restored is None:
            self.init_state()  # no snapshot yet: back to step 0
            self._step_offset = 0
            self._resume_plan = None
        else:
            restored = self._reconcile_qkv_tp(ckpt, restored)
            self._place_restored(restored)
            step = int(jax.device_get(self.state.step))
            # the fallback chain may land on an OLDER generation than
            # the one the elastic resume was keyed to: re-derive the
            # step->position offset from that generation's meta
            self._remap_step_offset(
                ckpt.read_meta(self.cfg.checkpoint_dir, step=step) or {},
                step)
        self.loader.order_salt += 1
        # the retrained window will revisit step numbers already saved —
        # with DIFFERENT state (re-drawn order); the final-save skip
        # must never treat those as already-committed
        self._last_saved_step = None
        return int(jax.device_get(self.state.step))

    # ---- silent-data-corruption defense (DESIGN.md §9) -------------------
    def _sdc_observe(self, at_step: int, fp, watchdog,
                     draining: bool = False) -> str:
        """Consume one lag-2 fingerprint: fetch the tiny per-device digest
        vector, form the GLOBAL verdict (in a multi-host world the digests
        are allgathered, so every process computes the identical verdict
        and takes the same branch — the incident path contains
        collectives), and on mismatch run the incident pipeline.  Returns
        ``"ok"``, ``"healed"`` or ``"rollback"``."""
        from ..parallel import distributed
        from ..utils import consistency

        digests, folds = consistency.Fingerprinter.fetch(fp)
        if distributed.is_multi_host():
            mat = np.asarray(distributed.allgather_host_array(digests))
        else:
            mat = digests[None, :]
        verdict = consistency.digest_report(mat)
        if not verdict:
            return "ok"
        return self._sdc_incident(at_step, verdict, folds, watchdog,
                                  draining)

    def _sdc_incident(self, at_step: int, fp_verdict: dict, folds,
                      watchdog, draining: bool) -> str:
        """Fingerprint mismatch: localize → record → replay-triage → heal
        or abort.  ``fp_verdict`` is identical on every process (computed
        from gathered digests), so every branch that reaches a collective
        is taken by all processes together; only the purely-local heal
        (device_put of the majority shard) differs per host."""
        from ..parallel import distributed
        from ..utils import consistency
        from .resilience import SDCAbort

        cfg = self.cfg
        log(f"[sdc] fingerprint mismatch detected for step {at_step} "
            f"(checked at lag 2): localizing...")
        with watchdog.suspended():
            # ---- localize: which leaf, which shard, which device -------
            report = consistency.divergence_report(self.state)
            cross = {}
            if fp_verdict.get("cross"):
                # cross-host sweep: each host's shard-0 content digest
                # per leaf, gathered and compared (collective; symmetric
                # because fp_verdict is)
                cross = distributed.cross_host_report(
                    consistency.leaf_digests(self.state))
            devices = sorted({d for r in report.values()
                              for d in r["devices"]})
            # ---- replay triage: deterministic bug vs transient fault ---
            # re-execute the last dispatch from a consistency-restored
            # state (majority-shard heal of the pre-replay snapshot) and
            # fingerprint the result: a software bug (lying out_spec,
            # miscompiled collective, desync?det) re-diverges every time;
            # a cosmic ray does not.  The replay input is a COPY — the
            # step donates its argument, and the healed state must
            # survive to continue training.
            healed, _ = consistency.heal_replication(self.state, report)
            replay_verdict = "unknown"
            if self._sdc_batch is not None and self._fp is not None:
                import jax.numpy as jnp

                replay_in = jax.tree_util.tree_map(jnp.copy, healed)
                step_fn = (self.multi_step if self.k_dispatch > 1
                           else self.train_step)
                replay_out, _ = step_fn(replay_in, self._sdc_batch)
                r_digests, _rf = consistency.Fingerprinter.fetch(
                    self._fp.compute(replay_out))
                if distributed.is_multi_host():
                    r_mat = np.asarray(
                        distributed.allgather_host_array(r_digests))
                else:
                    r_mat = r_digests[None, :]
                replay_verdict = ("deterministic"
                                  if consistency.digest_report(r_mat)
                                  else "transient")
            # ---- decide + record --------------------------------------
            cross_procs = list(fp_verdict.get("cross", []))
            strike_keys = devices + [f"process:{p}" for p in cross_procs]
            record = {
                "step": int(at_step),
                "leaves": {k: {"shards": r["shards"],
                               "devices": r["devices"],
                               "max_abs_diff": float(r["max_abs_diff"]),
                               "n_bad_elements": int(r["n_bad_elements"])}
                           for k, r in report.items()},
                "devices": devices,
                "cross_host": {k: v["processes"] for k, v in cross.items()}
                              if cross else {},
                "float_folds": [float(f) for f in folds],
                "verdict": replay_verdict,
            }
            if replay_verdict == "deterministic":
                record["action"] = "abort_deterministic"
                self.telemetry.on_sdc(record)
                names = (sorted(report) or sorted(cross)
                         or ["<unlocalized>"])
                raise SDCAbort(
                    f"replica divergence at step {at_step} REPRODUCED on "
                    f"replay from a consistency-restored state — "
                    f"deterministic software bug in the step function "
                    f"(diverged leaves: {names[:5]}); a relaunch would "
                    "replay it.  Suspects: a shard_map out_spec claiming "
                    "replication the math does not guarantee (check_vma "
                    "off), a nondeterministic kernel, or an injected "
                    "desync?det")
            exhausted = self._sdc_policy.record(strike_keys)
            if exhausted:
                record["action"] = "abort_strikes"
                record["strikes"] = dict(self._sdc_policy.counts)
                self.telemetry.on_sdc(record)
                raise SDCAbort(
                    f"transient replica divergence at step {at_step}, but "
                    f"{exhausted} exceeded the strike budget "
                    f"(--sdc_strikes {cfg.sdc_strikes}; counts "
                    f"{self._sdc_policy.counts}) — repeatedly flaky "
                    "hardware; drain the device instead of relaunching")
            if not self.sdc_heal:
                record["action"] = "detect_only"
                self.telemetry.on_sdc(record)
                worst = sorted(((k, r["max_abs_diff"])
                                for k, r in report.items()),
                               key=lambda kv: -kv[1])[:5]
                raise AssertionError(
                    f"replica divergence in train state @ step {at_step}: "
                    f"{len(report)} replicated leaves differ across device "
                    f"shards (worst: {worst}; cross-host: "
                    f"{record['cross_host']}); replay says "
                    f"{replay_verdict}.  Healing is off on this path — "
                    "use --sdc_check_every/--sdc_heal to heal instead of "
                    "dying")
            if cross_procs or (cross and not report):
                # hosts disagree while each host is internally consistent:
                # a local majority vote cannot pick the truth — roll back
                # to the newest VERIFIED checkpoint (identical bytes on
                # every host, DESIGN.md §8 machinery)
                record["action"] = "rollback"
                self.telemetry.on_sdc(record)
                if draining:
                    # transient + recoverable, so NOT SDCAbort/45 (the
                    # supervisor would refuse to relaunch a perfectly
                    # retryable job): die as a plain crash — the relaunch
                    # resumes from the newest verified checkpoint, which
                    # is exactly the mid-run rollback action anyway
                    raise RuntimeError(
                        f"[sdc] cross-host divergence detected at step "
                        f"{at_step} during the final drain — refusing to "
                        "write a final snapshot from unreconcilable "
                        "state; relaunch/resume from the newest verified "
                        "checkpoint")
                return "rollback"
            # transient, local, under budget: HEAL — restore replication
            # from the majority shard and keep training
            record["action"] = "healed"
            record["strikes"] = dict(self._sdc_policy.counts)
            self.telemetry.on_sdc(record)
            if report:
                self.state = healed
                self._sdc_policy.healed += 1
                log(f"[sdc] transient divergence healed at step {at_step}: "
                    f"{len(report)} leaf/leaves restored from the majority "
                    f"shard (implicated: {devices}; strikes "
                    f"{self._sdc_policy.counts})")
            else:
                # a PEER host healed its local divergence this round; this
                # host had nothing to repair
                log(f"[sdc] divergence at step {at_step} localized to a "
                    "peer host's shards; no local repair needed")
            return "healed"

    def _reconcile_qkv_tp(self, ckpt, restored: TrainState) -> TrainState:
        """The TP qkv column permutation is shape-preserving, so a
        checkpoint written under a different tensor-axis size is
        undetectable from the pytree alone — meta.json records it
        (checkpoint.save extra_meta) and we re-permute here, for params
        AND every optimizer slot (momentum/mu/nu mirror the param layout
        and carry the same permutation).  Runs on EVERY resume path: only
        the explicit shard_map TP layouts (pipeline, seq x tensor) use the
        permutation — plain DP/SP/GSPMD trainers expect the dense column
        order, so a checkpoint from a permuted layout must be unpermuted
        even when this trainer has no tensor axis at all.  Missing metadata
        means a dense-layout save (every save records qkv_tp since round 2;
        only the explicit-TP layouts ever set it > 1), so the default is 1
        — NOT the current tp, which would silently treat a dense checkpoint
        as already permuted when resuming INTO a TP layout."""
        tp = (int(self.mesh.shape.get("tensor", 1))
              if (self.pipeline or self.sp_tp or self.ep_tp) else 1)
        # meta of the generation actually restored, not the newest on disk
        # (they differ when the fallback chain skipped an unquarantinable
        # corrupt generation) — a mismatched qkv_tp would silently
        # mis-permute the qkv columns of an older generation's weights
        meta = ckpt.read_meta(self.cfg.checkpoint_dir,
                              step=int(np.asarray(restored.step))) or {}
        saved_tp = int(meta.get("qkv_tp", 1))
        if saved_tp == tp:
            return restored
        if not (isinstance(restored.params, dict)
                and "blocks" in restored.params):
            return restored  # non-transformer state carries no permutation
        from ..parallel import megatron

        c = self.model.cfg

        def fix(tree):
            if not (isinstance(tree, dict) and "blocks" in tree):
                return tree  # e.g. the optimizer's step counter
            tree = dict(tree)
            b = tree["blocks"]
            if saved_tp > 1:
                b = megatron.permute_qkv(b, c.d_model, c.n_heads,
                                         saved_tp, inverse=True,
                                         kv_heads=c.kv_heads)
            if tp > 1:
                b = megatron.permute_qkv(b, c.d_model, c.n_heads, tp,
                                         kv_heads=c.kv_heads)
            tree["blocks"] = b
            return tree

        def fix_state(st):
            # recurse through NamedTuple slots (SGDState/AdamState, and
            # the guard wrapper's GuardedState around them) down to the
            # param-mirroring dicts fix() permutes
            if isinstance(st, tuple) and type(st) is not tuple:
                return type(st)(*(fix_state(f) for f in st))
            return fix(st)

        # qstate passes through untouched: the fp8 calibration histories
        # carry no qkv column layout, and dropping them here would
        # silently reset delayed scaling on any resume that re-permutes
        return TrainState(step=restored.step, params=fix(restored.params),
                          opt_state=fix_state(restored.opt_state),
                          qstate=restored.qstate)

    def save(self, final: bool = False) -> None:
        # every process calls in: checkpoint.save is leader-only for
        # addressable state and shard-parallel (orbax) for TP/FSDP state
        # that spans hosts (device_get would raise there)
        if self.cfg.checkpoint_dir:
            from ..utils import checkpoint as ckpt

            # checkpoint writes emit no dispatches; keep the external
            # stale-heartbeat monitor from reading a long write as a hang
            self.telemetry.alive()
            # record the (shape-preserving, hence otherwise undetectable)
            # TP qkv permutation so maybe_resume can reconcile a different
            # tensor-axis size; dense layouts record 1 explicitly.  The
            # rollback salt rides along so a supervised relaunch resumes
            # with the re-drawn data order instead of replaying a poison
            # window the in-process rollback already routed around.
            # saved_world enriches checkpoint.current_world with the
            # layout facts only the trainer knows (dp width, mesh shape,
            # update sharding — what the cross-world reshard path keys
            # off); restored_world carries the ORIGINAL topology lineage
            # so a shrunken world's saves never shadow where the run
            # started; consumed_samples is the world-size-independent
            # progress coordinate an elastic resume with a different
            # batch size maps through (DESIGN.md §10).
            step_now = int(jax.device_get(self.state.step))
            # when the run ENDS exactly on a checkpoint boundary, the
            # loop's periodic save already committed this step and the
            # state has not changed since — the final save would rewrite
            # the same generation, which the orbax (multi-process) layout
            # refuses ("Destination already exists") and the npz layout
            # pays as a redundant full write.  Drain the async writer and
            # return: the committed generation IS the final snapshot.
            if final and self._last_saved_step == step_now:
                ckpt.wait_pending()
                return
            self._last_saved_step = step_now
            extra = {"qkv_tp": (int(self.mesh.shape.get("tensor", 1))
                                if (self.pipeline or self.sp_tp
                                    or self.ep_tp) else 1),
                     "order_salt": int(getattr(self.loader,
                                               "order_salt", 0)),
                     "saved_world": {
                         "dp": int(self.loader.dp),
                         "mesh": {k: int(v)
                                  for k, v in self.mesh.shape.items()},
                         "update_sharding": self.cfg.update_sharding},
                     "consumed_samples":
                         self.loader.consumed_samples(
                             step_now + self._step_offset)}
            if self._restored_world:
                extra["restored_world"] = self._restored_world
            # span "ckpt" = this call's host-side cost (the async path's
            # staging device_get); the writer thread's disk time shows
            # separately as "ckpt_write" (utils/checkpoint)
            with trace_lib.span("ckpt", step=step_now, final=final):
                if self.cfg.async_checkpoint and not final:
                    ckpt.save_async(self.cfg.checkpoint_dir, self.state,
                                    keep=self.cfg.checkpoint_keep,
                                    extra_meta=extra)
                else:
                    if final:  # drain in-flight writes before the last
                        ckpt.wait_pending()
                    ckpt.save(self.cfg.checkpoint_dir, self.state,
                              keep=self.cfg.checkpoint_keep,
                              extra_meta=extra)

    # ---- the loop --------------------------------------------------------
    def fit(self) -> Dict[str, Any]:
        cfg = self.cfg
        if self.state is None:
            self.init_state()
        spe = max(self.loader.steps_per_epoch, 1)
        start_step = self.maybe_resume()
        # _step_offset is 0 except after an elastic resume whose batch
        # size changed with the world — there the continuing step counter
        # maps onto a different (epoch, in-epoch) position
        start_epoch = (start_step + self._step_offset) // spe
        if self._topology_change is not None:
            self.telemetry.on_topology(
                int(start_step), dict(self._topology_change))
        update_note = ""
        if cfg.update_sharding != "replicated":
            update_note = (f" | update: {cfg.update_sharding}"
                           + (" + master weights" if cfg.master_weights
                              else "")
                           + (f" ({cfg.model.dtype} params)"
                              if cfg.model.dtype != "float32" else ""))
        log(f"mesh: {describe(self.mesh)} | model: {cfg.model.arch} "
            f"({self.model.n_params():,} params) | "
            f"{self.loader.n} samples, "
            f"{self.loader.steps_per_epoch} steps/epoch{update_note}")
        # --xla_trace_dir: the leader-gated jax.profiler DEVICE capture
        # (utils.profiling.trace) next to the host spans — same knob as
        # the legacy --profile_dir
        profiler = profiling.trace(cfg.profile_dir or cfg.xla_trace_dir)
        thr = Throughput()
        timer = profiling.StepTimer()
        last_loss = float("nan")
        # host-side step counter: keeps the hot loop free of device->host
        # syncs so XLA's async dispatch pipelines steps (the whole point of
        # replacing the reference's blocking gather, :185).  Loss logging
        # lags one step for the same reason: by the time step k+1 has been
        # dispatched, step k's loss future has materialized, so device_get
        # on it does not stall the pipeline.
        step = start_step
        prev: Optional[tuple] = None  # (step, epoch, loss_future)
        last_eval: Optional[tuple] = None  # (step, metrics dict)
        # hang watchdog (SURVEY.md §5.3): with log_every on, the loop blocks
        # in device_get on the previous step's loss, so a stalled device
        # stalls the pats and the watchdog fires instead of hanging forever
        from ..utils.watchdog import HangWatchdog
        from .resilience import (AnomalyAbort, GracefulShutdown,
                                 ResilienceMonitor, SDCPolicy)

        # the watchdog's last act before exit 42 is a flight-recorder
        # dump: the postmortem then says what the run was doing when the
        # device wedged (telemetry.emergency_dump is a no-op when off)
        watchdog = HangWatchdog(
            cfg.hang_timeout or None,
            on_timeout=lambda: telemetry_lib.emergency_dump("hang"))
        # anomaly policy (DESIGN.md §6): consumes the per-step loss
        # futures at a fixed lag of two dispatches, so its device_get only
        # ever waits on a step whose successor is already submitted — one
        # dispatch stays in flight and the async pipeline keeps host prep
        # overlapped with device compute (the pure lag-1 logging path
        # semantics are unchanged when the monitor is off)
        monitor = (ResilienceMonitor(cfg.rollback_after, cfg.max_rollbacks,
                                     cfg.loss_spike_factor)
                   if cfg.rollback_after > 0 else None)
        monitor_q: list = []  # (step, loss future), observed at lag 2
        fault_plan = self.fault_plan
        # SDC fingerprint monitor (DESIGN.md §9): one jitted O(1) digest
        # per check, queued and fetched at the same lag-2 discipline as
        # the loss monitor — routine checking never drains the pipeline
        sdc_q: list = []  # (step, fingerprint futures), observed at lag 2
        if self.sdc_every:
            from ..parallel import distributed
            from ..utils import consistency

            fpr = consistency.Fingerprinter(self.state, self.mesh)
            if fpr.n_leaves and (fpr.n_local_shards > 1
                                 or distributed.is_multi_host()):
                self._fp = fpr
                self._sdc_policy = SDCPolicy(cfg.sdc_strikes)
            else:
                self._fp = None
                log("[sdc] replica checking disabled: no replicated "
                    "leaves with >= 2 device shards in this layout/mesh")
        # preemption-safe exit: SIGTERM/SIGINT set a flag checked at each
        # dispatch boundary -> final checkpoint -> exit 0 (<= 1 lost step)
        shutdown = GracefulShutdown()
        dispatches = None

        def do_rollback(why: str) -> None:
            """Shared rollback bookkeeping (anomaly monitor + SDC
            cross-host heal): restore the newest verified snapshot,
            re-draw the data order, dump/rearm the postmortem, and reset
            BOTH lag queues — their futures belong to the abandoned
            timeline.  The caller breaks out of the dispatch loop."""
            nonlocal step, prev, rolled_back
            with trace_lib.span("rollback"), watchdog.suspended():
                step = self._rollback()
            log(f"{why} — restored step {step}, re-drew the data order")
            # postmortem now + a straddling re-dump after the first
            # post-rollback record
            self.telemetry.on_rollback(step,
                                       monitor.rollbacks if monitor else 0)
            prev = None
            monitor_q.clear()
            sdc_q.clear()
            rolled_back = True

        def sdc_pump(keep: int, draining: bool = False) -> str:
            """Observe queued SDC fingerprints down to ``keep`` entries.
            ``keep=1`` is the routine lag-2 discipline (one dispatch
            stays in flight); ``keep=0`` drains — used right before a
            snapshot and at the end of the run, so state the fingerprint
            has not yet cleared can never be captured to disk unobserved.
            Returns "ok", "healed" (queue cleared: pre-heal fingerprints
            are stale) or "rollback" (the caller rolls back)."""
            while len(sdc_q) > keep:
                act = self._sdc_observe(*sdc_q.pop(0), watchdog=watchdog,
                                        draining=draining)
                if act == "healed":
                    sdc_q.clear()
                    return "healed"
                if act == "rollback":
                    return "rollback"
            return "ok"

        try:
            with profiler, watchdog, shutdown:
                epoch = start_epoch
                # in-epoch offset, consumed by the first epoch iteration only
                # (and re-seeded by a rollback); mirrors the old
                # `epoch == start_epoch` special case
                mid_epoch_start = (start_step + self._step_offset) % spe
                while epoch < cfg.nepochs and not shutdown.requested:
                    log(f"Starting epoch {epoch + 1}")  # reference banner, :152
                    epoch_t0 = time.perf_counter()
                    epoch_start_step = mid_epoch_start
                    mid_epoch_start = 0
                    loss = None
                    rolled_back = False
                    if self.k_dispatch > 1:
                        # (stacked k-batch, n_steps, rows) per host dispatch;
                        # loss logging reports each dispatch's LAST step (the
                        # intermediate losses live only inside the scan)
                        dispatches = self.loader.epoch_groups(
                            epoch, self.k_dispatch, start_step=epoch_start_step)
                    else:
                        dispatches = (
                            (b, 1, self.loader.batch_rows(epoch_start_step + i))
                            for i, b in enumerate(self.loader.epoch(
                                epoch, start_step=epoch_start_step)))
                    # each next() is a "load" span (host batch assembly);
                    # pass-through when tracing is off
                    dispatches = trace_lib.traced_iter("load", dispatches)
                    for batch, n_steps, rows in dispatches:
                        if shutdown.requested:
                            break
                        if monitor is not None and len(monitor_q) >= 2:
                            # observe at lag 2 (not the newest future): the
                            # device_get then waits only on a step that
                            # already has a successor submitted, so one
                            # dispatch stays in flight and the async
                            # pipeline keeps overlapping host batch prep
                            # with device compute even when log_every > 1
                            m_step, m_loss = monitor_q.pop(0)
                            with trace_lib.span("fetch", what="monitor",
                                                step=m_step):
                                m_val = float(jax.device_get(m_loss))
                            action = monitor.observe(m_val)
                            if action == "abort":
                                raise AnomalyAbort(
                                    f"training diverged at step {m_step}: "
                                    f"{monitor.bad_steps} bad steps and the "
                                    f"rollback budget (max_rollbacks="
                                    f"{cfg.max_rollbacks}) is exhausted")
                            if action == "rollback":
                                do_rollback(
                                    f"anomaly rollback "
                                    f"#{monitor.rollbacks}: "
                                    f"{cfg.rollback_after} consecutive "
                                    "bad steps")
                                break
                        # log when the dispatch CROSSED a log_every boundary
                        # (== the modulo rule at n_steps=1; prev[3] is the
                        # step count before that dispatch)
                        if prev is not None and cfg.log_every and \
                                prev[0] // cfg.log_every > prev[3] // cfg.log_every:
                            with trace_lib.span("fetch", what="log",
                                                step=prev[0]):
                                last_loss = float(jax.device_get(prev[2]))
                            self.metrics.write({
                                "step": prev[0], "epoch": prev[1],
                                "loss": last_loss,
                                "samples_per_sec": thr.samples_per_sec,
                            })
                        if fault_plan is not None:
                            # I/O fault kinds need the checkpoint dir
                            batch = fault_plan.apply(
                                step, batch, ckpt_dir=cfg.checkpoint_dir)
                            # SDC kinds (bitflip/desync) corrupt one
                            # replica shard of the device-placed state
                            self.state = fault_plan.apply_state(step,
                                                                self.state)
                        if self._fp is not None:
                            # retained for the replay triage (batches are
                            # not donated; holding one dispatch's worth
                            # of rows is the entire cost)
                            self._sdc_batch = batch
                        # "dispatch" measures the HOST-side submission
                        # cost (async — the device runs behind it)
                        with trace_lib.span("dispatch", step=step):
                            if self.k_dispatch > 1:
                                self.state, outs = self.multi_step(
                                    self.state, batch)
                                # each dispatch reports its LAST step
                                # (the intermediate outputs live inside
                                # the scan; the 'skipped' metric is the
                                # guard's CUMULATIVE counter exactly so
                                # this slice cannot lose mid-dispatch
                                # fires)
                                out = jax.tree_util.tree_map(
                                    lambda x: x[-1], outs)
                            else:
                                self.state, out = self.train_step(
                                    self.state, batch)
                        # telemetry layouts return the on-device metrics
                        # dict; everything downstream keys off the loss
                        loss = out["loss"] if isinstance(out, dict) else out
                        watchdog.pat()
                        timer.tick()  # one tick per DISPATCH (= n_steps steps)
                        thr.add(rows)
                        before = step
                        step += n_steps
                        prev = (step, epoch, loss, before)
                        if monitor is not None:
                            monitor_q.append((step, loss))
                        # lag-2 fetch + metrics record + heartbeat refresh
                        self.telemetry.on_dispatch(step, epoch, before, out,
                                                   n_steps, rows)
                        # k>1 dispatches can stride over an exact multiple;
                        # fire on every boundary CROSSING (== the k=1 modulo
                        # rule when n_steps is 1).  While the monitor's
                        # bad-step streak is nonzero the snapshot is SKIPPED
                        # (next boundary saves): checkpointing mid-anomaly
                        # would capture possibly-diverged params and rotate
                        # the last good snapshot toward deletion — the very
                        # state rollback needs.  (The observation lag means
                        # a boundary landing within ~2 dispatches of the
                        # first bad step can still be captured; with the
                        # guard on, params are protected regardless.)
                        if (self._fp is not None and
                                step // self.sdc_every
                                > before // self.sdc_every):
                            # dispatch the fingerprint on the state the
                            # step just produced (async — its buffers are
                            # still valid here; the NEXT dispatch's
                            # donation is sequenced after this read), and
                            # observe at lag 2 like the loss monitor.
                            # Runs BEFORE the snapshot block below, so a
                            # corruption this boundary can surface is
                            # handled before anything reaches disk.
                            sdc_q.append((step, self._fp.compute(self.state)))
                            act = sdc_pump(keep=1)
                            if act == "rollback":
                                # cross-host divergence: the local
                                # majority is no reference — restore the
                                # newest verified checkpoint (identical
                                # on every host, DESIGN.md §8 machinery)
                                do_rollback("[sdc] cross-host divergence")
                                break
                        if (cfg.checkpoint_every and
                                step // cfg.checkpoint_every
                                > before // cfg.checkpoint_every and
                                (monitor is None or monitor.consecutive == 0)):
                            # a snapshot must never capture state the
                            # fingerprint queue has not cleared yet: the
                            # corrupt bytes would reach disk and rotate
                            # the last good generation toward deletion —
                            # the SDC analogue of the bad-streak skip
                            # above.  Draining costs nothing extra here:
                            # these futures are older than the state
                            # device_get the save itself stalls on.
                            if sdc_pump(keep=0) == "rollback":
                                do_rollback("[sdc] cross-host divergence "
                                            "at a snapshot boundary")
                                break
                            with watchdog.suspended():
                                self.save()
                    if rolled_back:
                        epoch = (step + self._step_offset) // spe
                        mid_epoch_start = (step + self._step_offset) % spe
                        continue
                    if shutdown.requested:
                        # graceful preemption: materialize the last loss, then
                        # fall through to the final save with <= 1 lost step
                        if loss is not None:
                            last_loss = float(jax.device_get(loss))
                        break
                    # per-epoch loss line (reference :224, but one global line
                    # instead of N interleaved per-rank prints)
                    if loss is not None:
                        last_loss = float(jax.device_get(loss))
                    log(f"epoch {epoch + 1}: loss {last_loss:.6f} "
                        f"({time.perf_counter() - epoch_t0:.3f}s)")
                    # periodic held-out eval (the reference's :213-220 intent)
                    if (self.val_data is not None and cfg.eval_every
                            and (epoch + 1) % cfg.eval_every == 0):
                        with trace_lib.span("eval", epoch=epoch), \
                                watchdog.suspended():
                            ev = self.evaluate(self.val_data)
                        last_eval = (step, ev)
                        log("validation: " + ", ".join(
                            f"{k} {v:.6f}" for k, v in sorted(ev.items())))
                        self.metrics.write({"step": step, "epoch": epoch,
                                            **{f"val_{k}": v
                                               for k, v in ev.items()}})
                    epoch += 1
                # drain the SDC lag queue before the final save: every
                # queued fingerprint is complete by now, and a divergence
                # detected here must still heal (or abort) BEFORE the
                # final snapshot can capture corrupt state
                sdc_pump(keep=0, draining=True)
        finally:
            # deterministic prefetch-worker release: an exception escaping
            # this frame (AnomalyAbort, a re-raised async-write failure)
            # keeps it alive in the traceback, so the abandoned dispatch
            # generator would otherwise park its loader thread until GC
            if dispatches is not None and hasattr(dispatches, "close"):
                dispatches.close()
            exc = sys.exc_info()[1]
            if exc is not None:
                # abnormal exit (anomaly abort, crash): the flight
                # recorder's dump is the black box a relaunch reads —
                # then release the telemetry/metrics handles (the normal
                # path closes them at the end of fit; without this an
                # aborted fit leaks the jsonl fd and leaves the module
                # _ACTIVE pointing at a dead run's directory)
                self.telemetry.on_abnormal_exit(exc)
                self.metrics.close()
                self.telemetry.close()
                if self.tracer is not None:
                    # flush the span timeline too: the trace must
                    # survive the crash for the postmortem merge
                    trace_lib.stop_run(self.tracer)
        if prev is not None and cfg.log_every and \
                prev[0] // cfg.log_every > prev[3] // cfg.log_every:
            self.metrics.write({"step": prev[0], "epoch": prev[1],
                                "loss": last_loss,
                                "samples_per_sec": thr.samples_per_sec})
        # drain the telemetry lag queue (every queued future is complete
        # by now) and write the final heartbeat at the real step (in the
        # heartbeat-only metrics_every=0 mode no record carries one)
        self.telemetry.flush(step=step)
        if shutdown.requested:
            self.telemetry.on_preempted(shutdown.signum, step)
        self.save(final=True)
        result = {"final_loss": last_loss,
                  "steps": step,
                  "samples_per_sec": thr.samples_per_sec,
                  **timer.stats()}
        if shutdown.requested:
            # preemption-safe exit: the final save above already drained
            # pending async writes and snapshotted the current step — an
            # external restart (--resume / the supervisor) loses <= 1 step
            if shutdown.noticed:
                # ADVANCE-notice preemption (SIGUSR1): the node is going
                # away — the cli maps this to EXIT_DECOMMISSION (47), a
                # terminal no-retry exit the goodput ledger prices as
                # drain (the coordinated-shrink path, DESIGN.md §10:
                # peers of a multi-host victim lose it and ride the
                # elastic probe-and-shrink relaunch from THIS snapshot
                # instead of rolling back)
                log(f"preemption notice (signal {shutdown.signum}, grace "
                    f"{shutdown.grace_s or 0:.1f}s): final checkpoint at "
                    "step "
                    f"{step}, exiting 47 (decommission)")
                result["preempt_notice"] = True
            else:
                log(f"preempted (signal {shutdown.signum}): final "
                    f"checkpoint at step {step}, exiting 0")
            result["preempted"] = True
        if monitor is not None:
            result["rollbacks"] = monitor.rollbacks
            result["bad_steps"] = monitor.bad_steps
        if self._sdc_policy is not None:
            result["sdc_incidents"] = self._sdc_policy.incidents
            result["sdc_healed"] = self._sdc_policy.healed
        if self.guarded:
            # GuardedState.skipped: cumulative rejected updates — read
            # once here, off the hot path
            result["skipped_updates"] = int(
                jax.device_get(self.state.opt_state.skipped))
        # achieved model FLOPs/s (fwd + ~2x bwd per optimizer step), from
        # the single-source analytic accounting (train.telemetry /
        # Module.fwd_flops) — None for unaccounted architectures
        sample_shape = (1,) + tuple(self.data["x"].shape[1:])
        step_flops = telemetry_lib.train_step_flops(self.model, sample_shape)
        if step_flops is not None:
            result["model_flops_per_sec"] = step_flops * thr.samples_per_sec
            if self.telemetry.enabled:
                result["mfu"] = (result["model_flops_per_sec"]
                                 / self.telemetry.peak_total)
        # peak device memory where the backend reports it (TPU HBM; {} on
        # CPU) — the observability the reference's prints never had.
        # PROCESS-lifetime high-water mark (the runtime never resets it),
        # so a second fit() in one process inherits the first's peak —
        # hence the explicit key name.
        mem = profiling.device_memory_stats()
        peaks = [v.get("peak_bytes_in_use") for v in mem.values()
                 if "peak_bytes_in_use" in v]
        if peaks:
            result["process_peak_memory_bytes"] = max(peaks)
        # post-training held-out eval (the reference's :227-236 intent);
        # reuse the periodic eval when it already ran at this exact step
        if self.val_data is not None:
            if last_eval is not None and last_eval[0] == step:
                ev = last_eval[1]
            else:
                with trace_lib.span("eval", final=True):
                    ev = self.evaluate(self.val_data)
                self.metrics.write({"step": step, "final": True,
                                    **{f"val_{k}": v for k, v in ev.items()}})
            result.update({f"val_{k}": v for k, v in ev.items()})
        self.metrics.close()
        self.telemetry.close()
        if self.tracer is not None:
            trace_lib.stop_run(self.tracer)
        return result

    def _eval_params(self):
        """Params in the *dense* (per-layer, unpermuted) layout — used for
        checkpoint interop and tests, NOT by :meth:`evaluate` (every eval
        step consumes the train state's own layout in place, so this
        single-host gather is off the eval path entirely)."""
        if not (self.pipeline or self.sp_tp or self.ep_tp):
            return self.state.params
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel import pipeline as pp

        params = dict(jax.device_get(self.state.params))
        params["blocks"] = pp.dense_layer_blocks(
            params["blocks"], self.model.cfg,
            saved_tp=int(self.mesh.shape.get("tensor", 1)))
        return jax.device_put(params, NamedSharding(self.mesh, P()))

    def evaluate(self, data: Optional[Dict[str, np.ndarray]] = None) -> Dict[str, float]:
        loader = self.loader if data is None else ShardedLoader(
            self.mesh, data, self.cfg.batch_size, shuffle=False,
            seed=self.cfg.seed, full_batch=self.cfg.full_batch,
            seq_axis="seq" if self.seq_parallel else None,
            batch_axes=self.batch_axes,
            seq_permutation=self.seq_permutation)
        # every eval step (dense, gspmd, moe, pipelined) consumes the train
        # state's own layout in place — no gather; _eval_params is only for
        # checkpoint interop / dense export
        params = self.state.params
        sums: Dict[str, float] = {}
        totals: Dict[str, float] = {}
        for batch in loader.epoch(0):
            # eval emits no train dispatches; beat the heartbeat so the
            # external staleness monitor doesn't kill a long eval tail
            self.telemetry.alive()
            m = jax.device_get(self.eval_step(params, batch))
            c = float(m.pop("count"))
            ec = float(m.pop("example_count", c))
            for k, v in m.items():
                w = ec if k == "accuracy" else c  # per-example vs per-token
                sums[k] = sums.get(k, 0.0) + float(v) * w
                totals[k] = totals.get(k, 0.0) + w
        out = {k: v / totals[k] for k, v in sums.items()}
        if self.cfg.loss == "cross_entropy" and "loss" in out:
            # token-level perplexity (the LM community's headline number);
            # clamp the exponent so a huge-but-finite loss can't overflow
            # to inf (a NaN loss stays NaN — same signal as val_loss)
            out["ppl"] = float(np.exp(min(out["loss"], 30.0))
                               if not np.isnan(out["loss"])
                               else float("nan"))
        return out
