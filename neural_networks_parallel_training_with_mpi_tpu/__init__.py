"""TPU-native synchronous data-parallel training framework.

A brand-new JAX/XLA framework with the capabilities of the reference
``btourn/Neural-Networks-parallel-training-with-MPI``
(/root/reference/dataParallelTraining_NN_MPI.py): a replicated model is
trained on disjoint shards of a dataset with per-shard gradients averaged
across workers every step.  Where the reference hand-rolls this over mpi4py
(state-dict ``bcast`` at :87, ``Scatter``/``Scatterv`` data distribution at
:108/:138, gather-average-at-root gradient sync at :185-208), this framework
expresses it TPU-first:

* world formation   -> ``jax.distributed`` + ``jax.sharding.Mesh``  (parallel.mesh)
* data distribution -> batch-axis ``NamedSharding`` / host sharding (parallel.sharding, data.loader)
* gradient sync     -> one fused ``lax.pmean``/``psum`` over ICI    (parallel.data_parallel)
* model/optimizer   -> pure-pytree modules + optimizers             (models, ops.optim)

Public API is re-exported here for convenience.
"""

from .utils import compat as _compat  # noqa: F401 — jax API shims, first
from .config import TrainConfig, MeshConfig, DataConfig, ModelConfig
from .parallel.mesh import make_mesh, world_setup, local_mesh
from .parallel.sharding import (
    shard_sizes,
    pad_to_multiple,
    batch_sharding,
    replicated_sharding,
    shard_batch,
)
from .ops import optim, losses
from .train.trainer import Trainer, TrainState

__version__ = "0.1.0"

__all__ = [
    "TrainConfig",
    "MeshConfig",
    "DataConfig",
    "ModelConfig",
    "make_mesh",
    "world_setup",
    "local_mesh",
    "shard_sizes",
    "pad_to_multiple",
    "batch_sharding",
    "replicated_sharding",
    "shard_batch",
    "optim",
    "losses",
    "Trainer",
    "TrainState",
]
