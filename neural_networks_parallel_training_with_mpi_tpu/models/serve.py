"""Continuous-batching decode server (in-flight batching).

The reference has no serving story at all (its closest artifact is the
dead test-eval block, dataParallelTraining_NN_MPI.py:227-236).  This is
the runtime layer above :mod:`models.generate`: a fixed pool of ``slots``
decodes as ONE batched jitted step per token, while requests join and
leave mid-flight — the scheduling model TPU serving wants, because the
chip's throughput comes from batching yet real traffic arrives ragged.

Design (slot server):

* Device state: per-layer KV caches ``(S, L, kv_heads, head_dim)``, a
  token ring ``(S, L)``, per-slot ``pos`` and ``target`` — all static
  shapes, so the decode step is ONE compiled program regardless of which
  subset of slots is live.
* ``submit()`` prefills the prompt with the existing chunk path
  (:func:`models.generate._forward_chunk`) on a batch-1 cache and
  inserts the resulting cache slab + first sampled token into a free
  slot (a vmapped ``dynamic_update_slice`` on the slot axis).  Admission
  cost is one prefill, never a pool-wide recompile.
* ``step()`` advances EVERY slot one token with
  :func:`models.generate._forward_token_batched` — each row attends at
  its own depth via a per-row causal mask and writes its K/V at its own
  position (vmapped update).  Finished or free slots still flow through
  the batch (their writes are idempotent re-writes of the same values
  and their samples are discarded); masking happens host-side in the
  pos/active bookkeeping, which is exactly the continuous-batching
  contract: dead lanes cost FLOPs, not recompiles, and are reclaimed at
  the next ``submit``.  Completion detection is host-side too: positions
  advance deterministically (+1 per active slot per step), so ``step()``
  performs ZERO per-token device syncs — the old per-step blocking
  ``device_get(self.pos)`` serialized the host against the device
  pipeline every token (measured delta in BENCH_SERVE.json;
  ``sync_per_step=True`` keeps the legacy fetch for that measurement).
* Greedy (temperature=0) decode matches :func:`models.generate.generate`
  token-for-token per request — pinned by tests/test_serve.py — because
  each row's attention reduces over exactly the same values in the same
  order as the single-stream path.

Host API::

    srv = DecodeServer(model, params, slots=4)
    rid = srv.submit([1, 2, 3], max_new_tokens=16)   # None if pool full
    while not srv.done(rid):
        srv.step()
    tokens = srv.result(rid)                          # prompt + decoded
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .generate import (
    _forward_chunk,
    _forward_token_batched,
    _sample,
    init_kv_cache,
)
from .transformer import Transformer

Pytree = Any


@functools.lru_cache(maxsize=8)
def _programs(model: Transformer, max_len: int, temperature: float,
              top_k: int, top_p: float, kv_quant: bool = False,
              prefill_chunk: int = 0):
    """The three jitted programs of a server instance, cached per (model,
    shape, sampling) so constructing several servers (or re-constructing
    in tests) compiles once."""

    def prefill(params, prompt):     # (1, P_bucket) -> logits + cache
        # prompts arrive padded to power-of-two buckets (submit), so the
        # number of compiled prefill programs is bounded by log2(max_len)
        # instead of one per distinct prompt length; all positions'
        # logits return and the caller indexes the true last position.
        # Pad positions' K/V land in the cache but are never attended:
        # decode masks keys <= pos and overwrites position p, p+1, ...
        # with generated tokens before each becomes visible.
        caches = init_kv_cache(model, 1, max_len, quant=kv_quant)
        pb = prompt.shape[1]
        if 0 < prefill_chunk < pb:
            # chunked prefill (generate()'s long-prompt lever): peak
            # attention memory O(chunk * T) instead of O(bucket * T);
            # all widths are static, so this is still ONE compiled
            # program per bucket
            outs = []
            for off in range(0, pb, prefill_chunk):
                w = min(prefill_chunk, pb - off)
                lg, caches = _forward_chunk(model, params, caches,
                                            prompt[:, off:off + w], off)
                outs.append(lg)
            return jnp.concatenate(outs, axis=1), caches
        logits, caches = _forward_chunk(model, params, caches, prompt, 0)
        return logits, caches

    def insert(pool, slab, slot):         # write batch-1 cache into slot
        return jax.tree_util.tree_map(
            lambda buf, one: lax.dynamic_update_slice(
                buf, one.astype(buf.dtype),
                (slot,) + (0,) * (buf.ndim - 1)),
            pool, slab)

    def step(params, caches, tokens, pos, active, key):
        b = tokens.shape[0]
        ids = jnp.take_along_axis(tokens, pos[:, None], axis=1)  # (S, 1)
        logits, caches = _forward_token_batched(model, params, caches,
                                                ids, pos)
        nxt, key = _sample(logits[:, 0], temperature, key, top_k, top_p)
        # only active slots append + advance; frozen slots re-write the
        # same K/V at the same pos (idempotent) and discard their sample
        nxt = jnp.where(active, nxt, jnp.take_along_axis(
            tokens, jnp.minimum(pos + 1, max_len - 1)[:, None],
            axis=1)[:, 0])
        write_at = jnp.minimum(pos + 1, max_len - 1)
        tokens = tokens.at[jnp.arange(b), write_at].set(nxt)
        pos = jnp.where(active, jnp.minimum(pos + 1, max_len - 1), pos)
        return caches, tokens, pos, key

    # compile-ledger seam (utils/compile_ledger): the dense server's
    # programs report their compiles like the paged server's
    from ..utils import compile_ledger as ledger_lib

    tag = f"T{max_len}" + ("/int8" if kv_quant else "")
    return (ledger_lib.instrument(jax.jit(prefill),
                                  f"dense_prefill[{tag}]"),
            ledger_lib.instrument(jax.jit(insert, donate_argnums=(0,)),
                                  f"dense_insert[{tag}]"),
            ledger_lib.instrument(jax.jit(step, donate_argnums=(1, 2, 3)),
                                  f"dense_decode[{tag}]"))


class DecodeServer:
    """Slot-based continuous batching on top of the KV-cache decoder."""

    def __init__(self, model: Transformer, params: Pytree, slots: int = 4,
                 max_len: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 kv_quant: bool = False, prefill_chunk: int = 0,
                 sync_per_step: bool = False):
        c = model.cfg
        self.model, self.params = model, params
        self.slots = int(slots)
        self.max_len = int(max_len or c.max_seq_len)
        if self.max_len > c.max_seq_len:
            raise ValueError(f"max_len {self.max_len} exceeds model "
                             f"max_seq_len {c.max_seq_len}")
        self._sampling = (float(temperature), int(top_k), float(top_p))
        self._prefill, self._insert, self._step = _programs(
            model, self.max_len, *self._sampling, bool(kv_quant),
            int(prefill_chunk))
        self.caches = init_kv_cache(model, self.slots, self.max_len,
                                    quant=kv_quant)
        self.tokens = jnp.zeros((self.slots, self.max_len), jnp.int32)
        self.pos = jnp.zeros((self.slots,), jnp.int32)
        self.active = np.zeros((self.slots,), bool)      # host-side
        # host shadow of ``pos``: positions advance deterministically
        # (one per active slot per step), so completion detection needs
        # NO device fetch — the per-token blocking device_get this loop
        # used to pay serialized every step against the device pipeline.
        # ``sync_per_step=True`` restores the old fetch, kept ONLY so
        # bench.py can measure the delta (BENCH_SERVE.json).
        self._pos_host = np.zeros((self.slots,), np.int64)
        self._sync_per_step = bool(sync_per_step)
        self.key = jax.random.PRNGKey(seed)
        # request bookkeeping (host): slot -> (request id, prompt_len,
        # target total length); results keyed by request id
        self._rid = 0
        self._slot_req: Dict[int, tuple] = {}
        self._results: Dict[int, List[int]] = {}
        if c.scan_layers:
            params = dict(params)
            stacked = params["blocks"]
            params["blocks"] = [
                jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
                for i in range(c.n_layers)]
            self.params = params

    # ---- admission ----------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int) -> Optional[int]:
        """Admit a request into a free slot; returns a request id, or
        None when the pool is full (caller queues and retries after
        step()s complete requests)."""
        free = [s for s in range(self.slots) if not self.active[s]
                and s not in self._slot_req]
        if not free:
            return None
        p = len(prompt_ids)
        if p == 0:
            raise ValueError("empty prompt: a request needs at least one "
                             "token (bucketed prefill would otherwise "
                             "sample from pad-position logits)")
        if p + max_new_tokens > self.max_len:
            raise ValueError(f"prompt {p} + {max_new_tokens} exceeds "
                             f"server max_len {self.max_len}")
        slot = free[0]
        bucket = 8
        while bucket < p:
            bucket *= 2
        bucket = min(bucket, self.max_len)
        padded = list(prompt_ids) + [0] * (bucket - p)
        prompt = jnp.asarray([padded], jnp.int32)
        logits, slab = self._prefill(self.params, prompt)
        t, tk, tp = self._sampling
        first_row, self.key = _sample(logits[:, p - 1], t, self.key, tk, tp)
        first = first_row[0]
        self.caches = [self._insert(pool, one, slot)
                       for pool, one in zip(self.caches, slab)]
        row = np.zeros((self.max_len,), np.int32)
        row[:p] = np.asarray(prompt_ids, np.int32)
        row[p] = int(first)
        self.tokens = self.tokens.at[slot].set(jnp.asarray(row))
        self.pos = self.pos.at[slot].set(p)      # last written position
        self._pos_host[slot] = p
        self.active[slot] = max_new_tokens > 1
        rid = self._rid
        self._rid += 1
        self._slot_req[slot] = (rid, p, p + max_new_tokens)
        if not self.active[slot]:                # single-token request
            self._finish(slot)
        return rid

    # ---- decode -------------------------------------------------------
    def step(self) -> None:
        """One batched decode step across all slots (no-op when nothing
        is active)."""
        if not self.active.any():
            return
        active_dev = jnp.asarray(self.active)
        self.caches, self.tokens, self.pos, self.key = self._step(
            self.params, self.caches, self.tokens, self.pos, active_dev,
            self.key)
        if self._sync_per_step:
            # measurement-only legacy path: block on the device every
            # step (the host sync the default path no longer pays)
            self._pos_host[:] = np.asarray(jax.device_get(self.pos))
        else:
            # positions advance deterministically: +1 per active slot.
            # The device array clamps at max_len-1 but an active slot
            # always finishes at target <= max_len first, so the shadow
            # never diverges while it matters.
            self._pos_host[self.active] += 1
        for slot, (rid, p, target) in list(self._slot_req.items()):
            if self.active[slot] and self._pos_host[slot] + 1 >= target:
                self._finish(slot)

    def _finish(self, slot: int) -> None:
        rid, p, target = self._slot_req.pop(slot)
        row = np.asarray(jax.device_get(self.tokens[slot]))
        self._results[rid] = [int(t) for t in row[:target]]
        self.active[slot] = False

    # ---- results ------------------------------------------------------
    def done(self, rid: int) -> bool:
        """True once ``rid`` finished; raises KeyError for an id this
        server never issued or whose result was already consumed — a
        'while not done(rid)' loop on a stale id must fail loudly, not
        spin forever on a pool with nothing active."""
        if rid in self._results:
            return True
        if any(r == rid for r, _, _ in self._slot_req.values()):
            return False
        raise KeyError(f"request {rid}: unknown or already consumed")

    def result(self, rid: int) -> List[int]:
        """Prompt + generated ids for a finished request (pops it)."""
        return self._results.pop(rid)

    def live(self) -> int:
        """Number of in-flight requests."""
        return len(self._slot_req)
