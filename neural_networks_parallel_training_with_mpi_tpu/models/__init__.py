"""Model zoo: pure-pytree functional modules.

``mlp.reference_mlp()`` is the parity model — the reference's
``MLP = Sequential(Linear(2,3), ReLU(), Linear(3,1))``
(dataParallelTraining_NN_MPI.py:41-45).  The rest covers the BASELINE.json
configs: wide MLP, MNIST MLP, CIFAR ConvNet, tiny Transformer LM.
"""

from .core import Module, Linear, Sequential, Activation, Conv2D, LayerNorm, Embedding
from .mlp import MLP, reference_mlp
from .convnet import ConvNet
from .transformer import Transformer, TransformerConfig
from .registry import build_model
from .generate import generate, generate_sharded
from .generate_tp import generate_tp, pipeline_params_for_decode
from .serve import DecodeServer
from .speculative import speculative_generate, speculative_generate_device

__all__ = [
    "Module", "Linear", "Sequential", "Activation", "Conv2D", "LayerNorm",
    "Embedding", "MLP", "reference_mlp", "ConvNet", "Transformer",
    "TransformerConfig", "build_model", "generate", "generate_sharded",
    "generate_tp", "pipeline_params_for_decode", "DecodeServer",
    "speculative_generate",
    "speculative_generate_device",
]
