"""Small ConvNet for CIFAR-10 (BASELINE.json config #4).

NHWC / HWIO layouts so XLA tiles the convs straight onto the MXU.  The
reference has no conv model (its only model is the 13-param MLP,
dataParallelTraining_NN_MPI.py:41-45); this is part of the model-zoo widening
mandated by BASELINE.json's configs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax.numpy as jnp

from .core import Activation, AvgPool2D, Conv2D, Flatten, Linear, Module, Sequential


@dataclass(frozen=True)
class ConvNet(Module):
    """conv-act-pool blocks -> flatten -> dense head."""

    in_channels: int = 3
    channels: Tuple[int, ...] = (32, 64)
    image_hw: Tuple[int, int] = (32, 32)
    n_classes: int = 10
    hidden: int = 128
    activation: str = "relu"
    param_dtype: Any = jnp.float32
    compute_dtype: Optional[Any] = None

    @property
    def net(self) -> Sequential:
        layers = []
        prev = self.in_channels
        h, w = self.image_hw
        for c in self.channels:
            layers += [Conv2D(prev, c, kernel=3, param_dtype=self.param_dtype),
                       Activation(self.activation),
                       AvgPool2D(2)]
            prev = c
            h, w = h // 2, w // 2
        layers += [Flatten(),
                   Linear(prev * h * w, self.hidden, param_dtype=self.param_dtype,
                          compute_dtype=self.compute_dtype),
                   Activation(self.activation),
                   Linear(self.hidden, self.n_classes, param_dtype=self.param_dtype,
                          compute_dtype=self.compute_dtype)]
        return Sequential(tuple(layers))

    def init(self, key):
        return self.net.init(key)

    def apply(self, params, x, **kwargs):
        return self.net.apply(params, x, **kwargs)

    def fwd_flops(self, x_shape):
        batch = x_shape[0]
        h, w = self.image_hw
        cin = self.in_channels
        f = 0.0
        for cout in self.channels:
            f += 2.0 * batch * h * w * 9 * cin * cout  # 3x3 SAME conv
            h, w, cin = h // 2, w // 2, cout           # then 2x2 avg-pool
        dims = (cin * h * w, self.hidden, self.n_classes)
        f += 2.0 * batch * sum(a * b for a, b in zip(dims, dims[1:]))
        return f
