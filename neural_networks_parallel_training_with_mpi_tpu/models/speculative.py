"""Speculative decoding (greedy): a small DRAFT model proposes k tokens,
the TARGET verifies all k in ONE chunked forward, and the longest agreeing
prefix is accepted plus the target's own correction token.

Why it belongs in a TPU serving stack: autoregressive decode runs one
bandwidth-bound (B, 1) step per token on the big model, while a chunked
verify runs k+1 positions through the SAME weights for nearly the same
HBM traffic as one step (weights stream once either way; the MXU eats
the extra rows).  With an accept rate a, the target pays roughly
ceil(N / (accepted-per-round)) chunk passes instead of N steps — the
classic latency lever when a cheap draft tracks the target well.

Greedy speculation is EXACT: every emitted token is argmax of the
target's logits at its position (accepted proposals by the verify
comparison, corrections directly), so the output is identical to
``generate(target, ...)`` token for token — pinned by
tests/test_speculative.py, not just asserted here.  One honest caveat:
the verify pass computes those logits in an (r+1)-wide chunk while
``generate`` uses (B, 1) steps — different XLA programs, so floats may
reassociate and a NEAR-TIE argmax can in principle flip.  Trained
models have logit margins that make this unobservable (the tests pin
bitwise equality), but UNTRAINED models' near-flat logits do flip ties
— visible as a sub-1 self-draft accept rate in the bench's mechanism
row, which is a tie-stability artifact, not a speculation bug.
Temperature speculation (``temperature > 0`` + a PRNG key) uses the
rejection-sampling correction of Leviathan et al. 2023
(:func:`accept_proposals`): each draft sample is accepted with
probability ``min(1, p/q)`` and the first rejection resamples from the
residual ``norm(max(0, p − q))``, so committed tokens are distributed
EXACTLY as target samples — pinned statistically on the pure numpy
core.  Greedy (``temperature == 0``) keeps the argmax-equality
contract above.

Cache bookkeeping rides the same invariant as the server's bucketed
prefill: positions past the accepted point hold stale K/V from rejected
proposals, but decode masks keys ``<= pos`` and every position is
REWRITTEN by the pass that next visits it before it becomes visible, so
no rewind is ever needed — "rollback" is free.  ONE position escapes
that invariant: after a fully-accepted round the draft never saw its
own last proposal (the round advances past it, so no later pass
rewrites it), which would leave a permanent ZERO draft-K/V entry that
every subsequent draft step attends.  Both paths therefore run a single
catch-up draft step there (see the ``n_acc == r`` blocks), keeping the
draft cache dense — pinned by the draft-cache-density regression tests.

Both models run their standard chunked forward
(``models.generate._forward_chunk``), so GQA, RoPE, SwiGLU, int8
weights, and the int8 KV cache all compose with speculation untouched.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .generate import _forward_chunk, init_kv_cache
from .transformer import Transformer


def _softmax(logits: np.ndarray, temperature: float) -> np.ndarray:
    z = logits.astype(np.float64) / temperature
    z -= z.max(-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(-1, keepdims=True)


def accept_proposals(p_logits: np.ndarray, q_logits: np.ndarray,
                     proposals: np.ndarray, temperature: float,
                     rng: np.random.Generator) -> Tuple[int, int]:
    """The Leviathan et al. 2023 rejection-sampling core for ONE batch
    row: proposals ``x_i ~ q_i`` are accepted with probability
    ``min(1, p_i(x_i) / q_i(x_i))``; at the first rejection the bonus
    token is drawn from the residual ``norm(max(0, p_i − q_i))``; if all
    r proposals survive, the bonus comes from the target's own
    ``p_{r}``.  Returns ``(n_accepted, bonus_token)``.

    The committed sequence ``x_0..x_{n-1}, bonus`` is distributed
    EXACTLY as n+1 ancestral samples from the target at this
    temperature — the marginal-exactness property pinned statistically
    by tests/test_speculative.py::test_acceptance_core_preserves_target
    (a pure-numpy function so the test can afford 10^5 trials).

    Shapes: ``p_logits (r+1, V)`` (target logits at the r proposal slots
    plus the bonus slot), ``q_logits (r, V)`` (draft logits the
    proposals were sampled from), ``proposals (r,)``.
    """
    r = proposals.shape[0]
    p = _softmax(p_logits, temperature)          # (r+1, V)
    q = _softmax(q_logits, temperature)          # (r, V)
    for i in range(r):
        x = int(proposals[i])
        if rng.random() < min(1.0, p[i, x] / max(q[i, x], 1e-38)):
            continue
        residual = np.maximum(p[i] - q[i], 0.0)
        total = residual.sum()
        if total <= 0:                            # p == q: accept x
            return i, x
        return i, int(rng.choice(p.shape[-1], p=residual / total))
    return r, int(rng.choice(p.shape[-1], p=p[r]))


@functools.lru_cache(maxsize=64)
def _chunk_program(model: Transformer, max_len: int, chunk: int,
                   kv_quant: bool):
    """One jitted (params, caches, ids (B, chunk), pos) -> (logits,
    caches) per (model, shapes): position is TRACED, so draft steps and
    verify chunks at every position share one compiled program each."""

    def run(params, caches, ids, pos):
        return _forward_chunk(model, params, caches, ids, pos)

    return jax.jit(run)


def speculative_generate(target: Transformer, target_params,
                         draft: Transformer, draft_params,
                         prompt: jax.Array, max_new_tokens: int,
                         k: int = 4, kv_quant: bool = False,
                         temperature: float = 0.0,
                         key: Optional[jax.Array] = None,
                         debug_state: Optional[dict] = None
                         ) -> Tuple[jax.Array, dict]:
    """Speculative decode -> ``(tokens (B, P + N), stats)``.

    ``temperature == 0`` (default) is greedy: output equals
    ``generate(target, ...)`` token for token.  ``temperature > 0``
    REQUIRES ``key`` and samples with the rejection-sampling correction
    (:func:`accept_proposals`), so committed tokens are distributed as
    target samples at that temperature; the decode is deterministic
    given ``(key, inputs)``.

    ``stats`` reports ``target_passes`` (chunked verifies the target ran,
    vs ``max_new_tokens`` single steps without speculation),
    ``draft_steps``, and ``accept_rate`` (accepted_total /
    proposed_total — tail rounds propose fewer than k, so the
    denominator is what was actually proposed).  The draft must share the target's vocabulary; batch
    rows are verified in lockstep (a row's round accepts the minimum of
    its own agreement — B=1 recovers the per-stream optimum, and larger
    B trades some accept rate for batching, the standard tradeoff).
    """
    if target.cfg.vocab_size != draft.cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft.cfg.vocab_size} != target vocab "
            f"{target.cfg.vocab_size}")
    use_temp = temperature > 0
    if use_temp and key is None:
        raise ValueError("temperature speculation needs a PRNG key")
    # numpy rng streams derived from the jax key: one per (round, row),
    # shared by the draft's sampling and the acceptance draws — the
    # whole decode is deterministic given (key, inputs)
    key_ints = ([int(x) for x in
                 np.asarray(jax.random.key_data(key)).ravel()]
                if use_temp else [])
    b, p = prompt.shape
    if max_new_tokens <= 0:   # mirror generate(): nothing to decode
        return jnp.asarray(prompt, jnp.int32), {
            "target_passes": 0, "draft_steps": 0, "rounds": 0,
            "accepted_total": 0, "proposed_total": 0, "accept_rate": 0.0}
    total = p + max_new_tokens
    for name, m in (("target", target), ("draft", draft)):
        if total > m.cfg.max_seq_len:
            raise ValueError(f"prompt {p} + {max_new_tokens} exceeds "
                             f"{name} max_seq_len {m.cfg.max_seq_len}")
    k = max(1, min(int(k), max_new_tokens))

    d_step = _chunk_program(draft, total, 1, kv_quant)
    t_caches = init_kv_cache(target, b, total, quant=kv_quant)
    d_caches = init_kv_cache(draft, b, total, quant=kv_quant)

    tokens = np.zeros((b, total), np.int32)
    tokens[:, :p] = np.asarray(prompt, np.int32)

    # prefill both models; the target's last-position argmax is token p
    t_prefill = _chunk_program(target, total, p, kv_quant)
    d_prefill = _chunk_program(draft, total, p, kv_quant)
    logits, t_caches = t_prefill(target_params, t_caches,
                                 jnp.asarray(tokens[:, :p]), 0)
    if use_temp:
        last = np.asarray(logits[:, -1])
        rng0 = np.random.default_rng(key_ints + [0xFEED])
        tokens[:, p] = [int(rng0.choice(last.shape[-1],
                                        p=_softmax(last[row], temperature)))
                       for row in range(b)]
    else:
        tokens[:, p] = np.asarray(jnp.argmax(logits[:, -1], -1))
    _, d_caches = d_prefill(draft_params, d_caches,
                            jnp.asarray(tokens[:, :p]), 0)

    pos = p            # index of the newest COMMITTED token
    stats = {"target_passes": 1, "draft_steps": 0, "rounds": 0,
             "accepted_total": 0, "proposed_total": 0}
    while pos < total - 1:
        r = min(k, total - 1 - pos)
        rngs = ([np.random.default_rng(key_ints + [stats["rounds"], row])
                 for row in range(b)] if use_temp else None)
        # --- draft proposes r tokens autoregressively ------------------
        proposals = np.zeros((b, r), np.int32)
        q_store = (np.zeros((b, r, target.cfg.vocab_size), np.float32)
                   if use_temp else None)
        cur = tokens[:, pos]
        for i in range(r):
            dl, d_caches = d_step(draft_params, d_caches,
                                  jnp.asarray(cur[:, None]), pos + i)
            if use_temp:
                dl_np = np.asarray(dl[:, -1])
                q_store[:, i] = dl_np
                cur = np.asarray(
                    [rngs[row].choice(dl_np.shape[-1],
                                      p=_softmax(dl_np[row], temperature))
                     for row in range(b)], np.int32)
            else:
                # greedy transfers only the (B,) argmax ints — never the
                # full logits row — on the latency-critical loop
                cur = np.asarray(jnp.argmax(dl[:, -1], -1), np.int32)
            proposals[:, i] = cur
            stats["draft_steps"] += 1
        # --- target verifies the r proposals in one chunk --------------
        # chunk = committed token at pos followed by the r proposals;
        # logits[i] are the target's prediction for position pos+1+i.
        # NO padding to a fixed width: a padded chunk near the sequence
        # end would write K/V past `total`, and dynamic_update_slice
        # CLAMPS the start index — silently corrupting earlier
        # positions.  The lru-cached program compiles once per distinct
        # r (k in steady state plus at most k-1 tail shapes).
        chunk = np.concatenate([tokens[:, pos:pos + 1], proposals], 1)
        vl, t_caches = _chunk_program(target, total, r + 1, kv_quant)(
            target_params, t_caches, jnp.asarray(chunk), pos)
        if use_temp:
            # per-row rejection sampling (accept_proposals), then batch
            # rows commit in LOCKSTEP at the minimum accepted count: a
            # row that accepted past the cut commits its accepted
            # proposal at the cut slot (a valid target draw), a row cut
            # at its own rejection commits its residual/bonus sample —
            # either way the committed tokens stay target-distributed
            vl_np = np.asarray(vl)
            accepts, bonuses = [], []
            for row in range(b):
                a_row, bonus = accept_proposals(
                    vl_np[row, :r + 1], q_store[row], proposals[row],
                    temperature, rngs[row])
                accepts.append(a_row)
                bonuses.append(bonus)
            n_acc = int(min(accepts))
            nxt = np.asarray(
                [proposals[row, n_acc] if accepts[row] > n_acc
                 else bonuses[row] for row in range(b)], np.int32)
        else:
            want = np.asarray(jnp.argmax(vl[:, :r + 1], -1), np.int32)
            # accepted prefix: proposals[i] == target argmax at that
            # slot, batch rows in lockstep (min across rows)
            agree = proposals == want[:, :r]
            n_acc = int(min((np.argmin(row) if not row.all() else r)
                            for row in agree))
            nxt = want[:, n_acc]
        # commit accepted proposals + the next token (the bonus slot may
        # not EXIST when the tail round's proposals were all accepted
        # and land exactly on the last position)
        round_pos = pos
        if n_acc:
            tokens[:, pos + 1:pos + 1 + n_acc] = proposals[:, :n_acc]
        if pos + 1 + n_acc < total:
            tokens[:, pos + 1 + n_acc] = nxt
            pos += n_acc + 1
        else:
            pos += n_acc
        if n_acc == r and pos < total - 1:
            # fully-accepted round: the draft loop fed positions
            # round_pos .. round_pos+r-1, so the LAST proposal's position
            # (round_pos + r, now a committed token) has no draft K/V —
            # and the next round starts at round_pos + r + 1 (the bonus),
            # so unlike a rejection it would never be rewritten: every
            # later draft step would attend a zero K/V entry there.  One
            # catch-up draft step (logits discarded) keeps the draft
            # cache dense (regression: tests/test_speculative.py
            # draft-cache-density tests).
            _, d_caches = d_step(draft_params, d_caches,
                                 jnp.asarray(proposals[:, r - 1:r]),
                                 round_pos + r)
            stats["draft_steps"] += 1
        stats["target_passes"] += 1
        stats["rounds"] += 1
        stats["accepted_total"] += n_acc
        stats["proposed_total"] += r
        # stale draft/target cache entries past `pos` are rewritten
        # before the mask can expose them (module docstring) — no rewind
    stats["accept_rate"] = (stats["accepted_total"]
                            / max(1, stats["proposed_total"]))
    if debug_state is not None:
        # test hook (draft-cache-density regression): final caches + pos
        debug_state.update(d_caches=d_caches, t_caches=t_caches, pos=pos)
    return jnp.asarray(tokens), stats


# ---------------------------------------------------------------------------
# Device-side greedy speculation: the WHOLE decode as one compiled program
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _spec_device_program(target: Transformer, draft: Transformer,
                         total: int, p: int, k: int, b: int,
                         debug_caches: bool = False):
    """One jitted (t_params, d_params, prompt) -> (tokens, stats-pytree)
    program for the whole greedy speculative decode (round 5).

    The host-loop :func:`speculative_generate` pays ~2 host dispatches
    per draft token plus a device->host logits round trip per round —
    measured 4x SLOWER than the single-program plain ``generate`` on the
    trained-pair eval (BENCH_DECODE_SPEC_CPU.json) even though it cut
    target passes per token to ~0.65.  The TPU-first fix is structural:
    draft proposals run as a ``lax.scan``, greedy acceptance (an argmax
    prefix-agreement count) runs on device, and rounds run under
    ``lax.while_loop`` — zero host traffic until the final tokens.

    Greedy acceptance on device: the round's verify chunk yields the
    target's argmax ``want`` at all k+1 slots; proposals agree on a
    prefix of length ``n_acc = min_rows(sum(cumprod(agree)))`` and the
    committed block is exactly ``want[:, :n_acc+1]`` (accepted
    proposals EQUAL ``want`` there, the bonus is ``want[n_acc]``), so
    the program writes ``want`` wholesale and advances ``pos`` by
    ``n_acc+1`` — positions past the advance hold garbage that the next
    visit REWRITES before the causal mask can expose it, the module's
    standard no-rewind invariant.  Full rounds run while ``pos < total
    - 1 - k`` (a k+1 chunk never writes past the buffer); the <= k
    remaining tokens finish as predicated single steps inside the same
    program."""

    def run(t_params, d_params, prompt):
        i32 = jnp.int32
        t_caches = init_kv_cache(target, b, total)
        d_caches = init_kv_cache(draft, b, total)
        tokens = jnp.zeros((b, total), i32)
        tokens = jax.lax.dynamic_update_slice(tokens,
                                              prompt.astype(i32), (0, 0))
        tl, t_caches = _forward_chunk(target, t_params, t_caches,
                                      prompt, 0)
        tokens = tokens.at[:, p].set(
            jnp.argmax(tl[:, -1], -1).astype(i32))
        _, d_caches = _forward_chunk(draft, d_params, d_caches, prompt, 0)

        st = dict(tokens=tokens, pos=jnp.asarray(p, i32),
                  t_caches=t_caches, d_caches=d_caches,
                  rounds=jnp.zeros((), i32),
                  accepted=jnp.zeros((), i32),
                  fills=jnp.zeros((), i32))

        def full_cond(st):
            return st["pos"] < total - 1 - k

        def full_round(st):
            pos = st["pos"]
            cur0 = jax.lax.dynamic_slice(st["tokens"], (0, pos), (b, 1))

            def d_tick(carry, i):
                cur, dc = carry
                dl, dc = _forward_chunk(draft, d_params, dc,
                                        cur[:, None], pos + i)
                nxt = jnp.argmax(dl[:, -1], -1).astype(i32)
                return (nxt, dc), nxt

            (_, d_caches), props = jax.lax.scan(
                d_tick, (cur0[:, 0], st["d_caches"]), jnp.arange(k))
            props = jnp.swapaxes(props, 0, 1)              # (B, k)
            chunk = jnp.concatenate([cur0, props], axis=1)  # (B, k+1)
            vl, t_caches = _forward_chunk(target, t_params,
                                          st["t_caches"], chunk, pos)
            want = jnp.argmax(vl, -1).astype(i32)           # (B, k+1)
            agree = (props == want[:, :k]).astype(i32)
            n_acc = jnp.min(jnp.sum(jnp.cumprod(agree, axis=1), axis=1))

            def fill_last_kv(dc):
                # fully-accepted round: the draft scan fed positions
                # pos..pos+k-1, leaving the last proposal's position
                # (pos + k, committed when n_acc == k) with ZERO draft
                # K/V that no later visit rewrites (the next round starts
                # at pos + k + 1) — run one catch-up draft step so later
                # rounds never attend a zero entry.  pos + k < total - 1
                # by full_cond, so the write stays in-buffer.  On a
                # partial accept the entry IS rewritten before it becomes
                # visible (the standard no-rewind invariant), so the cond
                # skips the extra forward.
                _, dc = _forward_chunk(draft, d_params, dc,
                                       props[:, k - 1:k], pos + k)
                return dc

            d_caches = jax.lax.cond(n_acc == k, fill_last_kv,
                                    lambda dc: dc, d_caches)
            tokens = jax.lax.dynamic_update_slice(st["tokens"], want,
                                                  (0, pos + 1))
            return dict(tokens=tokens, pos=pos + n_acc + 1,
                        t_caches=t_caches, d_caches=d_caches,
                        rounds=st["rounds"] + 1,
                        accepted=st["accepted"] + n_acc,
                        fills=st["fills"] + (n_acc == k).astype(i32))

        st = jax.lax.while_loop(full_cond, full_round, st)

        def t_tick(carry, _):
            tokens, tc, pos, steps = carry
            cur = jax.lax.dynamic_slice(tokens, (0, pos), (b, 1))
            tl, tc = _forward_chunk(target, t_params, tc, cur, pos)
            nxt = jnp.argmax(tl[:, -1], -1).astype(i32)
            live = pos < total - 1
            tokens = jnp.where(
                live,
                jax.lax.dynamic_update_slice(tokens, nxt[:, None],
                                             (0, pos + 1)),
                tokens)
            pos = jnp.where(live, pos + 1, pos)
            steps = steps + live.astype(i32)
            return (tokens, tc, pos, steps), None

        (tokens, _, pos, tail_steps), _ = jax.lax.scan(
            t_tick, (st["tokens"], st["t_caches"], st["pos"],
                     jnp.zeros((), jnp.int32)), None, length=k)
        stats = dict(rounds=st["rounds"], accepted=st["accepted"],
                     tail_steps=tail_steps, fills=st["fills"])
        if debug_caches:
            # test hook (draft-cache-density regression): the ring-phase
            # draft cache rides out of the jitted program
            return tokens, stats, (st["d_caches"], st["pos"])
        return tokens, stats

    return jax.jit(run)


def speculative_generate_device(target: Transformer, target_params,
                                draft: Transformer, draft_params,
                                prompt: jax.Array, max_new_tokens: int,
                                k: int = 4) -> Tuple[jax.Array, dict]:
    """Greedy speculative decode as ONE compiled program (see
    :func:`_spec_device_program`) -> ``(tokens (B, P+N), stats)`` with
    the host-loop's stats schema.  Output is token-identical to
    ``generate(target, ...)`` and to the host-loop
    :func:`speculative_generate` — same acceptance rule, same commits —
    pinned by tests/test_speculative.py on trained and untrained pairs.
    Temperature/kv-quant stay on the host-loop path (the numpy
    rejection-sampling core is the pinned exactness reference)."""
    if target.cfg.vocab_size != draft.cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft.cfg.vocab_size} != target vocab "
            f"{target.cfg.vocab_size}")
    b, p = prompt.shape
    if max_new_tokens <= 0:
        return jnp.asarray(prompt, jnp.int32), {
            "target_passes": 0, "draft_steps": 0, "rounds": 0,
            "accepted_total": 0, "proposed_total": 0, "accept_rate": 0.0}
    total = p + max_new_tokens
    for name, m in (("target", target), ("draft", draft)):
        if total > m.cfg.max_seq_len:
            raise ValueError(f"prompt {p} + {max_new_tokens} exceeds "
                             f"{name} max_seq_len {m.cfg.max_seq_len}")
    k = max(1, min(int(k), max_new_tokens))
    tokens, dstats = _spec_device_program(target, draft, total, p, k, b)(
        target_params, draft_params, jnp.asarray(prompt, jnp.int32))
    rounds = int(dstats["rounds"])
    accepted = int(dstats["accepted"])
    tail = int(dstats["tail_steps"])
    fills = int(dstats["fills"])
    stats = {
        "target_passes": 1 + rounds + tail,   # prefill + verifies + tail
        # proposals + the catch-up forward per fully-accepted round (the
        # draft-KV density fill) — same accounting as the host path
        "draft_steps": k * rounds + fills,
        "rounds": rounds,
        "accepted_total": accepted,
        "proposed_total": k * rounds,
        "accept_rate": accepted / max(1, k * rounds),
        "tail_steps": tail,
    }
    return tokens, stats
