"""Speculative decoding (greedy): a small DRAFT model proposes k tokens,
the TARGET verifies all k in ONE chunked forward, and the longest agreeing
prefix is accepted plus the target's own correction token.

Why it belongs in a TPU serving stack: autoregressive decode runs one
bandwidth-bound (B, 1) step per token on the big model, while a chunked
verify runs k+1 positions through the SAME weights for nearly the same
HBM traffic as one step (weights stream once either way; the MXU eats
the extra rows).  With an accept rate a, the target pays roughly
ceil(N / (accepted-per-round)) chunk passes instead of N steps — the
classic latency lever when a cheap draft tracks the target well.

Greedy speculation is EXACT: every emitted token is argmax of the
target's logits at its position (accepted proposals by the verify
comparison, corrections directly), so the output is identical to
``generate(target, ...)`` token for token — pinned by
tests/test_speculative.py, not just asserted here.  One honest caveat:
the verify pass computes those logits in an (r+1)-wide chunk while
``generate`` uses (B, 1) steps — different XLA programs, so floats may
reassociate and a NEAR-TIE argmax can in principle flip.  Trained
models have logit margins that make this unobservable (the tests pin
bitwise equality), but UNTRAINED models' near-flat logits do flip ties
— visible as a sub-1 self-draft accept rate in the bench's mechanism
row, which is a tie-stability artifact, not a speculation bug.
(Temperature speculation needs the rejection-sampling correction of
Leviathan et al. 2023 to keep the target distribution; not implemented
— greedy is the serving mode with an exactness contract.)

Cache bookkeeping rides the same invariant as the server's bucketed
prefill: positions past the accepted point hold stale K/V from rejected
proposals, but decode masks keys ``<= pos`` and every position is
REWRITTEN by the pass that next visits it before it becomes visible, so
no rewind is ever needed — "rollback" is free.

Both models run their standard chunked forward
(``models.generate._forward_chunk``), so GQA, RoPE, SwiGLU, int8
weights, and the int8 KV cache all compose with speculation untouched.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .generate import _forward_chunk, init_kv_cache
from .transformer import Transformer


@functools.lru_cache(maxsize=64)
def _chunk_program(model: Transformer, max_len: int, chunk: int,
                   kv_quant: bool):
    """One jitted (params, caches, ids (B, chunk), pos) -> (logits,
    caches) per (model, shapes): position is TRACED, so draft steps and
    verify chunks at every position share one compiled program each."""

    def run(params, caches, ids, pos):
        return _forward_chunk(model, params, caches, ids, pos)

    return jax.jit(run)


def speculative_generate(target: Transformer, target_params,
                         draft: Transformer, draft_params,
                         prompt: jax.Array, max_new_tokens: int,
                         k: int = 4, kv_quant: bool = False
                         ) -> Tuple[jax.Array, dict]:
    """Greedy speculative decode -> ``(tokens (B, P + N), stats)``.

    ``stats`` reports ``target_passes`` (chunked verifies the target ran,
    vs ``max_new_tokens`` single steps without speculation),
    ``draft_steps``, and ``accept_rate`` (accepted_total /
    proposed_total — tail rounds propose fewer than k, so the
    denominator is what was actually proposed).  The draft must share the target's vocabulary; batch
    rows are verified in lockstep (a row's round accepts the minimum of
    its own agreement — B=1 recovers the per-stream optimum, and larger
    B trades some accept rate for batching, the standard tradeoff).
    """
    if target.cfg.vocab_size != draft.cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft.cfg.vocab_size} != target vocab "
            f"{target.cfg.vocab_size}")
    b, p = prompt.shape
    if max_new_tokens <= 0:   # mirror generate(): nothing to decode
        return jnp.asarray(prompt, jnp.int32), {
            "target_passes": 0, "draft_steps": 0, "rounds": 0,
            "accepted_total": 0, "accept_rate": 0.0}
    total = p + max_new_tokens
    for name, m in (("target", target), ("draft", draft)):
        if total > m.cfg.max_seq_len:
            raise ValueError(f"prompt {p} + {max_new_tokens} exceeds "
                             f"{name} max_seq_len {m.cfg.max_seq_len}")
    k = max(1, min(int(k), max_new_tokens))

    d_step = _chunk_program(draft, total, 1, kv_quant)
    t_caches = init_kv_cache(target, b, total, quant=kv_quant)
    d_caches = init_kv_cache(draft, b, total, quant=kv_quant)

    tokens = np.zeros((b, total), np.int32)
    tokens[:, :p] = np.asarray(prompt, np.int32)

    # prefill both models; the target's last-position argmax is token p
    t_prefill = _chunk_program(target, total, p, kv_quant)
    d_prefill = _chunk_program(draft, total, p, kv_quant)
    logits, t_caches = t_prefill(target_params, t_caches,
                                 jnp.asarray(tokens[:, :p]), 0)
    tokens[:, p] = np.asarray(jnp.argmax(logits[:, -1], -1))
    _, d_caches = d_prefill(draft_params, d_caches,
                            jnp.asarray(tokens[:, :p]), 0)

    pos = p            # index of the newest COMMITTED token
    stats = {"target_passes": 1, "draft_steps": 0, "rounds": 0,
             "accepted_total": 0, "proposed_total": 0}
    while pos < total - 1:
        r = min(k, total - 1 - pos)
        # --- draft proposes r tokens autoregressively ------------------
        proposals = np.zeros((b, r), np.int32)
        cur = tokens[:, pos]
        for i in range(r):
            dl, d_caches = d_step(draft_params, d_caches,
                                  jnp.asarray(cur[:, None]), pos + i)
            cur = np.asarray(jnp.argmax(dl[:, -1], -1), np.int32)
            proposals[:, i] = cur
            stats["draft_steps"] += 1
        # --- target verifies the r proposals in one chunk --------------
        # chunk = committed token at pos followed by the r proposals;
        # logits[i] are the target's prediction for position pos+1+i.
        # NO padding to a fixed width: a padded chunk near the sequence
        # end would write K/V past `total`, and dynamic_update_slice
        # CLAMPS the start index — silently corrupting earlier
        # positions.  The lru-cached program compiles once per distinct
        # r (k in steady state plus at most k-1 tail shapes).
        chunk = np.concatenate([tokens[:, pos:pos + 1], proposals], 1)
        vl, t_caches = _chunk_program(target, total, r + 1, kv_quant)(
            target_params, t_caches, jnp.asarray(chunk), pos)
        want = np.asarray(jnp.argmax(vl[:, :r + 1], -1), np.int32)
        # accepted prefix: proposals[i] == target argmax at that slot,
        # batch rows in lockstep (min across rows)
        agree = proposals == want[:, :r]
        n_acc = int(min((np.argmin(row) if not row.all() else r)
                        for row in agree))
        # commit accepted proposals + the target's own next token (the
        # correction slot may not EXIST when the tail round's proposals
        # were all accepted and land exactly on the last position)
        if n_acc:
            tokens[:, pos + 1:pos + 1 + n_acc] = proposals[:, :n_acc]
        if pos + 1 + n_acc < total:
            tokens[:, pos + 1 + n_acc] = want[:, n_acc]
            pos += n_acc + 1
        else:
            pos += n_acc
        stats["target_passes"] += 1
        stats["rounds"] += 1
        stats["accepted_total"] += n_acc
        stats["proposed_total"] += r
        # stale draft/target cache entries past `pos` are rewritten
        # before the mask can expose them (module docstring) — no rewind
    stats["accept_rate"] = (stats["accepted_total"]
                            / max(1, stats["proposed_total"]))
    return jnp.asarray(tokens), stats
