"""Tensor-parallel autoregressive decoding — serving SP x TP / PP x TP
checkpoints in their NATIVE layout (VERDICT r2 item 4).

The reference has no inference path at all (its dead test-eval block,
dataParallelTraining_NN_MPI.py:227-236, is the closest thing); the dense
decode path is ``models.generate``.  This module removes the last host
gather from serving: a model trained on the seq x tensor layout
(``parallel.spmd``) or the pipe x tensor layout (``parallel.pipeline``)
decodes *without* ever assembling dense replicated params —

* **Megatron blocks, incremental.**  Each tensor rank holds its head-aligned
  qkv / ff_in column shards and attn_out / ff_out row shards (the training
  layout, ``parallel.megatron``); the per-chunk forward runs attention over
  ``n_heads / tp`` LOCAL query heads against a KV cache holding
  ``kv_heads / tp`` heads (== n_heads/tp for classic multi-head; under GQA
  the grouped heads — rank-local by the contiguous permutation — stack the
  cache shrink on top of the head sharding, with RoPE rotating the local
  heads at the chunk's absolute positions), one psum per row-parallel
  matmul (no backward here, so plain ``lax.psum`` replaces the f/g
  custom-vjp pair).
* **Vocab-parallel logits + sampling.**  With ``vocab_parallel=True`` the
  head matmul produces only the LOCAL ``(B, V/tp)`` logits shard
  (``megatron.vocab_parallel_logits``); greedy decoding argmaxes across the
  shards with the pmax/pmin trick (``megatron.vocab_parallel_accuracy``'s
  tie-breaking, exact vs dense argmax), and temperature sampling uses the
  **Gumbel-max trick**: each rank draws iid Gumbel noise for its own vocab
  slice (key folded with the rank index), and the global argmax of
  ``logits/T + g`` is *exactly* one categorical sample — the full logits
  row never exists on any device.
* **Batch rows over the data axes**, same contract as
  ``generate.generate_sharded``.

Pipeline checkpoints: :func:`pipeline_params_for_decode` unstacks the
(stage, layer) block stack back to the per-layer list with plain jnp ops on
the sharded arrays — XLA moves shards device-to-device; nothing bounces
through one host — after which the params ARE the SP x TP layout (the qkv
permutation convention is shared, ``parallel.pipeline.init_pipeline_params``)
and decode proceeds here.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import megatron
from .core import LayerNorm
from .generate import _filter_logits
from .transformer import Transformer

TENSOR_AXIS = "tensor"


def init_tp_kv_cache(model: Transformer, batch: int, max_len: int, tp: int):
    """Per-layer (k, v) buffers with LOCAL heads: (B, max_len, KV/tp, Dh)
    — under GQA the cache holds this rank's kv_heads/tp grouped heads
    (the same per-rank assignment as training, megatron.qkv_tp_permutation),
    stacking the GQA cache shrink on top of the head sharding."""
    c = model.cfg
    shape = (batch, max_len, c.kv_heads // tp, c.head_dim)
    zeros = lambda: jnp.zeros(shape, c.compute_dtype)
    return [{"k": zeros(), "v": zeros()} for _ in range(c.n_layers)]


def _tp_block_chunk(cfg, lp, cache, x, pos, heads_local: int,
                    axis: str = TENSOR_AXIS, moe_ffn=None):
    """One Megatron block on a chunk (B, S, D) at position ``pos`` with the
    KV cache holding this rank's heads.  Mirrors ``generate._block_chunk``
    (dense) with ``megatron.tp_block_apply``'s sharding: column-parallel
    qkv (local layout [q_r | k_r | v_r]), local-head attention, psum after
    the row-parallel matmuls with the bias added once post-psum.

    ``moe_ffn`` (from ``parallel.expert.moe_ffn_fn`` with
    ``expert_axis=None, tensor_axis='tensor'``) replaces the dense FFN
    for MoE checkpoints: experts held whole per rank, each expert's
    hidden dim tensor-sharded — the same layout the SP x TP MoE train
    step uses, so trained expert shards decode in place."""
    cdt = cfg.compute_dtype
    ln = LayerNorm(cfg.d_model, param_dtype=cfg.param_dtype)
    h = ln.apply(lp["ln1"], x)
    qkv = (h.astype(cdt) @ lp["qkv"]["w"].astype(cdt)
           + lp["qkv"]["b"].astype(cdt))
    b, s, _ = qkv.shape
    # local layout is [q_r | k_r | v_r] (megatron.qkv_tp_permutation);
    # under GQA the k/v spans hold this rank's kv_heads/tp heads, whose
    # query-head groups are exactly this rank's (contiguous assignment)
    tp = cfg.n_heads // heads_local
    kv_local = cfg.kv_heads // tp
    q_w = heads_local * cfg.head_dim
    kv_w = kv_local * cfg.head_dim
    q = qkv[..., :q_w].reshape(b, s, heads_local, cfg.head_dim)
    k = qkv[..., q_w:q_w + kv_w].reshape(b, s, kv_local, cfg.head_dim)
    v = qkv[..., q_w + kv_w:].reshape(b, s, kv_local, cfg.head_dim)
    if cfg.pos_encoding == "rope":
        # rotation is per-head-independent, so this rank's local heads
        # rotate correctly; cached keys are stored rotated (standard)
        from ..ops.rope import rope_rotate

        chunk_pos = pos + jnp.arange(s)
        q = rope_rotate(q, chunk_pos, cfg.rope_theta)
        k = rope_rotate(k, chunk_pos, cfg.rope_theta)
    new_k = lax.dynamic_update_slice(cache["k"], k.astype(cache["k"].dtype),
                                     (0, pos, 0, 0))
    new_v = lax.dynamic_update_slice(cache["v"], v.astype(cache["v"].dtype),
                                     (0, pos, 0, 0))
    scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.head_dim, jnp.float32))
    T = cache["k"].shape[1]
    mask = (jnp.arange(T)[None, None, None, :]
            <= pos + jnp.arange(s)[None, None, :, None])
    if kv_local == heads_local:
        logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            new_k.astype(jnp.float32)) * scale
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs,
                         new_v.astype(jnp.float32)).astype(x.dtype)
    else:
        # GQA: grouped-head attention on the local cache — the repeat
        # stays virtual (an einsum batch dim), mirroring the dense
        # decode's grouped branch (models.generate._block_chunk)
        g = heads_local // kv_local
        q5 = q.reshape(b, s, kv_local, g, cfg.head_dim)
        logits = jnp.einsum("bqcgd,bkcd->bcgqk", q5.astype(jnp.float32),
                            new_k.astype(jnp.float32)) * scale
        logits = jnp.where(mask[:, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bcgqk,bkcd->bqcgd", probs,
                         new_v.astype(jnp.float32)).astype(x.dtype)
        out = out.reshape(b, s, heads_local, cfg.head_dim)
    out = out.reshape(b, s, heads_local * cfg.head_dim)
    partial = out.astype(cdt) @ lp["attn_out"]["w"].astype(cdt)
    attn = lax.psum(partial, axis) + lp["attn_out"]["b"].astype(cdt)
    x = x + attn.astype(x.dtype)
    h = ln.apply(lp["ln2"], x)
    if moe_ffn is not None:
        ff, _aux = moe_ffn(lp, h)  # load-balance aux is a training signal
        return x + ff.astype(x.dtype), {"k": new_k, "v": new_v}
    hh = megatron.tp_ffn_hidden(cfg, lp, h)
    ff = (lax.psum(hh @ lp["ff_out"]["w"].astype(cdt), axis)
          + lp["ff_out"]["b"].astype(cdt))
    return x + ff.astype(x.dtype), {"k": new_k, "v": new_v}


def _sharded_sample(logits_local, temperature: float, key,
                    axis: str = TENSOR_AXIS, top_k: int = 0) -> jax.Array:
    """One token per row from vocab-SHARDED logits (B, V/tp), exact:

    * greedy — global argmax via pmax, smallest-index tie-break via pmin
      (matches ``jnp.argmax`` on the dense row);
    * temperature — Gumbel-max: per-rank iid Gumbel noise on the local
      slice (key folded with the rank index so no two ranks share noise),
      then the same global argmax.  argmax_i(l_i/T + g_i) ~ Categorical
      (softmax(l/T)) exactly;
    * ``top_k > 0`` — the candidate set is restricted WITHOUT gathering
      the logits row: each rank takes its local top-k (at most k global
      winners can live on one shard), an all_gather of those tp*k scalars
      per row yields the global k-th value, and everything below it masks
      out before the Gumbel noise.  Matches ``generate._filter_logits``'s
      ``logits < kth -> -inf`` rule exactly (ties at the threshold kept).
    """
    v_local = logits_local.shape[-1]
    rank = lax.axis_index(axis)
    offset = rank * v_local
    scores = logits_local.astype(jnp.float32)
    if temperature > 0:
        scaled = scores / temperature
        if top_k > 0:
            k_eff = min(top_k, v_local)
            local_top = lax.top_k(scaled, k_eff)[0]          # (B, k)
            # (B, tp*k) of candidate maxima — tiny; never the logits row
            all_top = lax.all_gather(local_top, axis, axis=-1, tiled=True)
            kth = lax.top_k(all_top, top_k)[0][..., -1:]     # global k-th
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        g = jax.random.gumbel(jax.random.fold_in(key, rank),
                              scaled.shape, jnp.float32)
        scores = scaled + g
    local_max = scores.max(-1)
    global_max = lax.pmax(local_max, axis)
    local_arg = jnp.argmax(scores, axis=-1).astype(jnp.int32) + offset
    cand = jnp.where(local_max >= global_max, local_arg,
                     jnp.iinfo(jnp.int32).max)
    return lax.pmin(cand, axis)


def _full_sample(logits, temperature: float, key, top_k: int, top_p: float):
    """Sampling on full (replicated-head) logits inside the shard body:
    same math as ``generate._sample`` but with the key threaded by the
    caller (every tensor rank uses the SAME key -> identical draws, so the
    replicated token stays replicated)."""
    if temperature > 0:
        logits = _filter_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@functools.lru_cache(maxsize=32)
def _tp_decode_program(model: Transformer, mesh, max_new_tokens: int,
                       temperature: float, top_k: int, top_p: float,
                       pad_id: int, vocab_parallel: bool, ragged: bool,
                       batch_axes: Tuple[str, ...]):
    """One jitted shard_map decode program per (model, mesh, knobs)."""
    c = model.cfg
    tp = int(mesh.shape[TENSOR_AXIS])
    megatron.validate_tp(c, tp)
    heads_local = c.n_heads // tp
    if vocab_parallel and c.vocab_size % tp:
        raise ValueError(f"vocab_size={c.vocab_size} not divisible by "
                         f"tp={tp}")
    if vocab_parallel and 0.0 < top_p < 1.0:
        raise NotImplementedError(
            "top_p needs a sorted cumulative view of the full logits row; "
            "with vocab_parallel the row is never materialized — use "
            "greedy, temperature, or top_k sampling here (top_k works "
            "shard-locally + a tp*k all_gather), or decode with "
            "vocab_parallel=False (replicated head)")
    if vocab_parallel and top_k > c.vocab_size:
        raise ValueError(f"top_k={top_k} > vocab_size={c.vocab_size}")

    def embed(params, ids, positions):
        if vocab_parallel:
            return model.add_pos(
                params,
                megatron.vocab_parallel_embed(params["embed"]["table"], ids),
                positions)
        return model.embed(params, ids, positions)

    def logits_last(params, x_last):
        """(B, S, D) -> sampling-ready logits of the LAST chunk position."""
        if vocab_parallel:
            return megatron.vocab_parallel_logits(
                model.final_norm(params, x_last), params["head"]["w"],
                compute_dtype=c.compute_dtype)
        return model.head_logits(params, x_last)

    def sample(logits_2d, key):
        if vocab_parallel:
            return _sharded_sample(logits_2d, temperature, key,
                                   top_k=top_k)
        return _full_sample(logits_2d, temperature, key, top_k, top_p)

    moe_ffn = None
    if c.moe_experts > 0:
        # experts whole per rank, hidden dim tensor-sharded — the SP x TP
        # MoE layout (parallel.expert.moe_ffn_fn is the single factory the
        # train steps use too, so decode cannot drift from training)
        from ..parallel.expert import moe_ffn_fn

        moe_ffn = moe_ffn_fn(c, expert_axis=None, tensor_axis=TENSOR_AXIS)

    def forward_chunk(params, caches, ids, pos):
        positions = pos + jnp.arange(ids.shape[1])
        x = embed(params, ids, positions)
        new_caches = []
        for lp, cache in zip(params["blocks"], caches):
            x, cache = _tp_block_chunk(c, lp, cache, x, pos, heads_local,
                                       moe_ffn=moe_ffn)
            new_caches.append(cache)
        return x, new_caches

    def shard_decode(params, prompt, lens, key):
        # Independent draws per DATA shard: the key arrives shard_map-
        # replicated (in_spec P()), so without this fold identical prompts
        # in different batch shards would decode identical continuations.
        # Only the batch axes fold here — the 'tensor' axis must NOT (the
        # sampled token must stay replicated across tensor ranks; the
        # per-rank fold for vocab-sharded Gumbel noise lives inside
        # _sharded_sample).
        for a in batch_axes:
            key = jax.random.fold_in(key, lax.axis_index(a))
        b, p = prompt.shape
        total = p + max_new_tokens
        caches = init_tp_kv_cache(model, b, total, tp)
        tokens = jnp.concatenate(
            [prompt.astype(jnp.int32),
             jnp.full((b, max_new_tokens), pad_id, jnp.int32)], axis=1)

        def step(carry, pos):
            tokens, caches, key = carry
            key, sub = jax.random.split(key)
            ids_1 = lax.dynamic_slice(tokens, (0, pos), (b, 1))
            x, caches = forward_chunk(params, caches, ids_1, pos)
            nxt = sample(logits_last(params, x)[:, 0], sub)
            if ragged:
                keep = (pos + 1) < lens
                cur = lax.dynamic_slice(tokens, (0, pos + 1), (b, 1))[:, 0]
                nxt = jnp.where(keep, cur, nxt)
            tokens = lax.dynamic_update_slice(tokens, nxt[:, None],
                                              (0, pos + 1))
            return (tokens, caches, key), None

        if ragged:
            start = 0
        else:  # prefill all P prompt positions in one parallel chunk
            x, caches = forward_chunk(params, caches, tokens[:, :p], 0)
            key, sub = jax.random.split(key)
            first = sample(logits_last(params, x[:, p - 1:p])[:, 0], sub)
            tokens = lax.dynamic_update_slice(tokens, first[:, None], (0, p))
            start = p
        if start < total - 1:
            (tokens, _, _), _ = lax.scan(step, (tokens, caches, key),
                                         jnp.arange(start, total - 1))
        return tokens

    dummy = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0)))
    if c.scan_layers:
        # the caller unstacks scanned params to a per-layer list (the decode
        # walks layers with per-layer caches); mirror that here or the spec
        # tree cannot match the param tree
        dummy = dict(dummy)
        dummy["blocks"] = [
            jax.tree_util.tree_map(
                lambda x, i=i: jax.ShapeDtypeStruct(x.shape[1:], x.dtype),
                dummy["blocks"])
            for i in range(c.n_layers)
        ]
    from ..parallel.spmd import sp_tp_param_specs

    pspecs = sp_tp_param_specs(dummy, vocab_parallel)
    rows = P(batch_axes)
    mapped = jax.shard_map(
        shard_decode, mesh=mesh,
        in_specs=(pspecs, rows, rows if ragged else P(), P()),
        out_specs=rows,
        check_vma=False,
    )
    return jax.jit(mapped), pspecs, rows


def generate_tp(model: Transformer, params, prompt, mesh,
                max_new_tokens: int, *, temperature: float = 0.0,
                top_k: int = 0, top_p: float = 1.0,
                key: Optional[jax.Array] = None,
                prompt_lens: Optional[jax.Array] = None,
                pad_id: int = 0, vocab_parallel: bool = False,
                batch_axes: Tuple[str, ...] = ("data",)) -> jax.Array:
    """Decode ``max_new_tokens`` after ``prompt`` (B, P) -> (B, P + N) with
    ``params`` in the NATIVE seq x tensor training layout (per-layer
    blocks, head-aligned qkv permutation, qkv/ff_in column- and
    attn_out/ff_out row-sharded over 'tensor'; MoE expert stacks whole
    per rank with their hidden dims tensor-sharded; embed/head
    vocab-sharded when ``vocab_parallel``).  No host gather, no dense
    param copy.

    Sampling knobs as in ``generate.generate``; with ``vocab_parallel``,
    greedy, temperature, and top_k are available (top_k restricts the
    candidate set via local top-k + a tp*k all_gather of scalars — the
    full logits row is still never materialized); top_p would need a
    sorted cumulative view of the whole row and is rejected.  ``prompt``
    rows shard over ``batch_axes`` (axes absent from the mesh are
    ignored).
    """
    c = model.cfg
    b, p = prompt.shape
    if p + max_new_tokens > c.max_seq_len:
        raise ValueError(f"prompt {p} + {max_new_tokens} new tokens exceeds "
                         f"max_seq_len {c.max_seq_len}")
    if temperature > 0 and key is None:
        raise ValueError("temperature sampling needs a PRNG key")
    if max_new_tokens == 0:
        return jnp.asarray(prompt, jnp.int32)
    if c.scan_layers:
        # per-layer caches need per-layer params; unstack the scanned
        # leaves (slices of the same buffers — no copy under jit)
        params = dict(params)
        stacked = params["blocks"]
        params["blocks"] = [
            jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
            for i in range(c.n_layers)
        ]
    axes = tuple(a for a in batch_axes if a in mesh.shape)
    n = 1
    for a in axes:
        n *= int(mesh.shape[a])
    if b % n:
        raise ValueError(f"prompt batch {b} not divisible by the {axes} "
                         f"axes product {n}")
    ragged = prompt_lens is not None
    run, pspecs, rows = _tp_decode_program(
        model, mesh, max_new_tokens, temperature, top_k, top_p, pad_id,
        vocab_parallel, ragged, axes)
    params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params,
        pspecs)
    prompt = jax.device_put(jnp.asarray(prompt, jnp.int32),
                            NamedSharding(mesh, rows))
    if ragged:
        prompt_lens = jax.device_put(jnp.asarray(prompt_lens, jnp.int32),
                                     NamedSharding(mesh, rows))
    else:
        prompt_lens = jnp.zeros((), jnp.int32)  # unused placeholder
    if key is None:
        key = jax.random.PRNGKey(0)
    return run(params, prompt, prompt_lens, key)


def pipeline_params_for_decode(params, model: Transformer,
                               qkv_tp: Optional[int] = None,
                               decode_tp: Optional[int] = None):
    """(stage, layer)-stacked pipeline params (plain or interleaved — the
    stack depth is inferred from the leaf ndim) -> the per-layer list
    layout :func:`generate_tp` consumes.  Plain jnp ops on the sharded
    arrays: XLA reshards device-to-device (the pipe-sharded stack
    redistributes to the tensor/replicated decode placement inside
    ``generate_tp``'s device_put); no single-host gather
    (``Trainer._eval_params``) on the path.

    The qkv head-alignment convention is shared between the pipeline and
    sp_tp layouts, but the column *permutation* is tp-DEGREE-dependent:
    a checkpoint permuted for tp=2 decoded on a tensor=4 mesh would emit
    silently wrong tokens.  Pass ``qkv_tp`` (the checkpoint meta's value,
    as ``cli._dense_decode_params`` does) and ``decode_tp``
    (``mesh.shape['tensor']`` of the decode mesh): when they differ the
    blocks are re-permuted (inverse of the saved permutation, then the
    decode mesh's).  Omitting either keeps the historical same-degree
    assumption — only safe when caller guarantees the degrees match."""
    from ..parallel import megatron
    from ..parallel.pipeline import dense_layer_blocks

    out = dict(params)
    if (qkv_tp is not None and decode_tp is not None
            and int(qkv_tp) != int(decode_tp)):
        # undo the saved permutation via the one place that owns that rule
        # (dense_layer_blocks, parallel/pipeline.py), then re-permute for
        # the decode mesh's degree
        c = model.cfg
        out["blocks"] = dense_layer_blocks(params["blocks"], c,
                                           saved_tp=int(qkv_tp))
        if int(decode_tp) > 1:
            out["blocks"] = megatron.permute_qkv(
                out["blocks"], c.d_model, c.n_heads, int(decode_tp),
                kv_heads=c.kv_heads)
    else:
        # degrees match (or caller vouches): keep the head-aligned
        # permutation — generate_tp consumes the NATIVE tp layout; only
        # the stacking is flattened here
        out["blocks"] = dense_layer_blocks(params["blocks"])
    n_layers = model.cfg.n_layers
    if (not isinstance(out["blocks"], list)
            or len(out["blocks"]) != n_layers):
        raise ValueError(
            f"expected a stacked pipeline blocks pytree flattening to "
            f"{n_layers} layers; got "
            f"{type(params['blocks']).__name__} -> "
            f"{len(out['blocks']) if isinstance(out['blocks'], list) else 'non-list'}")
    return out
