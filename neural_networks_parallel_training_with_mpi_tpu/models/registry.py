"""Model construction from config (the 'model zoo' front door)."""

from __future__ import annotations

import jax.numpy as jnp

from ..config import ModelConfig
from .convnet import ConvNet
from .mlp import MLP
from .core import Module
from .transformer import Transformer, TransformerConfig

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


def build_model(cfg: ModelConfig) -> Module:
    pdt = _DTYPES[cfg.dtype]
    cdt = _DTYPES[cfg.compute_dtype]
    if cfg.arch == "mlp":
        return MLP(in_features=cfg.in_features, hidden=tuple(cfg.hidden),
                   out_features=cfg.out_features, activation=cfg.activation,
                   param_dtype=pdt, compute_dtype=cdt)
    if cfg.arch == "convnet":
        return ConvNet(in_channels=cfg.in_channels, channels=tuple(cfg.channels),
                       image_hw=tuple(cfg.image_hw), n_classes=cfg.out_features,
                       activation=cfg.activation, param_dtype=pdt,
                       compute_dtype=cdt)
    if cfg.arch == "transformer":
        tc = TransformerConfig(
            vocab_size=cfg.vocab_size, max_seq_len=cfg.max_seq_len,
            n_layers=cfg.n_layers, d_model=cfg.d_model, n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_kv_heads or None,
            pos_encoding=cfg.pos_encoding,
            activation=cfg.ffn_activation,
            d_ff=cfg.d_ff, attention=cfg.attention, param_dtype=pdt,
            compute_dtype=cdt, remat=cfg.remat,
            remat_policy=cfg.remat_policy,
            moe_experts=cfg.moe_experts,
            moe_expert_axis=cfg.moe_expert_axis,
            moe_capacity_factor=cfg.moe_capacity_factor,
            moe_top_k=cfg.moe_top_k,
            ce_chunk=cfg.ce_chunk,
            matmul_dtype=cfg.matmul_dtype,
            matmul_skip=tuple(cfg.matmul_skip),
            scan_layers=cfg.scan_layers)
        return Transformer(tc)
    raise ValueError(f"unknown arch {cfg.arch!r}")
