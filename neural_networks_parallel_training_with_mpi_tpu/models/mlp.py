"""MLP family.

``reference_mlp()`` is the parity model: the reference's
``nn.Sequential(nn.Linear(2,3), nn.ReLU(), nn.Linear(3,1))``
(dataParallelTraining_NN_MPI.py:41-45) — 13 scalar params in 4 tensors
(SURVEY.md §3.2).  ``MLP`` generalizes it for the wide-MLP and MNIST
BASELINE.json configs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Tuple

import jax.numpy as jnp

from .core import Activation, Linear, Module, Sequential


def _build_layers(in_features: int, hidden: Tuple[int, ...], out_features: int,
                  activation: str, param_dtype, compute_dtype) -> Tuple[Module, ...]:
    layers = []
    prev = in_features
    for h in hidden:
        layers.append(Linear(prev, h, param_dtype=param_dtype,
                             compute_dtype=compute_dtype))
        layers.append(Activation(activation))
        prev = h
    layers.append(Linear(prev, out_features, param_dtype=param_dtype,
                         compute_dtype=compute_dtype))
    return tuple(layers)


@dataclass(frozen=True)
class MLP(Module):
    in_features: int = 2
    hidden: Tuple[int, ...] = (3,)
    out_features: int = 1
    activation: str = "relu"
    param_dtype: Any = jnp.float32
    compute_dtype: Optional[Any] = None

    @property
    def net(self) -> Sequential:
        return Sequential(_build_layers(self.in_features, tuple(self.hidden),
                                        self.out_features, self.activation,
                                        self.param_dtype, self.compute_dtype))

    def init(self, key):
        return self.net.init(key)

    def apply(self, params, x, **kwargs):
        return self.net.apply(params, x, **kwargs)

    def fwd_flops(self, x_shape):
        dims = (self.in_features,) + tuple(self.hidden) + (self.out_features,)
        batch = 1
        for s in x_shape[:-1]:
            batch *= s
        return float(2 * batch * sum(a * b for a, b in zip(dims, dims[1:])))


def reference_mlp(param_dtype=jnp.float32) -> MLP:
    """The reference's exact architecture: 2 -> 3 (ReLU) -> 1."""
    return MLP(in_features=2, hidden=(3,), out_features=1, activation="relu",
               param_dtype=param_dtype)


def wide_mlp(in_features: int = 2, width: int = 512, depth: int = 4,
             out_features: int = 1, param_dtype=jnp.float32,
             compute_dtype=None) -> MLP:
    """BASELINE.json config #2: 4x512 regression MLP to stress the gradient
    allreduce."""
    return MLP(in_features=in_features, hidden=(width,) * depth,
               out_features=out_features, param_dtype=param_dtype,
               compute_dtype=compute_dtype)


def mnist_mlp(param_dtype=jnp.float32, compute_dtype=None) -> MLP:
    """BASELINE.json config #3: 784 -> 256 -> 128 -> 10 classifier."""
    return MLP(in_features=784, hidden=(256, 128), out_features=10,
               param_dtype=param_dtype, compute_dtype=compute_dtype)
