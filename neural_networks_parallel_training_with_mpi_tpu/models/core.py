"""Minimal pure-functional module system.

Models are ``(init, apply)`` pairs over plain pytrees (nested dicts/lists of
``jax.Array``), the closest TPU-native analogue of the reference's 13-param
``nn.Module`` (dataParallelTraining_NN_MPI.py:35-51) without dragging in a
framework: parameters are first-class pytrees, so sharding annotations,
``jax.grad``, ``shard_map`` and optimizers compose with no extraction step
(the reference must pull ``param.grad`` tensors out into a list to
communicate them, :179-182 — here the pytree *is* the interface).

Weight init follows torch's ``nn.Linear``/``nn.Conv2d`` resets (Kaiming
uniform with a=sqrt(5), i.e. U(+-1/sqrt(fan_in)) for both weight and bias) so
models are distributionally faithful to the reference; init is deterministic
from a ``jax.random`` key (fixing the reference's misleading seeding, bug B5:
``torch.manual_seed(rank)`` runs only on rank 0, :66-69).
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Pytree = Any


class Module:
    """Protocol: ``init(key) -> params`` and ``apply(params, x, **kw) -> y``.

    Subclasses are frozen dataclasses (hashable, safe as jit static args).
    """

    def init(self, key: jax.Array) -> Pytree:
        raise NotImplementedError

    def apply(self, params: Pytree, x: jax.Array, **kwargs) -> jax.Array:
        raise NotImplementedError

    def __call__(self, params: Pytree, x: jax.Array, **kwargs) -> jax.Array:
        return self.apply(params, x, **kwargs)

    def n_params(self, key: Optional[jax.Array] = None) -> int:
        params = self.init(key if key is not None else jax.random.PRNGKey(0))
        return sum(p.size for p in jax.tree_util.tree_leaves(params))

    def fwd_flops(self, x_shape: Tuple[int, ...]) -> Optional[float]:
        """Matmul/conv FLOPs of one forward pass on a batch of shape
        ``x_shape`` (2 x MACs; elementwise ops excluded — they are noise
        next to the matmuls on the MXU).  None = unaccounted architecture.
        One optimizer step is conventionally ``3 x fwd_flops`` (forward +
        ~2x for the backward).  Single source for bench.py's MFU and the
        Trainer's achieved-FLOPs metric."""
        return None


def _uniform(key: jax.Array, shape: Tuple[int, ...], bound: float,
             dtype: jnp.dtype) -> jax.Array:
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


ACTIVATIONS: Dict[str, Callable[[jax.Array], jax.Array]] = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "silu": jax.nn.silu,
    "identity": lambda x: x,
}


@dataclass(frozen=True)
class Activation(Module):
    """Parameter-free activation (reference's ``nn.ReLU()``, :43)."""

    name: str = "relu"

    def init(self, key: jax.Array) -> Pytree:
        return {}

    def apply(self, params: Pytree, x: jax.Array, **kwargs) -> jax.Array:
        return ACTIVATIONS[self.name](x)


@dataclass(frozen=True)
class Linear(Module):
    """Dense layer ``y = x @ W + b`` (reference's ``nn.Linear``, :42/:44).

    Stored as ``W: (in, out)`` — the natural layout for ``x @ W`` on the MXU
    (torch stores the transpose).  ``compute_dtype`` casts inputs/params for
    the matmul (bfloat16 on TPU) while params stay in ``param_dtype``.
    """

    in_features: int
    out_features: int
    use_bias: bool = True
    param_dtype: Any = jnp.float32
    compute_dtype: Optional[Any] = None
    # quantized-matmul seam (ops.qmm): 'bf16' = the plain path below,
    # byte-identical to the pre-seam layer; 'int8'/'fp8' run the
    # contraction in the quantized domain (training: custom_vjp qdot;
    # serving: a true int8 activation dot against ops.quant PTQ weights).
    # q_role names this layer's fp8 amax-history slot (delayed scaling).
    matmul_dtype: str = "bf16"
    q_role: str = ""

    def init(self, key: jax.Array) -> Pytree:
        wkey, bkey = jax.random.split(key)
        bound = 1.0 / math.sqrt(self.in_features)
        params = {"w": _uniform(wkey, (self.in_features, self.out_features),
                                bound, self.param_dtype)}
        if self.use_bias:
            params["b"] = _uniform(bkey, (self.out_features,), bound,
                                   self.param_dtype)
        return params

    def apply(self, params: Pytree, x: jax.Array,
              qscales=None, qobserved=None, **kwargs) -> jax.Array:
        cdt = self.compute_dtype or x.dtype
        fmt = self.matmul_dtype
        if fmt == "int8" and "w_scale" in params:
            # serving: ops.quant PTQ weights + the quantized-compute seam
            # — dynamic per-token activation scales, int8 x int8 -> int32
            # on the MXU, both scales folded on the output tile (the
            # dequant-then-bf16-dot below was the bandwidth half only)
            from ..ops import qmm

            y = qmm.int8_serve_dot(x.astype(cdt), params["w"],
                                   params["w_scale"]).astype(cdt)
        elif fmt == "fp8" and "w_scale" in params:
            # refuse at the dispatch site, not only in the CLI: fp8 qdot
            # needs float kernels, and silently falling through to the
            # dequant matmul would mislabel every non-CLI caller's run
            raise ValueError(
                "matmul_dtype='fp8' cannot run over int8 PTQ kernels "
                "(params carry w_scale); use matmul_dtype='int8' for "
                "true int8 compute or 'bf16' for the dequant path")
        elif fmt in ("int8", "fp8"):
            from ..ops import qmm

            a_amax = None
            if fmt == "fp8" and qscales is not None and self.q_role:
                a_amax = qscales.get(self.q_role)
            if fmt == "fp8" and qobserved is not None and self.q_role:
                # calibration observation (stop-gradient amax); max-merged
                # across layers sharing this role
                prev = qobserved.get(self.q_role)
                obs = qmm.tensor_amax(x)
                qobserved[self.q_role] = (obs if prev is None
                                          else jnp.maximum(prev, obs))
            y = qmm.qdot(x.astype(cdt), params["w"],
                         fmt=fmt, scales=a_amax).astype(cdt)
        else:
            y = jnp.matmul(x.astype(cdt), params["w"].astype(cdt))
            if "w_scale" in params:
                # weights-only int8 (ops.quant.quantize_params): w is int8,
                # cast in-register for a bf16 MXU matmul, and the per-output-
                # channel scale commutes through the contraction — one fused
                # multiply on the output tile, half the HBM bytes per token
                # on the bandwidth-bound decode path
                y = y * params["w_scale"].astype(cdt)
        if self.use_bias:
            y = y + params["b"].astype(cdt)
        return y


@dataclass(frozen=True)
class Sequential(Module):
    """Chain of modules (reference's ``nn.Sequential``, :41-45).  Params are
    a list aligned with the layer tuple."""

    layers: Tuple[Module, ...]

    def init(self, key: jax.Array) -> Pytree:
        keys = jax.random.split(key, max(len(self.layers), 1))
        return [layer.init(k) for layer, k in zip(self.layers, keys)]

    def apply(self, params: Pytree, x: jax.Array, **kwargs) -> jax.Array:
        for layer, p in zip(self.layers, params):
            x = layer.apply(p, x, **kwargs)
        return x


@dataclass(frozen=True)
class Conv2D(Module):
    """NHWC conv for the CIFAR ConvNet (BASELINE.json config #4).  NHWC +
    HWIO is XLA's preferred TPU layout."""

    in_channels: int
    out_channels: int
    kernel: int = 3
    stride: int = 1
    padding: str = "SAME"
    use_bias: bool = True
    param_dtype: Any = jnp.float32

    def init(self, key: jax.Array) -> Pytree:
        wkey, bkey = jax.random.split(key)
        fan_in = self.in_channels * self.kernel * self.kernel
        bound = 1.0 / math.sqrt(fan_in)
        params = {"w": _uniform(
            wkey, (self.kernel, self.kernel, self.in_channels, self.out_channels),
            bound, self.param_dtype)}
        if self.use_bias:
            params["b"] = _uniform(bkey, (self.out_channels,), bound,
                                   self.param_dtype)
        return params

    def apply(self, params: Pytree, x: jax.Array, **kwargs) -> jax.Array:
        y = jax.lax.conv_general_dilated(
            x, params["w"].astype(x.dtype),
            window_strides=(self.stride, self.stride),
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.use_bias:
            y = y + params["b"].astype(y.dtype)
        return y


@dataclass(frozen=True)
class LayerNorm(Module):
    dim: int
    eps: float = 1e-5
    param_dtype: Any = jnp.float32

    def init(self, key: jax.Array) -> Pytree:
        return {"scale": jnp.ones((self.dim,), self.param_dtype),
                "bias": jnp.zeros((self.dim,), self.param_dtype)}

    def apply(self, params: Pytree, x: jax.Array, **kwargs) -> jax.Array:
        x32 = x.astype(jnp.float32)
        mean = x32.mean(-1, keepdims=True)
        var = x32.var(-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)
        return y.astype(x.dtype)


@dataclass(frozen=True)
class Embedding(Module):
    vocab_size: int
    dim: int
    param_dtype: Any = jnp.float32

    def init(self, key: jax.Array) -> Pytree:
        return {"table": jax.random.normal(key, (self.vocab_size, self.dim),
                                           self.param_dtype)}

    def apply(self, params: Pytree, ids: jax.Array, **kwargs) -> jax.Array:
        return jnp.take(params["table"], ids, axis=0)


@dataclass(frozen=True)
class Flatten(Module):
    def init(self, key: jax.Array) -> Pytree:
        return {}

    def apply(self, params: Pytree, x: jax.Array, **kwargs) -> jax.Array:
        return x.reshape(x.shape[0], -1)


@dataclass(frozen=True)
class AvgPool2D(Module):
    window: int = 2
    stride: Optional[int] = None

    def init(self, key: jax.Array) -> Pytree:
        return {}

    def apply(self, params: Pytree, x: jax.Array, **kwargs) -> jax.Array:
        s = self.stride or self.window
        return jax.lax.reduce_window(
            x, 0.0, jax.lax.add, (1, self.window, self.window, 1),
            (1, s, s, 1), "VALID") / float(self.window * self.window)

_REMAT_POLICIES = {
    # what jax.checkpoint may SAVE between forward and backward:
    "full": None,  # nothing — recompute the whole block (max HBM saving)
    "dots": "dots_saveable",  # keep matmul outputs (skip re-running the MXU)
    "dots_no_batch": "dots_with_no_batch_dims_saveable",
}


def make_remat(policy: str = "full"):
    """``jax.checkpoint`` bound to a named save policy (config
    ``--remat_policy``) — the HBM <-> recompute-FLOPs dial every
    block-remat site shares, so the policy vocabulary cannot drift
    between the DP/SP, SP x TP, EP x TP and pipeline paths."""
    try:
        name = _REMAT_POLICIES[policy]
    except KeyError:
        raise ValueError(f"unknown remat policy {policy!r}; have "
                         f"{sorted(_REMAT_POLICIES)}") from None
    if name is None:
        return jax.checkpoint
    pol = getattr(jax.checkpoint_policies, name)
    return lambda fn: jax.checkpoint(fn, policy=pol)
