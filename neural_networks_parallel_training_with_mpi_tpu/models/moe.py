"""Mixture-of-Experts feed-forward layer (Switch-style top-1 routing).

The reference has no MoE (SURVEY.md §2.2: expert parallelism "not required")
— this is a TPU-native capability layered on top of parity, built the way
MoE maps onto XLA rather than onto per-process MPI alltoallv:

* **Static shapes everywhere.**  Routing is expressed as dense one-hot
  dispatch/combine tensors with a fixed per-expert capacity ``C`` — the
  einsum formulation of GShard/Switch — so XLA sees only matmuls, never
  data-dependent gather sizes.  Tokens overflowing an expert's capacity are
  dropped (contribute zero), the standard trade.
* **Expert parallelism is one pair of `lax.all_to_all`s.**  With experts
  sharded over the mesh's 'expert' axis, the locally-dispatched slot tensor
  ``(E, C, d)`` is exchanged so each device receives every peer's slots for
  its own experts, runs its expert FFNs as one batched einsum on the MXU,
  and the reverse all_to_all brings results home (parallel.expert wires the
  train step).
* **Load balancing** is the Switch aux loss ``E * Σ_e f_e · p_e`` (fraction
  of tokens routed to e times mean router prob for e), returned alongside
  the output for the trainer to weight.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .core import ACTIVATIONS, Module, Pytree, _uniform


@dataclass(frozen=True)
class MoEFFN(Module):
    """Top-1 gated mixture of ``n_experts`` two-layer FFNs.

    ``expert_axis`` selects the execution path:
    * ``None`` — dense: every device holds all experts (or there is one
      device); pure einsum, no collectives.
    * an axis name — expert-parallel: expert params are sharded over that
      mesh axis (leading expert dim), and apply() must run inside a
      ``shard_map`` that binds the axis; slots travel by all_to_all.

    ``tensor_axis`` additionally Megatron-shards every expert's FFN over
    that mesh axis: the local ``w_in``/``b_in`` hold a column slice
    (E_local, d, f/tp) of the hidden units, ``w_out`` the matching row
    slice (E_local, f/tp, d), and the row-parallel output is psum'd over
    the axis before ``b_out`` (replicated) is added — GShard's
    expert + model parallelism.  Activations entering apply() must be
    replicated over ``tensor_axis`` (parallel.expert's EP x TP step wires
    the f/g conjugate ops so the backward collective is explicit).

    ``capacity`` is the per-routing-group per-expert slot count; default
    ``ceil(capacity_factor * group_tokens / n_experts)``.
    """

    d_model: int
    d_ff: int
    n_experts: int
    capacity_factor: float = 1.25
    capacity: Optional[int] = None
    activation: str = "gelu"
    expert_axis: Optional[str] = None
    tensor_axis: Optional[str] = None
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    # 1 = Switch top-1 (combine weight = the chosen expert's raw prob);
    # k>1 = GShard-style top-k (weights = the top-k probs renormalized,
    # rank-0 choices claim expert queue slots before rank-1, etc.)
    router_top_k: int = 1

    def init(self, key: jax.Array) -> Pytree:
        kg, k1, k2, k3, k4, k5, k6 = jax.random.split(key, 7)
        e, d, f = self.n_experts, self.d_model, self.d_ff
        bd, bf = 1.0 / math.sqrt(d), 1.0 / math.sqrt(f)
        experts = {
            "w_in": _uniform(k1, (e, d, f), bd, self.param_dtype),
            "b_in": _uniform(k2, (e, f), bd, self.param_dtype),
            "w_out": _uniform(k3, (e, f, d), bf, self.param_dtype),
            "b_out": _uniform(k4, (e, d), bf, self.param_dtype),
        }
        if self.activation == "swiglu":
            # gated experts (round 4): silu(x W_gate) * (x W_in) per
            # expert — same column layout as w_in/b_in, so the tensor-
            # sharding spec and the EP dispatch treat it identically
            experts["w_gate"] = _uniform(k5, (e, d, f), bd,
                                         self.param_dtype)
            experts["b_gate"] = _uniform(k6, (e, f), bd, self.param_dtype)
        return {
            "gate": {"w": _uniform(kg, (d, e), bd, self.param_dtype)},
            "experts": experts,
        }

    # ---- routing -------------------------------------------------------

    def __post_init__(self):
        if not 1 <= self.router_top_k <= self.n_experts:
            raise ValueError(
                f"router_top_k must be in [1, n_experts={self.n_experts}], "
                f"got {self.router_top_k}")

    def _capacity(self, n_tokens: int) -> int:
        if self.capacity is not None:
            return self.capacity
        # top-k demand is k assignments per token (GShard scales capacity
        # by k; without this, default top-2 would drop >= 37% of
        # assignments even under perfectly uniform load)
        return max(1, math.ceil(self.capacity_factor * self.router_top_k
                                * n_tokens / self.n_experts))

    @staticmethod
    def _assign_slots(onehot: jax.Array, cap: int, counts: jax.Array):
        """Queue positions for one choice rank: each token's 0-based slot in
        its expert's queue, offset by ``counts`` (slots already claimed by
        earlier ranks).  Returns ((N, E, C) dispatch mask, updated counts)."""
        pos = (jnp.cumsum(onehot, axis=0) - 1.0
               + counts[None, :]) * onehot               # (N, E)
        pos_tok = pos.sum(-1)                            # (N,)
        keep = (pos_tok < cap) & (onehot.sum(-1) > 0)
        slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), cap,
                              dtype=jnp.float32)         # (N, C)
        mask = (onehot[:, :, None] * slot[:, None, :]
                * keep[:, None, None].astype(jnp.float32))
        return mask, counts + onehot.sum(0)

    def _route(self, gate_params: Pytree, x: jax.Array, cap: int):
        """x: (N, d) -> dispatch (N, E, C) bool-ish, combine (N, E, C),
        aux scalar."""
        e, k = self.n_experts, self.router_top_k
        logits = jnp.matmul(x.astype(jnp.float32),
                            gate_params["w"].astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)            # (N, E)
        counts = jnp.zeros((e,), jnp.float32)
        if k == 1:
            # Switch: combine weight = the chosen expert's RAW probability
            onehot = jax.nn.one_hot(jnp.argmax(probs, axis=-1), e,
                                    dtype=jnp.float32)
            gate_val = (probs * onehot).sum(-1)            # (N,)
            dispatch, _ = self._assign_slots(onehot, cap, counts)
            combine = dispatch * gate_val[:, None, None]
            top1 = onehot
        else:
            # GShard-style top-k: weights are the top-k probs renormalized;
            # rank r claims expert queue slots after ranks < r (dropped
            # tokens still consume their attempted position — keeps slot
            # assignment one cumsum per rank instead of data-dependent)
            top_p, top_i = jax.lax.top_k(probs, k)         # (N, k)
            weights = top_p / jnp.maximum(
                top_p.sum(-1, keepdims=True), 1e-9)
            dispatch = jnp.zeros((x.shape[0], e, cap), jnp.float32)
            combine = jnp.zeros_like(dispatch)
            for r in range(k):
                onehot = jax.nn.one_hot(top_i[:, r], e, dtype=jnp.float32)
                mask, counts = self._assign_slots(onehot, cap, counts)
                dispatch = dispatch + mask
                combine = combine + mask * weights[:, r][:, None, None]
                if r == 0:
                    top1 = onehot
        # load-balance loss on the primary assignment (Switch / GShard
        # convention): E * sum_e f_e * p_e  (1.0 when uniform)
        f_e = top1.mean(0)
        p_e = probs.mean(0)
        aux = e * jnp.sum(f_e * p_e)
        return dispatch, combine, aux

    # ---- expert compute ------------------------------------------------

    def _experts_ffn(self, ep: Pytree, slots: jax.Array) -> jax.Array:
        """slots: (E_local, S, d) -> (E_local, S, d); one batched einsum
        pair per layer — E_local independent matmuls tiled onto the MXU.

        With ``tensor_axis``, the local ``w_in``/``b_in``/``w_out`` hold
        Megatron column/row shards (hidden dim f/tp) and the row-parallel
        partial output is psum'd over the axis before the replicated
        ``b_out``; the f operator at entry makes the backward psum of the
        input-cotangents explicit (megatron.make_megatron_ops)."""
        cdt = self.compute_dtype
        if self.tensor_axis is not None:
            from ..parallel.megatron import make_megatron_ops

            f, g = make_megatron_ops(self.tensor_axis)
            slots = f(slots)
        h = jnp.einsum("esd,edf->esf", slots.astype(cdt),
                       ep["w_in"].astype(cdt))
        if "w_in_scale" in ep:
            # weights-only int8 experts (ops.quant): per-(expert, column)
            # scale folded into the einsum output BEFORE bias/activation
            h = h * ep["w_in_scale"][:, None, :].astype(cdt)
        h = h + ep["b_in"][:, None, :].astype(cdt)
        if self.activation == "swiglu":
            # gated experts: the gate shares w_in's column layout, so
            # under tensor sharding the local gated product is the local
            # shard of the global one (same argument as the dense TP FFN)
            gate = jnp.einsum("esd,edf->esf", slots.astype(cdt),
                              ep["w_gate"].astype(cdt))
            if "w_gate_scale" in ep:
                gate = gate * ep["w_gate_scale"][:, None, :].astype(cdt)
            gate = gate + ep["b_gate"][:, None, :].astype(cdt)
            h = jax.nn.silu(gate) * h
        else:
            h = ACTIVATIONS[self.activation](h)
        out = jnp.einsum("esf,efd->esd", h, ep["w_out"].astype(cdt))
        if "w_out_scale" in ep:
            out = out * ep["w_out_scale"][:, None, :].astype(cdt)
        if self.tensor_axis is not None:
            out = g(out)
        return out + ep["b_out"][:, None, :].astype(cdt)

    def apply(self, params: Pytree, x: jax.Array, **kwargs
              ) -> Tuple[jax.Array, jax.Array]:
        """x: (..., d_model) -> (y, aux).  Leading dims are flattened into
        the token axis for routing."""
        lead = x.shape[:-1]
        d = x.shape[-1]
        toks = x.reshape(-1, d)
        n = toks.shape[0]
        cap = self._capacity(n)
        dispatch, combine, aux = self._route(params["gate"], toks, cap)
        cdt = self.compute_dtype
        slots = jnp.einsum("nec,nd->ecd", dispatch.astype(cdt),
                           toks.astype(cdt))               # (E, C, d)
        if self.expert_axis is None:
            out = self._experts_ffn(params["experts"], slots)
        else:
            # (E, C, d) -> exchange -> (E_local, ep*C, d): each device
            # gathers every peer's slots for the experts it owns
            slots = lax.all_to_all(slots, self.expert_axis,
                                   split_axis=0, concat_axis=1, tiled=True)
            out = self._experts_ffn(params["experts"], slots)
            out = lax.all_to_all(out, self.expert_axis,
                                 split_axis=1, concat_axis=0, tiled=True)
        y = jnp.einsum("nec,ecd->nd", combine.astype(cdt), out)
        return y.reshape(*lead, d).astype(cdt), aux
