"""Mixture-of-Experts feed-forward layer (Switch-style top-1 routing).

The reference has no MoE (SURVEY.md §2.2: expert parallelism "not required")
— this is a TPU-native capability layered on top of parity, built the way
MoE maps onto XLA rather than onto per-process MPI alltoallv:

* **Static shapes everywhere.**  Routing is expressed as dense one-hot
  dispatch/combine tensors with a fixed per-expert capacity ``C`` — the
  einsum formulation of GShard/Switch — so XLA sees only matmuls, never
  data-dependent gather sizes.  Tokens overflowing an expert's capacity are
  dropped (contribute zero), the standard trade.
* **Expert parallelism is one pair of `lax.all_to_all`s.**  With experts
  sharded over the mesh's 'expert' axis, the locally-dispatched slot tensor
  ``(E, C, d)`` is exchanged so each device receives every peer's slots for
  its own experts, runs its expert FFNs as one batched einsum on the MXU,
  and the reverse all_to_all brings results home (parallel.expert wires the
  train step).
* **Load balancing** is the Switch aux loss ``E * Σ_e f_e · p_e`` (fraction
  of tokens routed to e times mean router prob for e), returned alongside
  the output for the trainer to weight.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .core import ACTIVATIONS, Module, Pytree, _uniform


@dataclass(frozen=True)
class MoEFFN(Module):
    """Top-1 gated mixture of ``n_experts`` two-layer FFNs.

    ``expert_axis`` selects the execution path:
    * ``None`` — dense: every device holds all experts (or there is one
      device); pure einsum, no collectives.
    * an axis name — expert-parallel: expert params are sharded over that
      mesh axis (leading expert dim), and apply() must run inside a
      ``shard_map`` that binds the axis; slots travel by all_to_all.

    ``capacity`` is the per-routing-group per-expert slot count; default
    ``ceil(capacity_factor * group_tokens / n_experts)``.
    """

    d_model: int
    d_ff: int
    n_experts: int
    capacity_factor: float = 1.25
    capacity: Optional[int] = None
    activation: str = "gelu"
    expert_axis: Optional[str] = None
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32

    def init(self, key: jax.Array) -> Pytree:
        kg, k1, k2, k3, k4 = jax.random.split(key, 5)
        e, d, f = self.n_experts, self.d_model, self.d_ff
        bd, bf = 1.0 / math.sqrt(d), 1.0 / math.sqrt(f)
        return {
            "gate": {"w": _uniform(kg, (d, e), bd, self.param_dtype)},
            "experts": {
                "w_in": _uniform(k1, (e, d, f), bd, self.param_dtype),
                "b_in": _uniform(k2, (e, f), bd, self.param_dtype),
                "w_out": _uniform(k3, (e, f, d), bf, self.param_dtype),
                "b_out": _uniform(k4, (e, d), bf, self.param_dtype),
            },
        }

    # ---- routing -------------------------------------------------------

    def _capacity(self, n_tokens: int) -> int:
        if self.capacity is not None:
            return self.capacity
        return max(1, math.ceil(self.capacity_factor * n_tokens
                                / self.n_experts))

    def _route(self, gate_params: Pytree, x: jax.Array, cap: int):
        """x: (N, d) -> dispatch (N, E, C) bool-ish, combine (N, E, C),
        aux scalar."""
        e = self.n_experts
        logits = jnp.matmul(x.astype(jnp.float32),
                            gate_params["w"].astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)            # (N, E)
        expert_idx = jnp.argmax(probs, axis=-1)            # (N,)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        gate_val = (probs * onehot).sum(-1)                # (N,)
        # slot assignment: position of each token within its expert's queue
        pos = jnp.cumsum(onehot, axis=0) * onehot - onehot  # (N, E), 0-based
        pos_tok = pos.sum(-1)                               # (N,)
        keep = (pos_tok < cap) & (onehot.sum(-1) > 0)
        slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), cap,
                              dtype=jnp.float32)                 # (N, C)
        dispatch = onehot[:, :, None] * slot[:, None, :]         # (N, E, C)
        dispatch = dispatch * keep[:, None, None].astype(jnp.float32)
        combine = dispatch * gate_val[:, None, None]
        # Switch load-balance loss: E * sum_e f_e * p_e  (1.0 when uniform)
        f_e = onehot.mean(0)
        p_e = probs.mean(0)
        aux = e * jnp.sum(f_e * p_e)
        return dispatch, combine, aux

    # ---- expert compute ------------------------------------------------

    def _experts_ffn(self, ep: Pytree, slots: jax.Array) -> jax.Array:
        """slots: (E_local, S, d) -> (E_local, S, d); one batched einsum
        pair per layer — E_local independent matmuls tiled onto the MXU."""
        cdt = self.compute_dtype
        h = jnp.einsum("esd,edf->esf", slots.astype(cdt),
                       ep["w_in"].astype(cdt)) + ep["b_in"][:, None, :].astype(cdt)
        h = ACTIVATIONS[self.activation](h)
        out = jnp.einsum("esf,efd->esd", h,
                         ep["w_out"].astype(cdt)) + ep["b_out"][:, None, :].astype(cdt)
        return out

    def apply(self, params: Pytree, x: jax.Array, **kwargs
              ) -> Tuple[jax.Array, jax.Array]:
        """x: (..., d_model) -> (y, aux).  Leading dims are flattened into
        the token axis for routing."""
        lead = x.shape[:-1]
        d = x.shape[-1]
        toks = x.reshape(-1, d)
        n = toks.shape[0]
        cap = self._capacity(n)
        dispatch, combine, aux = self._route(params["gate"], toks, cap)
        cdt = self.compute_dtype
        slots = jnp.einsum("nec,nd->ecd", dispatch.astype(cdt),
                           toks.astype(cdt))               # (E, C, d)
        if self.expert_axis is None:
            out = self._experts_ffn(params["experts"], slots)
        else:
            # (E, C, d) -> exchange -> (E_local, ep*C, d): each device
            # gathers every peer's slots for the experts it owns
            slots = lax.all_to_all(slots, self.expert_axis,
                                   split_axis=0, concat_axis=1, tiled=True)
            out = self._experts_ffn(params["experts"], slots)
            out = lax.all_to_all(out, self.expert_axis,
                                 split_axis=1, concat_axis=0, tiled=True)
        y = jnp.einsum("nec,ecd->nd", combine.astype(cdt), out)
        return y.reshape(*lead, d).astype(cdt), aux
