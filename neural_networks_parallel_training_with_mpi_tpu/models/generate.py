"""Autoregressive decoding for the Transformer LM (inference API).

The reference is a pure training demo — it has no inference path at all
(its dead test-evaluation block at dataParallelTraining_NN_MPI.py:227-236 is
the closest thing).  A complete framework needs one, so this module adds
jitted autoregressive decoding, TPU-shaped:

* **KV cache with static shapes**: the cache is a preallocated
  ``(B, max_len, heads, head_dim)`` buffer per layer, written with
  ``lax.dynamic_update_slice`` at the current position — no growing arrays,
  so the whole decode loop is one compiled program.
* **Prefill + scan**: uniform prompts are prefixed in ONE batched chunk
  (prompt positions run in parallel on the MXU, exactly like the training
  forward), then new tokens come from a ``lax.scan`` of single-position
  chunks.  Ragged prompts (``prompt_lens``) fall back to the fully
  sequential scan so short rows' generated tokens — not their pads — enter
  the cache.
* **Shared wiring with training**: embeddings/head come from
  ``Transformer.embed``/``head_logits`` and the block weights from
  ``Transformer._block_modules``, so inference cannot drift from training
  (pinned by tests/test_generate.py's replay check).

Works with the dense-attention configuration (flash/ring add nothing at
chunk size 1).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .core import ACTIVATIONS
from .transformer import Transformer, split_qkv


def init_kv_cache(model: Transformer, batch: int, max_len: int,
                  quant: bool = False):
    """Per-layer (k, v) buffers, (B, max_len, kv_heads, head_dim).

    Under GQA (cfg.n_kv_heads < n_heads) the cache stores the
    UN-repeated K/V heads — kv_heads/n_heads of the MHA bytes, which is
    the whole point: decode streams the cache every step, so grouped
    heads cut the long-context serving bandwidth (and HBM residency) by
    the group factor.

    ``quant=True`` stores K/V as int8 with one f32 scale per (batch,
    position, head) — the third serving-bandwidth lever (stacks with
    GQA and int8 weights).  Both scales commute through the attention
    contractions: the K scale multiplies each key position's logit
    column, and the V scale folds into the softmax weights before the
    value einsum, so dequantization never materializes an f32 cache."""
    c = model.cfg
    shape = (batch, max_len, c.kv_heads, c.head_dim)
    if quant:
        zeros = lambda: jnp.zeros(shape, jnp.int8)
        ones = lambda: jnp.ones(shape[:-1], jnp.float32)
        return [{"k": zeros(), "v": zeros(),
                 "k_scale": ones(), "v_scale": ones()}
                for _ in range(c.n_layers)]
    zeros = lambda: jnp.zeros(shape, c.compute_dtype)
    return [{"k": zeros(), "v": zeros()} for _ in range(c.n_layers)]


def _quantize_kv(x: jax.Array):
    """(..., head_dim) -> int8 codes + f32 scale over the trailing dim
    (symmetric, +/-127; zero rows get scale 1 so 0/1 round-trips)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    codes = jnp.clip(jnp.round(x.astype(jnp.float32) / s[..., None]),
                     -127, 127).astype(jnp.int8)
    return codes, s.astype(jnp.float32)


def _block_chunk(model: Transformer, params, cache, x, pos):
    """One block on a chunk ``x`` (B, S, D) starting at position ``pos``:
    writes the chunk's K/V into the cache and attends causally over
    positions 0..pos+S-1.  S = prompt length at prefill, 1 per decode step.
    Mirrors Transformer._block for the incremental case.

    ``pos`` may be a scalar (every row at the same depth — the
    single-stream generate() path) or a ``(B,)`` vector (each row at its
    OWN depth — continuous batching, models.serve): the cache write is a
    vmapped per-row dynamic_update_slice and the causal mask compares
    against each row's own position, so both cases share one
    implementation and the int8-KV branch."""
    c = model.cfg
    mods = model._block_modules()
    h = mods["ln1"].apply(params["ln1"], x)
    qkv = mods["qkv"].apply(params["qkv"], h)
    b, s, _ = qkv.shape
    q, k, v = split_qkv(c, qkv)      # q: (b,s,H,hd); k/v: (b,s,KV,hd)
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    if c.pos_encoding == "rope":
        # rotate q and THIS chunk's k at their absolute positions; the
        # cache then holds already-rotated keys (standard RoPE decode),
        # so earlier positions are never revisited
        from ..ops.rope import rope_rotate

        chunk_pos = pos_b[:, None] + jnp.arange(s)[None, :]   # (b, s)
        q = rope_rotate(q, chunk_pos, c.rope_theta)
        k = rope_rotate(k, chunk_pos, c.rope_theta)
    write = jax.vmap(lambda buf, row, p: lax.dynamic_update_slice(
        buf, row, (p,) + (0,) * (buf.ndim - 1)))
    quant = "k_scale" in cache       # int8 KV cache (init_kv_cache)
    if quant:
        k, ks = _quantize_kv(k)
        v, vs = _quantize_kv(v)
        new_ks = write(cache["k_scale"], ks, pos_b)
        new_vs = write(cache["v_scale"], vs, pos_b)
    new_k = write(cache["k"], k.astype(cache["k"].dtype), pos_b)
    new_v = write(cache["v"], v.astype(cache["v"].dtype), pos_b)
    scale = 1.0 / jnp.sqrt(jnp.asarray(c.head_dim, jnp.float32))
    T = cache["k"].shape[1]
    # causal within the chunk: key position <= row position + query
    # offset — (b, s, T), degenerating to the classic chunk mask when
    # pos is scalar
    mask = (jnp.arange(T)[None, None, :]
            <= pos_b[:, None, None] + jnp.arange(s)[None, :, None])
    if c.kv_heads == c.n_heads:
        logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            new_k.astype(jnp.float32)) * scale
        if quant:
            # K scale: one multiplier per key position/head on the logit
            # column — dequantization without an f32 copy of the cache
            logits = logits * new_ks.transpose(0, 2, 1)[:, :, None, :]
        logits = jnp.where(mask[:, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        if quant:
            # V scale folds into the softmax weights (out is linear in
            # each value row, so p_k * s_k reweights exactly)
            probs = probs * new_vs.transpose(0, 2, 1)[:, :, None, :]
        out = jnp.einsum("bhqk,bkhd->bqhd", probs,
                         new_v.astype(jnp.float32)).astype(x.dtype)
    else:
        # GQA: attend with the cache's grouped heads directly — the
        # repeat stays virtual (an einsum batch dim), so each decode
        # step streams only kv_heads/n_heads of the MHA cache bytes
        g = c.n_heads // c.kv_heads
        q5 = q.reshape(b, s, c.kv_heads, g, c.head_dim)
        logits = jnp.einsum("bqcgd,bkcd->bcgqk", q5.astype(jnp.float32),
                            new_k.astype(jnp.float32)) * scale
        if quant:
            logits = logits * new_ks.transpose(0, 2, 1)[:, :, None, None, :]
        logits = jnp.where(mask[:, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        if quant:
            probs = probs * new_vs.transpose(0, 2, 1)[:, :, None, None, :]
        out = jnp.einsum("bcgqk,bkcd->bqcgd", probs,
                         new_v.astype(jnp.float32)).astype(x.dtype)
        out = out.reshape(b, s, c.n_heads, c.head_dim)
    out = out.reshape(b, s, c.d_model)
    x = x + mods["attn_out"].apply(params["attn_out"], out)
    h = mods["ln2"].apply(params["ln2"], x)
    if c.moe_experts > 0:
        ff, _ = mods["moe"].apply(params["moe"], h)
    else:
        ff = model._ffn(mods, params, h)
    new_cache = {"k": new_k, "v": new_v}
    if quant:
        new_cache.update(k_scale=new_ks, v_scale=new_vs)
    return x + ff.astype(x.dtype), new_cache


def _forward_token_batched(model: Transformer, params, caches, ids,
                           pos_vec: jax.Array):
    """Logits for one token per row at PER-ROW positions (continuous
    batching, models.serve): ids (B, 1), pos_vec (B,) -> ((B, 1, vocab)
    f32, updated caches).  Rides :func:`_block_chunk`'s vector-``pos``
    mode, so the int8-KV branch and any future attention fix are shared
    with the single-stream path by construction."""
    x = model.embed(params, ids, pos_vec[:, None])
    new_caches = []
    for layer_params, cache in zip(params["blocks"], caches):
        x, cache = _block_chunk(model, layer_params, cache, x, pos_vec)
        new_caches.append(cache)
    return model.head_logits(params, x), new_caches


def _forward_chunk(model: Transformer, params, caches, ids, pos):
    """Logits for a chunk: ids (B, S) at start position ``pos`` ->
    ((B, S, vocab) f32, updated caches)."""
    positions = pos + jnp.arange(ids.shape[1])
    x = model.embed(params, ids, positions)
    new_caches = []
    for layer_params, cache in zip(params["blocks"], caches):
        x, cache = _block_chunk(model, layer_params, cache, x, pos)
        new_caches.append(cache)
    return model.head_logits(params, x), new_caches


def _filter_logits(logits, top_k: int, top_p: float):
    """Mask logits outside the top-k / nucleus-p candidate sets to -inf.
    Static control flow only (both knobs are trace-time constants), so the
    decode step stays one compiled program."""
    neg = jnp.finfo(logits.dtype).min
    if top_k > 0:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if 0.0 < top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest prefix with cumulative mass >= top_p; the shifted mask
        # always keeps the most-probable token
        keep_sorted = jnp.roll(cum < top_p, 1, axis=-1).at[..., 0].set(True)
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, -neg),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, neg, logits)
    return logits


def _sample(logits, temperature, key, top_k: int = 0, top_p: float = 1.0):
    if temperature > 0:
        key, sub = jax.random.split(key)
        # temperature FIRST, then the nucleus: top_p must measure the mass
        # of the distribution actually being sampled (top_k is monotone in
        # the logits, so its candidate set is temperature-invariant)
        logits = _filter_logits(logits / temperature, top_k, top_p)
        nxt = jax.random.categorical(sub, logits, axis=-1)
    else:
        nxt = jnp.argmax(logits, axis=-1)
    return nxt.astype(jnp.int32), key


def generate(model: Transformer, params, prompt: jax.Array,
             max_new_tokens: int, *, temperature: float = 0.0,
             top_k: int = 0, top_p: float = 1.0,
             key: Optional[jax.Array] = None,
             prompt_lens: Optional[jax.Array] = None,
             pad_id: int = 0, kv_quant: bool = False,
             prefill_chunk: int = 0) -> jax.Array:
    """Decode ``max_new_tokens`` after ``prompt`` (B, P) -> (B, P + N).

    ``temperature=0`` is greedy argmax; otherwise softmax sampling at the
    given temperature (``key`` required), optionally restricted to the
    ``top_k`` most likely tokens and/or the smallest nucleus with
    cumulative probability ``top_p`` (both static; 0 / 1.0 disable).
    With ragged prompts, right-pad to a common P with ``pad_id`` and pass
    ``prompt_lens`` (B,); each row starts generating at its own length
    (sequential path — generated tokens, not pads, populate the cache for
    short rows).

    ``kv_quant=True`` stores the KV cache as int8 with per-(batch,
    position, head) f32 scales (see ``init_kv_cache``) — ~half the cache
    bytes re-streamed per step vs the bf16-compute cache (~4x vs f32),
    the long-context serving lever that stacks with GQA and int8
    weights.  Also accepted by :func:`generate_sharded`.

    ``prefill_chunk > 0`` prefills the prompt in chunks of that many
    positions instead of one (B, P) pass: peak prefill attention memory
    drops from O(P·T) scores to O(chunk·T) — the long-PROMPT lever;
    identical tokens (chunk boundaries only change which query rows
    share a pass).  Ignored on the ragged path (already sequential).

    Wrap in ``jax.jit`` (static: model, max_new_tokens, temperature,
    top_k, top_p, kv_quant) for repeated use; shapes are static so
    recompiles only on new (B, P, N).
    """
    c = model.cfg
    b, p = prompt.shape
    total = p + max_new_tokens
    if total > c.max_seq_len:
        raise ValueError(f"prompt {p} + {max_new_tokens} new tokens exceeds "
                         f"max_seq_len {c.max_seq_len}")
    if temperature > 0 and key is None:
        raise ValueError("temperature sampling needs a PRNG key")
    if max_new_tokens == 0:
        # nothing to generate; the prefill path below would sample one token
        # and clamp its write onto the last prompt column
        return prompt.astype(jnp.int32)
    key = key if key is not None else jax.random.PRNGKey(0)
    if c.scan_layers:
        # decode walks layers with per-layer caches; unstack the scanned
        # (n_layers, ...) block leaves back to a per-layer list (slices of
        # the same buffers — no copy under jit)
        params = dict(params)
        stacked = params["blocks"]
        params["blocks"] = [
            jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
            for i in range(c.n_layers)
        ]
    caches = init_kv_cache(model, b, total, quant=kv_quant)
    tokens = jnp.concatenate(
        [prompt.astype(jnp.int32),
         jnp.full((b, max_new_tokens), pad_id, jnp.int32)], axis=1)
    ragged = prompt_lens is not None

    def step(carry, pos):
        tokens, caches, key = carry
        ids_1 = lax.dynamic_slice(tokens, (0, pos), (b, 1))
        logits, caches = _forward_chunk(model, params, caches, ids_1, pos)
        nxt, key = _sample(logits[:, 0], temperature, key, top_k, top_p)
        if ragged:
            # rows whose prompt extends past pos+1 keep their prompt token
            keep = (pos + 1) < prompt_lens
            cur = lax.dynamic_slice(tokens, (0, pos + 1), (b, 1))[:, 0]
            nxt = jnp.where(keep, cur, nxt)
        tokens = lax.dynamic_update_slice(tokens, nxt[:, None], (0, pos + 1))
        return (tokens, caches, key), None

    if ragged:  # fully sequential: per-row start positions
        start = 0
    else:  # prefill: prompt positions in parallel chunks
        if 0 < prefill_chunk < p:
            # chunked prefill (long-context serving): attention scores
            # for a chunk are (B, H, C, T) instead of (B, H, P, T), so
            # peak prefill memory is bounded by the chunk size while the
            # cache still fills left to right (each chunk attends over
            # everything already written, mirroring _block_chunk's
            # causal mask at its start offset).  Chunk boundaries don't
            # change the math — only which query rows share a pass.
            logits = None
            for off in range(0, p, prefill_chunk):
                c_len = min(prefill_chunk, p - off)
                logits, caches = _forward_chunk(
                    model, params, caches, tokens[:, off:off + c_len],
                    off)
            last_logits = logits[:, -1]   # final chunk ends at p - 1
        else:
            logits, caches = _forward_chunk(model, params, caches,
                                            tokens[:, :p], 0)
            last_logits = logits[:, p - 1]
        first, key = _sample(last_logits, temperature, key, top_k, top_p)
        tokens = lax.dynamic_update_slice(tokens, first[:, None], (0, p))
        start = p
    if start < total - 1:
        (tokens, _, _), _ = lax.scan(step, (tokens, caches, key),
                                     jnp.arange(start, total - 1))
    return tokens


@functools.lru_cache(maxsize=32)
def _sharded_decode_program(model: Transformer, mesh, max_new_tokens: int,
                            temperature: float, top_k: int, top_p: float,
                            pad_id: int, batch_axes,
                            kv_quant: bool = False):
    """One jitted decode program per (model, mesh, decode knobs) — cached
    so a serving loop pays compilation once, not per call.  The PRNG key
    and prompt lengths are TRACED arguments (new keys don't recompile)."""
    from ..parallel.sharding import batch_sharding

    rows = batch_sharding(mesh, ndim=2, batch_axes=batch_axes)

    def run(params, prompt, lens, key):
        return generate(model, params, prompt, max_new_tokens,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        key=key, prompt_lens=lens, pad_id=pad_id,
                        kv_quant=kv_quant)

    # compile-ledger seam (utils/compile_ledger): decode-path compiles
    # land in compiles.jsonl whenever a ledger is installed
    from ..utils import compile_ledger as ledger_lib

    return ledger_lib.instrument(
        jax.jit(run, out_shardings=rows),
        f"generate_sharded[n={max_new_tokens}]"), rows


def generate_sharded(model: Transformer, params, prompt, mesh,
                     max_new_tokens: int, *, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 1.0,
                     key: Optional[jax.Array] = None,
                     prompt_lens: Optional[jax.Array] = None,
                     pad_id: int = 0,
                     batch_axes=("data", "fsdp"),
                     kv_quant: bool = False) -> jax.Array:
    """Batch-parallel decode over the mesh's data axes: params replicated,
    prompt rows sharded, one CACHED jitted program — GSPMD partitions the
    KV caches and the sampling with the batch, so serving throughput
    scales with devices the same way training does (the reference has no
    inference path at all; its closest artifact is the dead test-eval
    block, dataParallelTraining_NN_MPI.py:227-236).

    ``prompt`` (B, P) with B divisible by the product of the mesh's
    ``batch_axes`` sizes; axes absent from the mesh are ignored.  Same
    sampling knobs as :func:`generate`."""
    from ..parallel.sharding import batch_sharding, replicated_sharding

    if temperature > 0 and key is None:  # mirror generate()'s guard:
        # defaulting the key here would make every "sampled" request
        # silently deterministic
        raise ValueError("temperature sampling needs a PRNG key")
    axes = tuple(a for a in batch_axes if a in mesh.shape)
    n = 1
    for a in axes:
        n *= int(mesh.shape[a])
    b = prompt.shape[0]
    if b % n:
        raise ValueError(f"prompt batch {b} not divisible by the "
                         f"{axes} axes product {n}")
    run, rows = _sharded_decode_program(model, mesh, max_new_tokens,
                                        temperature, top_k, top_p, pad_id,
                                        axes, kv_quant)
    params = jax.device_put(params, replicated_sharding(mesh))
    prompt = jax.device_put(jnp.asarray(prompt, jnp.int32), rows)
    if prompt_lens is not None:
        prompt_lens = jax.device_put(jnp.asarray(prompt_lens, jnp.int32),
                                     batch_sharding(mesh, ndim=1,
                                                    batch_axes=axes))
    if key is None:
        key = jax.random.PRNGKey(0)
    return run(params, prompt, prompt_lens, key)
