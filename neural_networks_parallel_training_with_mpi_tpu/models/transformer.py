"""Tiny decoder-only Transformer LM (BASELINE.json config #5).

The reference has no attention or sequence axis (SURVEY.md §5.7); this model
is the flagship for the TPU-native capabilities the framework adds on top of
reference parity: bfloat16 matmuls on the MXU, optional rematerialization,
and pluggable attention (dense / ring / ulysses — parallel.sequence) so the
sequence dimension can be sharded over the mesh's 'seq' axis.

Pre-LN architecture: x + Attn(LN(x)), x + MLP(LN(x)); learned positional
embeddings; weight-tied output head kept separate (simpler sharding).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ..parallel.sequence import sequence_sharded_attention
from .core import Embedding, LayerNorm, Linear, Module, ACTIVATIONS


def split_qkv(c: "TransformerConfig", qkv: jax.Array):
    """Split a fused qkv projection (B, T, qkv_dim) into per-head
    q (B, T, n_heads, hd) and k/v (B, T, kv_heads, hd) — the single
    definition shared by the training block and the KV-cache decode path
    so the GQA column layout [q | k | v] cannot drift between them."""
    b, t, _ = qkv.shape
    kvw = c.kv_heads * c.head_dim
    q = qkv[..., :c.d_model].reshape(b, t, c.n_heads, c.head_dim)
    k = qkv[..., c.d_model:c.d_model + kvw].reshape(b, t, c.kv_heads,
                                                    c.head_dim)
    v = qkv[..., c.d_model + kvw:].reshape(b, t, c.kv_heads, c.head_dim)
    return q, k, v


def repeat_kv(c: "TransformerConfig", kv: jax.Array) -> jax.Array:
    """Broadcast grouped K/V heads (B, T, kv_heads, hd) to full query
    heads (B, T, n_heads, hd); identity for classic multi-head."""
    groups = c.n_heads // c.kv_heads
    if groups == 1:
        return kv
    return jnp.repeat(kv, groups, axis=2)


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    max_seq_len: int = 512
    n_layers: int = 2
    d_model: int = 128
    n_heads: int = 4
    d_ff: int = 512
    activation: str = "gelu"
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32   # set bfloat16 for TPU throughput
    # "auto" dispatches dense-vs-flash by (backend, T) at the measured
    # crossover (parallel.sequence.resolve_attention_impl)
    attention: str = "auto"            # auto | dense | flash | ring | ...
    seq_axis: str = "seq"
    # Position encoding: "learned" adds a trained position-embedding table
    # (the default, matching the original treedef); "rope" rotates q/k by
    # their global positions instead (ops.rope — no position parameters at
    # all, relative-distance attention, fused elementwise on TPU).  The
    # rotation happens inside sequence_sharded_attention, so every
    # attention impl (dense/flash/ring/striped/ulysses) and every
    # seq-parallel layout inherits it; the KV-cache decode paths
    # (dense AND the native-TP generate_tp) rotate the new position and
    # cache rotated keys; Megatron-TP dense attention rotates inside
    # tp_block_apply on its local heads.
    pos_encoding: str = "learned"      # learned | rope
    rope_theta: float = 10000.0
    # Grouped-query attention (GQA, Ainslie et al. 2023): n_kv_heads < n_heads
    # shares each K/V head across n_heads/n_kv_heads query heads.  None =
    # classic multi-head (n_kv_heads == n_heads), keeping the default
    # param treedef byte-identical to pre-GQA checkpoints.  The win is
    # the KV cache: decode streams (and stores) n_kv_heads/n_heads of
    # the MHA cache bytes — the long-context serving bottleneck — while
    # training repeats K/V to full heads before the attention impls
    # (same math, unchanged kernels).  Under Megatron TP the K/V heads
    # shard over the tensor axis too (needs n_kv_heads % tp == 0; the
    # contiguous head-aligned permutation keeps each rank's query-head
    # groups on exactly its own K/V heads — qkv_tp_permutation), and the
    # native-TP decode (generate_tp) serves the kv_heads/tp-sharded
    # cache with grouped local attention.
    n_kv_heads: Optional[int] = None
    # Pallas flash-kernel tile sizes (flash / ring_flash / striped_flash
    # only; dense and the non-flash ring ignore them).  128 x 128 is the
    # v5e-safe default — block_k is the MXU contraction tile for the
    # score matmul and block_q rows live in VMEM across the k-loop, so
    # larger block_k amortizes loop overhead at the price of VMEM;
    # bench's flagship sweep (tools/big_lm_sweep.py) tunes these on the
    # real chip rather than guessing.
    flash_block_q: int = 128
    flash_block_k: int = 128
    remat: bool = False                # jax.checkpoint each block (HBM <-> FLOPs)
    remat_policy: str = "full"         # full | dots | dots_no_batch (models.core.make_remat)
    # lax.scan over a stacked block pytree (leaves (n_layers, ...)) instead
    # of a Python loop: XLA traces/compiles ONE block body regardless of
    # depth, so compile time and program size stop growing with n_layers —
    # the TPU-idiomatic layout for deep models.  Changes the param treedef
    # (stacked vs per-layer list); composes with remat (checkpoint the
    # scan body) and with the seq x tensor path (parallel.spmd scans the
    # Megatron block), but not with the pipeline/GSPMD/expert layouts,
    # which own their own stacking/sharding.
    scan_layers: bool = False
    # MoE FFN (models.moe): 0 experts = dense FFN.  With ``moe_expert_axis``
    # set, apply() must run inside a shard_map binding that mesh axis and
    # expert params sharded over it (parallel.expert wires the train step).
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_capacity: Optional[int] = None
    moe_expert_axis: Optional[str] = None
    moe_top_k: int = 1  # 1 = Switch; 2 = GShard-style top-2 routing
    # Quantized-matmul seam (ops.qmm, DESIGN.md §14): run every dense
    # projection (qkv/attn_out/ffn/head) in this format.  'bf16' = the
    # plain compute_dtype matmul (byte-identical to the pre-seam model);
    # 'int8' = dynamic symmetric int8 x int8 -> int32 (training via
    # custom_vjp, serving against ops.quant PTQ weights); 'fp8' = e4m3
    # fwd / e5m2 bwd with delayed-scaling activation amax histories
    # carried in TrainState.qstate and threaded through apply(qscales=).
    # Attention's score/value einsums stay in compute_dtype.
    matmul_dtype: str = "bf16"
    # Roles excluded from the quantized-compute seam (kept on the plain
    # compute_dtype matmul): mirrors ops.quant's `skip` — a layer the
    # user kept full-precision in STORAGE (--quantize_skip head) must
    # not be dynamically quantized in COMPUTE either, and high-precision
    # first/last layers are the standard low-precision-training recipe.
    matmul_skip: Tuple[str, ...] = ()
    # Fused chunked cross-entropy (>0 enables): the LM head + CE are
    # evaluated over sequence blocks of this many tokens under
    # jax.checkpoint, so the full (B, T, vocab) f32 logits tensor — the
    # dominant HBM temp for large vocabularies, bigger than the entire
    # rest of the activation stack for the flagship 32k-vocab config —
    # is never materialized.  Peak head memory drops from O(B*T*V) to
    # O(B*ce_chunk*V) in both passes (backward recomputes each chunk's
    # logits).  Identical math to head_logits + ops.losses
    # softmax_cross_entropy up to f32 summation order.  T must be a
    # multiple of ce_chunk.  Training-loss path only (the decode path
    # wants actual logits); picked up via fused_loss_sum by
    # parallel.data_parallel.make_loss_fn.
    ce_chunk: int = 0

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        """Effective K/V head count (== n_heads unless GQA)."""
        kv = self.n_kv_heads if self.n_kv_heads is not None else self.n_heads
        assert self.n_heads % kv == 0, (
            f"n_heads={self.n_heads} not divisible by n_kv_heads={kv}")
        return kv

    @property
    def qkv_dim(self) -> int:
        """Fused qkv projection width: d (q) + 2 * kv_heads * head_dim
        (k, v) — reduces to 3 * d_model for classic multi-head."""
        return self.d_model + 2 * self.kv_heads * self.head_dim


@dataclass(frozen=True)
class Transformer(Module):
    cfg: TransformerConfig = dataclasses.field(default_factory=TransformerConfig)

    # ---- submodule builders (stateless; params live in the pytree) ----
    def _mm(self, role: str) -> str:
        """Effective matmul format for one projection site: the config
        format, unless the role is in ``matmul_skip`` (kept full
        precision — the compute analogue of ops.quant's ``skip``)."""
        c = self.cfg
        return "bf16" if role in c.matmul_skip else c.matmul_dtype

    def _block_modules(self):
        c = self.cfg
        mods = {
            "ln1": LayerNorm(c.d_model, param_dtype=c.param_dtype),
            "qkv": Linear(c.d_model, c.qkv_dim, param_dtype=c.param_dtype,
                          compute_dtype=c.compute_dtype,
                          matmul_dtype=self._mm("qkv"), q_role="qkv"),
            "attn_out": Linear(c.d_model, c.d_model, param_dtype=c.param_dtype,
                               compute_dtype=c.compute_dtype,
                               matmul_dtype=self._mm("attn_out"),
                               q_role="attn_out"),
            "ln2": LayerNorm(c.d_model, param_dtype=c.param_dtype),
        }
        if c.moe_experts > 0:
            from .moe import MoEFFN

            mods["moe"] = MoEFFN(
                c.d_model, c.d_ff, c.moe_experts,
                capacity_factor=c.moe_capacity_factor,
                capacity=c.moe_capacity, activation=c.activation,
                expert_axis=c.moe_expert_axis,
                router_top_k=c.moe_top_k,
                param_dtype=c.param_dtype, compute_dtype=c.compute_dtype)
        else:
            mods["ff_in"] = Linear(c.d_model, c.d_ff,
                                   param_dtype=c.param_dtype,
                                   compute_dtype=c.compute_dtype,
                                   matmul_dtype=self._mm("ff_in"),
                                   q_role="ff_in")
            if c.activation == "swiglu":
                # gated FFN (Shazeer 2020): silu(x W_gate) * (x W_in),
                # then W_out — the modern-LM FFN.  A third (d, ff)
                # projection; pick d_ff ~2/3 of the ungated width for
                # iso-parameter comparisons.
                mods["ff_gate"] = Linear(c.d_model, c.d_ff,
                                         param_dtype=c.param_dtype,
                                         compute_dtype=c.compute_dtype,
                                         matmul_dtype=self._mm("ff_gate"),
                                         q_role="ff_gate")
            mods["ff_out"] = Linear(c.d_ff, c.d_model,
                                    param_dtype=c.param_dtype,
                                    compute_dtype=c.compute_dtype,
                                    matmul_dtype=self._mm("ff_out"),
                                    q_role="ff_out")
        return mods

    def quant_roles(self):
        """fp8 delayed-scaling roles (ops.qmm): one activation amax
        history per logical matmul site, shared across layers (under
        scan_layers the layers share one traced block anyway; for the
        python-loop stack the cross-layer max is a conservative
        per-tensor bound).  Skipped roles carry no history — their
        Linears run the plain matmul.  MoE blocks apply no ffn Linears
        (the expert einsums live outside the seam; the Trainer refuses
        the combination, but a directly-built step must not seed
        histories no forward will ever observe)."""
        c = self.cfg
        roles = ["qkv", "attn_out", "head"]
        if c.moe_experts <= 0:
            ffn = ["ff_in", "ff_out"]
            if c.activation == "swiglu":
                ffn.insert(1, "ff_gate")
            roles[2:2] = ffn
        return tuple(r for r in roles if r not in c.matmul_skip)

    def _ffn(self, mods, params, h: jax.Array, **qkw) -> jax.Array:
        """Dense-FFN tail shared by the training block and the KV-cache
        decode chunk (anti-drift): classic act(W_in h) W_out, or SwiGLU
        when activation == 'swiglu'.  ``qkw`` threads the fp8
        delayed-scaling context (qscales/qobserved) to the Linears."""
        c = self.cfg
        if c.activation == "swiglu":
            gate = jax.nn.silu(mods["ff_gate"].apply(params["ff_gate"], h,
                                                     **qkw))
            return mods["ff_out"].apply(
                params["ff_out"],
                gate * mods["ff_in"].apply(params["ff_in"], h, **qkw),
                **qkw)
        h = mods["ff_in"].apply(params["ff_in"], h, **qkw)
        h = ACTIVATIONS[c.activation](h)
        return mods["ff_out"].apply(params["ff_out"], h, **qkw)

    def init(self, key: jax.Array):
        c = self.cfg
        keys = jax.random.split(key, c.n_layers + 3)
        embed = Embedding(c.vocab_size, c.d_model, c.param_dtype)
        pos = Embedding(c.max_seq_len, c.d_model, c.param_dtype)
        head = Linear(c.d_model, c.vocab_size, use_bias=False,
                      param_dtype=c.param_dtype, compute_dtype=c.compute_dtype)
        mods = self._block_modules()
        blocks = []
        for i in range(c.n_layers):
            bkeys = jax.random.split(keys[i], len(mods))
            blocks.append({name: m.init(k) for (name, m), k in zip(mods.items(), bkeys)})
        if c.scan_layers:  # stacked layout: leaves (n_layers, ...)
            blocks = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                            *blocks)
        out = {
            "embed": embed.init(keys[-3]),
            "blocks": blocks,
            "ln_f": LayerNorm(c.d_model, param_dtype=c.param_dtype).init(keys[-1]),
            "head": head.init(keys[-1]),
        }
        if c.pos_encoding != "rope":   # RoPE has no position parameters
            out["pos"] = pos.init(keys[-2])
        return out

    def _block(self, params, x: jax.Array, qscales=None, collect=False):
        """One pre-LN block: (params, x) -> (x, aux, qobs); aux is the MoE
        load-balance loss for this block (0.0 for a dense FFN), qobs the
        fp8 calibration observations ({role: amax} when ``collect``, {}
        otherwise — ops.qmm delayed scaling; ``qscales`` is the delayed
        amax each Linear reads)."""
        c = self.cfg
        mods = self._block_modules()
        qobs = {} if collect else None
        qkw = ({"qscales": qscales, "qobserved": qobs}
               if c.matmul_dtype == "fp8" else {})
        h = mods["ln1"].apply(params["ln1"], x)
        qkv = mods["qkv"].apply(params["qkv"], h, **qkw)
        q, k, v = split_qkv(c, qkv)
        # GQA training path: repeat K/V to full query heads so every
        # attention impl (dense/flash/ring/...) sees plain MHA — same
        # math as grouped attention; the bandwidth win is decode-side
        # (models.generate caches the UN-repeated kv_heads)
        k, v = repeat_kv(c, k), repeat_kv(c, v)
        out = sequence_sharded_attention(
            c.attention, q, k, v,
            axis=c.seq_axis, causal=True, block_q=c.flash_block_q,
            block_k=c.flash_block_k,
            rope_theta=(c.rope_theta if c.pos_encoding == "rope"
                        else None))
        out = out.reshape(*out.shape[:2], c.d_model)
        x = x + mods["attn_out"].apply(params["attn_out"], out, **qkw)
        h = mods["ln2"].apply(params["ln2"], x)
        if c.moe_experts > 0:
            ff, aux = mods["moe"].apply(params["moe"], h)
        else:
            ff = self._ffn(mods, params, h, **qkw)
            aux = jnp.zeros((), jnp.float32)
        return x + ff.astype(x.dtype), aux, (qobs or {})

    def add_pos(self, params, x_tokens: jax.Array,
                positions: jax.Array) -> jax.Array:
        """Positional embedding + compute-dtype cast on an already-looked-up
        token embedding — the non-vocab half of :meth:`embed`, shared with
        the vocab-parallel path (parallel.spmd) where the token lookup is
        table-sharded but THIS part must stay identical to the dense
        model."""
        c = self.cfg
        if c.pos_encoding == "rope":
            # position enters through the q/k rotation inside attention
            # (sequence_sharded_attention / the decode chunk), not here
            return x_tokens.astype(c.compute_dtype)
        x = x_tokens + Embedding(c.max_seq_len, c.d_model,
                                 c.param_dtype).apply(params["pos"],
                                                      positions)
        return x.astype(c.compute_dtype)

    def embed(self, params, ids: jax.Array, positions: jax.Array) -> jax.Array:
        """Token + positional embedding -> (B, T, D) in compute dtype.
        Single definition shared by the training forward and the KV-cache
        decode path (models.generate), so they cannot drift."""
        c = self.cfg
        x = Embedding(c.vocab_size, c.d_model, c.param_dtype).apply(
            params["embed"], ids)
        return self.add_pos(params, x, positions)

    def final_norm(self, params, x: jax.Array) -> jax.Array:
        """The pre-head LayerNorm — the non-vocab half of
        :meth:`head_logits`, shared with the vocab-parallel head (same
        drift argument as :meth:`add_pos`)."""
        c = self.cfg
        return LayerNorm(c.d_model, param_dtype=c.param_dtype).apply(
            params["ln_f"], x)

    def head_logits(self, params, x: jax.Array, qscales=None) -> jax.Array:
        """Final LayerNorm + untied head -> f32 logits (shared with
        models.generate, same drift argument as :meth:`embed`)."""
        c = self.cfg
        x = self.final_norm(params, x)
        logits = Linear(c.d_model, c.vocab_size, use_bias=False,
                        param_dtype=c.param_dtype,
                        compute_dtype=c.compute_dtype,
                        matmul_dtype=self._mm("head"),
                        q_role="head").apply(params["head"], x,
                                             qscales=qscales)
        return logits.astype(jnp.float32)

    def fwd_flops(self, x_shape):
        """(B, T) token batch.  qkv/out/ffn/attention matmuls + LM head;
        with MoE, each token runs ``moe_top_k`` expert FFNs plus the
        router matmul."""
        c = self.cfg
        b, t = x_shape
        d, ff, v = c.d_model, c.d_ff, c.vocab_size
        per_layer = 2.0 * b * t * d * c.qkv_dim  # qkv projection (GQA-aware)
        per_layer += 2.0 * b * t * d * d        # attention out projection
        per_layer += 2.0 * (2.0 * b * t * t * d)  # scores + values
        # FFN in + out per expert; SwiGLU adds the (d, ff) gate matmul
        ffn = 2.0 * ((3.0 if c.activation == "swiglu" else 2.0)
                     * b * t * d * ff)
        if c.moe_experts > 0:
            ffn *= c.moe_top_k
            per_layer += 2.0 * b * t * d * c.moe_experts  # router
        per_layer += ffn
        return float(c.n_layers * per_layer + 2.0 * b * t * d * v)

    def backbone(self, params, ids: jax.Array, qscales=None,
                 collect=False):
        """Embedding + all blocks -> ((B, T_local, d_model) pre-head
        hidden states, MoE aux sum, fp8 amax observations).  The shared
        trunk of :meth:`apply` and the fused chunked-CE loss path (same
        drift argument as :meth:`embed` / :meth:`head_logits`).

        ``qscales``/``collect`` are the fp8 delayed-scaling context
        (ops.qmm): blocks read the per-role delayed amax and, under
        ``collect``, report this step's observed amax — max-merged across
        layers, riding the scan carry under ``scan_layers`` so the
        observations escape the scan trace."""
        c = self.cfg
        from ..parallel.sequence import global_positions

        positions = global_positions(c.attention, c.seq_axis, ids.shape[1])
        x = self.embed(params, ids, positions)
        collect = collect and c.matmul_dtype == "fp8"
        # qscales/collect are CLOSED OVER (not block_fn args): collect is
        # a static python bool — as a positional arg, jax.checkpoint
        # would trace it — and qscales is calibration state, constant
        # w.r.t. the differentiated params
        _qs, _collect = qscales, collect

        def block_fn(layer_params, h):
            return self._block(layer_params, h, _qs, _collect)

        if c.remat:
            from .core import make_remat

            block_fn = make_remat(c.remat_policy)(block_fn)
        aux_total = jnp.zeros((), jnp.float32)
        # block-level roles only (head observes in apply/qloss callers)
        block_roles = [r for r in (self.quant_roles() if collect else ())
                       if r != "head"]
        qobs_total = {r: jnp.zeros((), jnp.float32) for r in block_roles}
        if c.scan_layers:
            def body(carry, layer_params):
                h, aux_sum, obs_acc = carry
                h, aux, obs = block_fn(layer_params, h)
                obs_acc = {r: jnp.maximum(obs_acc[r], obs[r])
                           for r in obs_acc}
                return (h, aux_sum + aux, obs_acc), None

            (x, aux_total, qobs_total), _ = jax.lax.scan(
                body, (x, aux_total, qobs_total), params["blocks"])
        else:
            for layer_params in params["blocks"]:
                x, aux, obs = block_fn(layer_params, x)
                aux_total = aux_total + aux
                qobs_total = {r: jnp.maximum(qobs_total[r], obs[r])
                              for r in qobs_total}
        return x, aux_total, qobs_total

    def apply(self, params, ids: jax.Array, return_aux: bool = False,
              qscales=None, return_qobs: bool = False, **kwargs):
        """ids: (B, T_local) int32 -> logits (B, T_local, vocab), or
        (logits, aux) with ``return_aux`` (aux = summed MoE load-balance
        loss over blocks; 0.0 for dense FFNs), or (logits, qobs) with
        ``return_qobs`` (the fp8 delayed-scaling observations,
        {role: amax} — the training step's calibration input).

        ``qscales`` is the per-role delayed amax read from
        TrainState.qstate (ops.qmm.delayed_amax); None = current scaling
        (eval/decode, no calibration state to thread).

        Under sequence parallelism T_local = T / seq_axis_size and
        ``pos_offset`` (the shard's global starting position) is derived from
        the bound axis index; dense attention uses offset 0.
        """
        x, aux_total, qobs = self.backbone(params, ids, qscales=qscales,
                                           collect=return_qobs)
        if (return_qobs and self.cfg.matmul_dtype == "fp8"
                and "head" in self.quant_roles()):
            from ..ops import qmm

            qobs = dict(qobs)
            qobs["head"] = qmm.tensor_amax(self.final_norm(params, x))
        logits = self.head_logits(params, x, qscales=qscales)
        if return_qobs:
            return (logits, aux_total, qobs) if return_aux else (logits,
                                                                 qobs)
        return (logits, aux_total) if return_aux else logits

    # ---- fused chunked cross-entropy (cfg.ce_chunk > 0) ----

    def _chunked_ce_sum(self, params, x: jax.Array, labels: jax.Array,
                        mask: Optional[jax.Array],
                        label_smoothing: float
                        ) -> Tuple[jax.Array, jax.Array]:
        """(loss_sum, token_count) of head-projection + softmax CE computed
        ``ce_chunk`` tokens at a time under ``jax.checkpoint``.  ``x`` is
        the post-final-norm hidden state (B, T, d_model); the (B, T, V)
        logits tensor never exists — each scan tick materializes only a
        (B, ce_chunk, V) slice, and backward recomputes it.  Matches
        head_logits + ops.losses.softmax_cross_entropy exactly up to f32
        summation order (chunk sums are accumulated sequentially)."""
        c = self.cfg
        B, T, _ = x.shape
        k = c.ce_chunk
        if T % k != 0:
            raise ValueError(
                f"ce_chunk={k} must divide the local sequence length {T}")
        n = T // k
        head = Linear(c.d_model, c.vocab_size, use_bias=False,
                      param_dtype=c.param_dtype,
                      compute_dtype=c.compute_dtype,
                      matmul_dtype=self._mm("head"), q_role="head")

        from ..ops import losses as losses_lib

        def chunk_sum(head_params, xc, yc):
            # ops.losses.softmax_cross_entropy is the single definition of
            # the nll/mask/count semantics (same anti-drift argument as
            # embed/head_logits: the fused path must stay byte-equivalent
            # in math to the materializing path it replaces); per chunk it
            # returns (sum over B x k masked tokens, mask.sum() * k), and
            # the scan total reproduces reduce_token_nll's (sum,
            # mask.sum() * T) exactly
            logits = head.apply(head_params, xc).astype(jnp.float32)
            return losses_lib.softmax_cross_entropy(
                logits, yc, mask, label_smoothing=label_smoothing)

        chunk_sum = jax.checkpoint(chunk_sum)
        xs = x.reshape(B, n, k, x.shape[-1]).swapaxes(0, 1)  # (n, B, k, d)
        ys = labels.reshape(B, n, k).swapaxes(0, 1)          # (n, B, k)

        def body(acc, inp):
            xc, yc = inp
            s, cnt = chunk_sum(params["head"], xc, yc)
            return (acc[0] + s, acc[1] + cnt), None

        (s, cnt), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (xs, ys))
        return s, cnt

    def fused_loss_sum(self, loss_name: str):
        """(params, batch) -> (loss_sum, count) closure fusing the LM head
        into a chunked cross-entropy, or None when not applicable (chunking
        disabled, or a loss the fusion doesn't cover).  Hook consumed by
        parallel.data_parallel.make_loss_fn; batch/mask semantics are
        those of ops.losses.softmax_cross_entropy + reduce_token_nll."""
        if self.cfg.ce_chunk <= 0:
            return None
        base, _, smooth = loss_name.partition("@")
        if base != "cross_entropy":
            return None
        label_smoothing = float(smooth) if smooth else 0.0

        def loss_fn(params, batch):
            x, _aux, _qobs = self.backbone(params, batch["x"])
            x = self.final_norm(params, x)
            return self._chunked_ce_sum(params, x, batch["y"],
                                        batch.get("mask"), label_smoothing)

        return loss_fn
