"""ctypes binding for the native (C++) batch loader in ``native/``.

The reference's host data path is torch's Python DataLoader over a
root-materialized array (dataParallelTraining_NN_MPI.py:146, :72).  The
native runtime replaces the per-batch numpy fancy-indexing with a C++
worker pool that shuffles, gathers rows (shared permutation across fields),
and prefetches finished batches into a bounded queue — so batch assembly
overlaps device compute instead of serializing with it.

The binding is optional everywhere: :func:`available` gates it, and
``ShardedLoader`` silently falls back to the numpy path when the shared
library is missing or the build toolchain is absent.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path
from typing import Dict, Iterator, Optional

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parents[2]
_NATIVE_DIR = _REPO_ROOT / "native"
_SO_PATH = _NATIVE_DIR / "libnnploader.so"
_lib = None
_lib_lock = threading.Lock()


def _build() -> bool:
    if not (_NATIVE_DIR / "dataloader.cpp").exists():
        return False
    try:
        subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                       capture_output=True, timeout=120)
        return _SO_PATH.exists()
    except Exception:
        return False


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _SO_PATH.exists() and not _build():
            return None
        lib = ctypes.CDLL(str(_SO_PATH))
        lib.dl_create.restype = ctypes.c_void_p
        lib.dl_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64,
                                  ctypes.c_int]
        lib.dl_add_field.restype = ctypes.c_int
        lib.dl_add_field.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_uint64]
        lib.dl_start_epoch.restype = ctypes.c_uint64
        lib.dl_start_epoch.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                       ctypes.c_uint64, ctypes.c_int,
                                       ctypes.c_uint64, ctypes.c_int,
                                       ctypes.c_uint64]
        lib.dl_next_batch.restype = ctypes.c_uint64
        lib.dl_next_batch.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_void_p)]
        lib.dl_destroy.restype = None
        lib.dl_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeBatcher:
    """Shuffle+gather+prefetch over a dict of equal-length numpy arrays.

    Yields batches as dicts of freshly-owned numpy arrays with the SAME
    permutation applied to every field.  Shuffle order is deterministic in
    (seed, epoch) — but intentionally a different (native splitmix64)
    sequence than the numpy path's, so resuming a run must stick with one
    backend (ShardedLoader pins it per instance).
    """

    def __init__(self, data: Dict[str, np.ndarray], batch_size: int,
                 *, seed: int = 0, shuffle: bool = True,
                 drop_remainder: bool = False, n_threads: int = 2,
                 prefetch_depth: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native loader unavailable (libnnploader.so "
                               "missing and build failed)")
        self._lib = lib
        self.keys = list(data)
        # keep C-contiguous copies alive for the loader's whole lifetime
        self.arrays = {k: np.ascontiguousarray(v) for k, v in data.items()}
        lens = {k: v.shape[0] for k, v in self.arrays.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"ragged dataset: {lens}")
        self.n = next(iter(lens.values()))
        self.batch_size = batch_size if batch_size else self.n
        self.drop_remainder = drop_remainder
        self.n_threads = n_threads
        self.prefetch_depth = prefetch_depth
        self._handle = lib.dl_create(self.n, seed, int(shuffle))
        self._row_shapes = {}
        self._dtypes = {}
        for k in self.keys:
            a = self.arrays[k]
            row_bytes = a.dtype.itemsize * int(np.prod(a.shape[1:], dtype=np.int64))
            lib.dl_add_field(self._handle, a.ctypes.data_as(ctypes.c_void_p),
                             row_bytes)
            self._row_shapes[k] = a.shape[1:]
            self._dtypes[k] = a.dtype

    @property
    def steps_per_epoch(self) -> int:
        if self.drop_remainder:
            return max(self.n // self.batch_size, 1)
        return -(-self.n // self.batch_size)

    def epoch(self, epoch: int, start_batch: int = 0
              ) -> Iterator[Dict[str, np.ndarray]]:
        lib = self._lib
        remaining = lib.dl_start_epoch(
            self._handle, epoch, self.batch_size, int(self.drop_remainder),
            start_batch, self.n_threads, self.prefetch_depth)
        ptrs = (ctypes.c_void_p * len(self.keys))()
        for _ in range(remaining):
            rows = lib.dl_next_batch(self._handle, ptrs)
            if rows == 0:
                return
            out = {}
            for i, k in enumerate(self.keys):
                shape = (rows,) + self._row_shapes[k]
                nbytes = int(np.prod(shape, dtype=np.int64)) * \
                    self._dtypes[k].itemsize
                buf = ctypes.cast(
                    ptrs[i], ctypes.POINTER(ctypes.c_uint8 * nbytes))
                # copy out: the native buffer is reused on the next call
                arr = np.frombuffer(bytearray(buf.contents),
                                    dtype=self._dtypes[k]).reshape(shape)
                out[k] = arr
            yield out

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.dl_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
