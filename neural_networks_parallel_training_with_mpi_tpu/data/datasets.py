"""Dataset builders.

``regression_dataset`` reproduces the reference workload byte-for-byte:
sklearn ``make_regression(n_samples=16, n_features=2, noise=1,
random_state=42)`` (dataParallelTraining_NN_MPI.py:72).  Standardization is
*global* (train-set statistics applied before sharding), deliberately fixing
reference bug B4 (per-shard ``StandardScaler`` at :21-22 gives each worker a
differently-normalized view).

MNIST/CIFAR-10/LM builders first look for real data under ``data_dir`` and
otherwise generate deterministic synthetic stand-ins with the right
shapes/dtypes — the benchmark harness measures throughput, which is
data-content-independent, and CI must run hermetic (zero egress).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, Optional, Tuple

import numpy as np

from ..config import DataConfig

Arrays = Dict[str, np.ndarray]


def standardize(x: np.ndarray) -> np.ndarray:
    """Global z-score over axis 0 (the fix for bug B4)."""
    mean = x.mean(axis=0, keepdims=True)
    std = x.std(axis=0, keepdims=True)
    return (x - mean) / np.where(std == 0.0, 1.0, std)


def regression_dataset(n_samples: int = 16, n_features: int = 2,
                       noise: float = 1.0, seed: int = 42,
                       do_standardize: bool = True) -> Arrays:
    """The reference's dataset (reference :72), X globally standardized."""
    from sklearn.datasets import make_regression

    x, y = make_regression(n_samples=n_samples, n_features=n_features,
                           noise=noise, random_state=seed)
    x = x.astype(np.float32)
    y = y.astype(np.float32).reshape(-1, 1)
    if do_standardize:
        x = standardize(x)
    return {"x": x, "y": y}


def digits_dataset(seed: int = 0, do_standardize: bool = True) -> Arrays:
    """sklearn ``load_digits`` — 1797 REAL 8x8 handwritten-digit images,
    bundled with sklearn (no network; the only real classification dataset
    available under zero egress).  Shapes mirror the MNIST pipeline at 1/12
    resolution: x (N, 64) float32, y (N,) int32.  Rows are shuffled
    deterministically by ``seed`` so train/val splits are class-balanced."""
    from sklearn.datasets import load_digits

    d = load_digits()
    x = d.data.astype(np.float32)
    y = d.target.astype(np.int32)
    if do_standardize:
        x = standardize(x)
    order = np.random.default_rng(seed).permutation(len(x))
    return {"x": x[order], "y": y[order]}


def _load_idx_images(path: Path) -> Optional[np.ndarray]:
    """Minimal IDX reader for locally-present MNIST files (no download)."""
    import gzip
    import struct

    opener = gzip.open if path.suffix == ".gz" else open
    try:
        with opener(path, "rb") as f:
            magic, = struct.unpack(">I", f.read(4))
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            data = np.frombuffer(f.read(), dtype=np.uint8)
            return data.reshape(dims)
    except (OSError, ValueError):
        return None


def mnist_dataset(data_dir: Optional[str] = None, seed: int = 0,
                  n_samples: int = 60_000) -> Arrays:
    """Real MNIST if idx files exist under data_dir, else synthetic with
    identical shapes: x (N, 784) float32 in [0,1]-ish, y (N,) int32 in [0,10)."""
    if data_dir:
        d = Path(data_dir)
        imgs = _load_idx_images(d / "train-images-idx3-ubyte.gz") \
            if (d / "train-images-idx3-ubyte.gz").exists() else \
            _load_idx_images(d / "train-images-idx3-ubyte")
        labs = _load_idx_images(d / "train-labels-idx1-ubyte.gz") \
            if (d / "train-labels-idx1-ubyte.gz").exists() else \
            _load_idx_images(d / "train-labels-idx1-ubyte")
        if imgs is not None and labs is not None:
            x = imgs.reshape(imgs.shape[0], -1).astype(np.float32) / 255.0
            return {"x": x, "y": labs.astype(np.int32)}
    rng = np.random.default_rng(seed)
    x = rng.random((n_samples, 784), dtype=np.float32)
    y = rng.integers(0, 10, size=n_samples).astype(np.int32)
    return {"x": x, "y": y}


def cifar10_dataset(data_dir: Optional[str] = None, seed: int = 0,
                    n_samples: int = 50_000) -> Arrays:
    """CIFAR-10 NHWC: x (N, 32, 32, 3) float32, y (N,) int32."""
    if data_dir:
        d = Path(data_dir) / "cifar-10-batches-py"
        if d.exists():
            import pickle

            xs, ys = [], []
            for i in range(1, 6):
                with open(d / f"data_batch_{i}", "rb") as f:
                    batch = pickle.load(f, encoding="bytes")
                xs.append(batch[b"data"])
                ys.append(batch[b"labels"])
            x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            return {"x": x.astype(np.float32) / 255.0,
                    "y": np.concatenate(ys).astype(np.int32)}
    rng = np.random.default_rng(seed)
    x = rng.random((n_samples, 32, 32, 3), dtype=np.float32)
    y = rng.integers(0, 10, size=n_samples).astype(np.int32)
    return {"x": x, "y": y}


def lm_dataset(seq_len: int = 128, vocab_size: int = 256, seed: int = 0,
               n_samples: int = 2048, data_dir: Optional[str] = None) -> Arrays:
    """Next-token LM data: x (N, T) int32 tokens, y (N, T) int32 shifted
    targets.  Uses a local WikiText-2-style text file if present (byte-level
    tokenization), else a deterministic Markov-ish synthetic stream."""
    text_path = None
    if data_dir:
        for name in ("wiki.train.tokens", "wikitext-2/wiki.train.tokens",
                     "train.txt"):
            p = Path(data_dir) / name
            if p.exists():
                text_path = p
                break
    if text_path is not None:
        raw = np.frombuffer(text_path.read_bytes(), dtype=np.uint8)
        tokens = (raw % vocab_size).astype(np.int32)
    else:
        rng = np.random.default_rng(seed)
        tokens = rng.integers(0, vocab_size,
                              size=n_samples * (seq_len + 1)).astype(np.int32)
    n = min(n_samples, len(tokens) // (seq_len + 1))
    tokens = tokens[: n * (seq_len + 1)].reshape(n, seq_len + 1)
    return {"x": tokens[:, :-1].copy(), "y": tokens[:, 1:].copy()}


def text_dataset(text_file: str, seq_len: int = 128, vocab_size: int = 256,
                 n_samples: Optional[int] = None) -> Arrays:
    """Byte-level next-token LM windows over ANY local text file — the
    zero-egress real-text path (the reference has no text/LM capability at
    all; SURVEY.md §5.7).  Bytes are the tokens (vocab 256 covers them;
    smaller vocabs fold via modulo, documented lossy).  Non-overlapping
    (seq_len + 1)-byte windows, x/y shifted by one."""
    p = Path(text_file)
    if not p.exists():
        raise FileNotFoundError(f"--text_file {text_file!r} does not exist")
    raw = np.frombuffer(p.read_bytes(), dtype=np.uint8).astype(np.int32)
    tokens = raw if vocab_size >= 256 else raw % vocab_size
    n_avail = len(tokens) // (seq_len + 1)
    if n_avail == 0:
        raise ValueError(
            f"{text_file}: {len(tokens)} bytes < one window of "
            f"seq_len+1={seq_len + 1}")
    n = min(n_samples, n_avail) if n_samples else n_avail
    tokens = tokens[: n * (seq_len + 1)].reshape(n, seq_len + 1)
    return {"x": tokens[:, :-1].copy(), "y": tokens[:, 1:].copy()}


def train_val_split(data: Arrays, val_fraction: float,
                    seed: int = 0) -> Tuple[Arrays, Arrays]:
    """Deterministic shuffled train/validation split.

    Realizes the held-out-eval intent of the reference's dead validation
    code (dataParallelTraining_NN_MPI.py:213-220, :227-236 — commented out,
    SURVEY.md C10) as a real feature.  Every host computes the identical
    split from the seed — no root-rank coordination needed.
    """
    if not 0.0 <= val_fraction < 1.0:
        raise ValueError(f"val_fraction must be in [0, 1), got {val_fraction}")
    n = next(iter(data.values())).shape[0]
    n_val = int(round(n * val_fraction))
    if n_val == 0:
        return data, {}
    if n_val >= n:
        raise ValueError(
            f"val_fraction={val_fraction} leaves no training samples (n={n})")
    perm = np.random.default_rng(seed).permutation(n)
    val_idx, train_idx = perm[:n_val], perm[n_val:]
    train = {k: v[train_idx] for k, v in data.items()}
    val = {k: v[val_idx] for k, v in data.items()}
    return train, val


def build_dataset(cfg: DataConfig, data_dir: Optional[str] = None) -> Arrays:
    data_dir = data_dir or os.environ.get("NNPT_DATA_DIR")
    if cfg.dataset == "regression":
        return regression_dataset(cfg.n_samples or 16, cfg.n_features,
                                  cfg.noise, cfg.seed, cfg.standardize)
    if cfg.dataset == "wide_regression":
        return regression_dataset(cfg.n_samples or 1_000_000, cfg.n_features,
                                  cfg.noise, cfg.seed, cfg.standardize)
    if cfg.dataset == "digits":
        return digits_dataset(cfg.seed, cfg.standardize)
    if cfg.dataset == "mnist":
        return mnist_dataset(data_dir, cfg.seed,
                             n_samples=cfg.n_samples or 60_000)
    if cfg.dataset == "cifar10":
        return cifar10_dataset(data_dir, cfg.seed,
                               n_samples=cfg.n_samples or 50_000)
    if cfg.dataset == "lm":
        return lm_dataset(cfg.seq_len, cfg.vocab_size, cfg.seed,
                          n_samples=cfg.n_samples or 2048, data_dir=data_dir)
    if cfg.dataset == "text":
        if not cfg.text_file:
            raise ValueError("dataset='text' needs --text_file")
        return text_dataset(cfg.text_file, cfg.seq_len, cfg.vocab_size,
                            n_samples=cfg.n_samples)
    raise ValueError(f"unknown dataset {cfg.dataset!r}")
