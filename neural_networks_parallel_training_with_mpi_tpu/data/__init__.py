"""Host-side data pipeline: dataset builders and the sharded loader."""

from .datasets import build_dataset, regression_dataset
from .loader import ShardedLoader
