"""Sharded batch loader.

Replaces the reference's data distribution *and* per-worker DataLoader
(dataParallelTraining_NN_MPI.py:96-146) with one host-side iterator that:

* honors a real ``batch_size`` (the reference parses ``--batch_size`` but
  feeds the whole shard as one batch, :146/:249 — bug B1); ``full_batch=True``
  reproduces the reference behavior,
* shuffles with an explicit per-epoch ``numpy`` PRNG seeded from the job seed
  (fixing the reference's rank-0-only ``torch.manual_seed``, bug B5),
* pads the final/uneven batch to a multiple of the data-axis size with a
  validity mask (the Scatterv replacement, SURVEY.md §7), or drops it,
* in multi-host jobs materializes only this process's rows and assembles the
  logically-global array via ``jax.make_array_from_process_local_data``
  (unlike the reference, where rank 0 materializes everything, :72).

Every yielded batch is a dict pytree ``{"x", "y", "mask"}`` of
``jax.Array``s already placed on the mesh with dim-0 'data' sharding.
"""

from __future__ import annotations

import math
import queue
import threading
from typing import Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from ..parallel import sharding as shd

Arrays = Dict[str, np.ndarray]

_DONE = object()


def _thread_prefetch(gen: Iterator[Arrays], depth: int) -> Iterator[Arrays]:
    """Run ``gen`` (pure numpy work) on a daemon thread, ``depth`` items
    ahead.  Exceptions re-raise on the consumer thread.  When the consumer
    abandons the iterator early (``next(iter(epoch(0)))`` example-batch
    grabs, early breaks), generator close sets the stop event and the
    worker exits within its put-poll interval — no parked threads, no
    pinned batches."""
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def work():
        def put(item) -> bool:
            # EVERY handoff polls the stop event — including the _DONE
            # sentinel and the exception handoff.  A plain q.put() there
            # would park the worker forever when the consumer abandons the
            # iterator with the queue full (e.g. an exception unwinding
            # the train loop right at epoch end).
            while True:
                if stop.is_set():
                    return False
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue

        try:
            for item in gen:
                if not put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — handed to the consumer
            put(e)
            return
        put(_DONE)

    threading.Thread(target=work, daemon=True,
                     name="loader-prefetch").start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


class ShardedLoader:
    def __init__(self, mesh: Mesh, data: Arrays, batch_size: int,
                 *, shuffle: bool = True, seed: int = 0,
                 full_batch: bool = False, remainder: str = "pad",
                 multi_host: Optional[bool] = None,
                 seq_axis: Optional[str] = None,
                 backend: str = "numpy",
                 batch_axes: Optional[tuple] = None,
                 prefetch: int = 2,
                 seq_permutation: Optional[np.ndarray] = None):
        if remainder not in ("pad", "drop"):
            raise ValueError("remainder must be 'pad' or 'drop'")
        if prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        if backend not in ("numpy", "native", "auto"):
            raise ValueError("backend must be 'numpy', 'native' or 'auto'")
        self.mesh = mesh
        # when sequence parallelism is on, rank>=2 leaves are also sharded
        # along dim 1 over this axis (see parallel.spmd.batch_specs)
        self.seq_axis = (seq_axis
                         if seq_axis and mesh.shape.get(seq_axis, 1) > 1
                         else None)
        # reorders dim 1 of every rank>=2 leaf (inputs AND targets
        # together, so per-token losses are unchanged): the
        # striped-attention token layout (parallel.sequence.
        # striped_permutation) — contiguous shard d then holds round-robin
        # stripe d
        self.seq_permutation = (np.asarray(seq_permutation)
                                if seq_permutation is not None else None)
        self.data = {k: np.asarray(v) for k, v in data.items()}
        if self.seq_permutation is not None:
            # applied ONCE here (not per batch): the layout is static, and
            # the native batcher below gathers from the permuted arrays too
            self.data = {k: (v[:, self.seq_permutation] if v.ndim >= 2
                             else v)
                         for k, v in self.data.items()}
        lens = {k: v.shape[0] for k, v in self.data.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"ragged dataset: {lens}")
        self.n = next(iter(lens.values()))
        # axes that jointly shard the batch dim; the expert-parallel path
        # adds 'expert' (tokens are batch-sharded over it too)
        self.batch_axes = tuple(batch_axes or ("data", "fsdp"))
        self.dp = int(np.prod([mesh.shape[a] for a in self.batch_axes]))
        self.batch_size = self.n if full_batch else min(batch_size, self.n)
        self.shuffle = shuffle
        self.seed = seed
        # anomaly-rollback re-draw (train.resilience): bumping the salt
        # changes every SUBSEQUENT epoch order so a rolled-back run does
        # not replay a poisonous batch window verbatim.  0 (the default)
        # keeps the historical (seed, epoch) stream bitwise intact; the
        # native (C++) batcher owns its own permutation and ignores the
        # salt (rollback there replays the same order — still correct,
        # just not re-drawn).
        self.order_salt = 0
        self.remainder = remainder
        self.prefetch = prefetch
        self.multi_host = (jax.process_count() > 1 if multi_host is None
                           else multi_host)
        # native (C++) shuffle+gather+prefetch path: batch assembly overlaps
        # device compute on a worker pool (data.native_loader).  Its shuffle
        # permutation differs from the numpy path's, so the backend is
        # pinned per loader instance (resume must not switch backends).
        self._native = None
        if backend in ("native", "auto"):
            from . import native_loader

            if native_loader.available():
                self._native = native_loader.NativeBatcher(
                    self.data, self.batch_size, seed=seed, shuffle=shuffle,
                    drop_remainder=(remainder == "drop"))
            elif backend == "native":
                raise RuntimeError("backend='native' requested but the "
                                   "native loader is unavailable")

    @property
    def steps_per_epoch(self) -> int:
        if self.remainder == "drop":
            return max(self.n // self.batch_size, 1)
        return math.ceil(self.n / self.batch_size)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        order = np.arange(self.n)
        if self.shuffle:
            key = ((self.seed, epoch) if not self.order_salt
                   else (self.seed, epoch, self.order_salt))
            np.random.default_rng(key).shuffle(order)
        return order

    def batch_rows(self, step: int) -> int:
        """Real (unpadded) rows in batch ``step`` of any epoch — for exact
        samples/sec accounting on the final partial batch."""
        bs = self.batch_size
        return min(bs, self.n - step * bs)

    def consumed_samples(self, global_step: int) -> int:
        """Dataset sample-slots consumed after ``global_step`` steps —
        the WORLD-SIZE-INDEPENDENT progress coordinate checkpoint meta
        carries (DESIGN.md §10): the per-epoch order is derived from
        (seed, epoch, order_salt) alone, so two loaders with different
        batch sizes / dp widths walk the SAME sample permutation and only
        cut it into batches differently.  Counts order slots, so padded
        rows don't distort it and a full epoch is exactly ``n``."""
        spe = self.steps_per_epoch
        full_epochs, in_epoch = divmod(global_step, spe)
        return full_epochs * self.n + min(in_epoch * self.batch_size,
                                          self.n)

    def start_for_samples(self, samples: int) -> tuple:
        """(epoch, start_step) under THIS loader's batch size for a run
        that has already consumed ``samples`` order slots — the inverse
        of :meth:`consumed_samples` for an elastic resume whose batch
        size changed with the world.  A sample offset that no longer
        lands on a batch boundary rounds DOWN (re-trains up to
        batch_size-1 samples rather than silently skipping any), so the
        resumed stream remains a permutation of the original epoch."""
        epoch, offset = divmod(max(0, int(samples)), self.n)
        if offset >= self.steps_per_epoch * self.batch_size:
            # the old batch size covered the epoch tail this one drops
            # (remainder='drop'): start the next epoch
            return epoch + 1, 0
        return epoch, offset // self.batch_size

    def epoch(self, epoch: int, start_step: int = 0
              ) -> Iterator[Dict[str, jax.Array]]:
        """Yield device-placed global batches for one epoch.  ``start_step``
        skips already-trained batches when resuming mid-epoch (the order is
        deterministic per (seed, epoch), so a resumed run sees the identical
        remaining batches).

        Host-side batch assembly (index gather over the dataset arrays)
        runs ``prefetch`` batches ahead on a daemon thread so it overlaps
        device compute — the Python-path analogue of the native (C++)
        loader's worker pool; device placement stays on the caller's
        thread (single-threaded JAX API use)."""
        if self._native is not None:
            for batch in self._native.epoch(epoch, start_batch=start_step):
                yield self._place(batch)
            return
        host = self._host_batches(epoch, start_step)
        if self.prefetch > 0:
            host = _thread_prefetch(host, self.prefetch)
        for batch in host:
            yield self._place(batch)

    def _host_batches(self, epoch: int, start_step: int) -> Iterator[Arrays]:
        order = self._epoch_order(epoch)
        bs = self.batch_size
        for step in range(start_step, self.steps_per_epoch):
            idx = order[step * bs: (step + 1) * bs]
            if self.remainder == "drop" and len(idx) < bs:
                break
            yield {k: v[idx] for k, v in self.data.items()}

    def epoch_groups(self, epoch: int, k: int, start_step: int = 0
                     ) -> Iterator[tuple]:
        """Yield ``(stacked_batch, n_steps, rows)`` groups of up to ``k``
        consecutive batches, stacked on a leading scan axis and shipped in
        ONE host->device transfer (parallel.sharding.shard_batch_stack) —
        the data side of multi-step dispatch (--steps_per_dispatch).  The
        batches and their order are IDENTICAL to :meth:`epoch`'s (same
        shuffle, same padding, same seq permutation), so a k-step
        ``lax.scan`` over the stack replays exactly the steps the
        per-step loop would run; the final group of an epoch may be
        shorter.  ``rows`` is the group's real (unpadded) row count for
        samples/sec accounting.  Seq-parallel layouts stack through
        ``spmd.place_batch_stack`` (seq-sharded dim 2)."""
        if self.multi_host:
            raise NotImplementedError(
                "steps_per_dispatch > 1 is single-host for now: the "
                "stacked group would need a make_global_batch variant "
                "assembling per-process rows under the scan axis")
        if self.seq_axis:
            from ..parallel import spmd

            place = lambda group: spmd.place_batch_stack(
                self.mesh, group, self.seq_axis,
                batch_axes=self.batch_axes)
        else:
            place = lambda group: shd.shard_batch_stack(
                self.mesh, group, self.batch_axes)
        host = (self._native.epoch(epoch, start_batch=start_step)
                if self._native is not None
                else self._host_batches(epoch, start_step))
        if self.prefetch > 0 and self._native is None:
            host = _thread_prefetch(host, self.prefetch)
        group, rows, step = [], 0, start_step
        for batch in host:
            group.append(self._pad(batch))
            rows += self.batch_rows(step)
            step += 1
            if len(group) == k:
                yield place(group), len(group), rows
                group, rows = [], 0
        if group:
            yield place(group), len(group), rows

    def _pad(self, batch: Arrays) -> Arrays:
        padded = {}
        pad_mask = None
        for k, v in batch.items():
            pv, pad_mask = shd.pad_to_multiple(v, self.dp)
            padded[k] = pv
        # combine with a caller-provided per-row mask rather than clobber it
        # (the mask contract of ops.losses: 0 rows contribute nothing)
        if "mask" in batch:
            padded["mask"] = padded["mask"].astype(np.float32) * pad_mask
        else:
            padded["mask"] = pad_mask
        return padded

    def _place(self, batch: Arrays) -> Dict[str, jax.Array]:
        padded = self._pad(batch)
        if not self.multi_host:
            if self.seq_axis:
                from ..parallel import spmd

                # rows over ALL the loader's batch axes (incl. 'expert' on
                # the MoE layouts) — the placement must match the step's
                # in_specs or jit reshards every batch on the hot path
                return spmd.place_batch(self.mesh, padded, self.seq_axis,
                                        batch_axes=self.batch_axes)
            return shd.shard_batch(self.mesh, padded, self.batch_axes)
        # multi-host: slice out this process's contiguous row block
        total = padded["mask"].shape[0]
        nproc = jax.process_count()
        start, stop = shd.process_local_slice(total, nproc, jax.process_index())
        local = {k: v[start:stop] for k, v in padded.items()}
        return shd.make_global_batch(self.mesh, local, total, self.batch_axes)
