"""Sharded batch loader.

Replaces the reference's data distribution *and* per-worker DataLoader
(dataParallelTraining_NN_MPI.py:96-146) with one host-side iterator that:

* honors a real ``batch_size`` (the reference parses ``--batch_size`` but
  feeds the whole shard as one batch, :146/:249 — bug B1); ``full_batch=True``
  reproduces the reference behavior,
* shuffles with an explicit per-epoch ``numpy`` PRNG seeded from the job seed
  (fixing the reference's rank-0-only ``torch.manual_seed``, bug B5),
* pads the final/uneven batch to a multiple of the data-axis size with a
  validity mask (the Scatterv replacement, SURVEY.md §7), or drops it,
* in multi-host jobs materializes only this process's rows and assembles the
  logically-global array via ``jax.make_array_from_process_local_data``
  (unlike the reference, where rank 0 materializes everything, :72).

Every yielded batch is a dict pytree ``{"x", "y", "mask"}`` of
``jax.Array``s already placed on the mesh with dim-0 'data' sharding.
"""

from __future__ import annotations

import math
from typing import Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from ..parallel import sharding as shd

Arrays = Dict[str, np.ndarray]


class ShardedLoader:
    def __init__(self, mesh: Mesh, data: Arrays, batch_size: int,
                 *, shuffle: bool = True, seed: int = 0,
                 full_batch: bool = False, remainder: str = "pad",
                 multi_host: Optional[bool] = None,
                 seq_axis: Optional[str] = None,
                 backend: str = "numpy",
                 batch_axes: Optional[tuple] = None):
        if remainder not in ("pad", "drop"):
            raise ValueError("remainder must be 'pad' or 'drop'")
        if backend not in ("numpy", "native", "auto"):
            raise ValueError("backend must be 'numpy', 'native' or 'auto'")
        self.mesh = mesh
        # when sequence parallelism is on, rank>=2 leaves are also sharded
        # along dim 1 over this axis (see parallel.spmd.batch_specs)
        self.seq_axis = (seq_axis
                         if seq_axis and mesh.shape.get(seq_axis, 1) > 1
                         else None)
        self.data = {k: np.asarray(v) for k, v in data.items()}
        lens = {k: v.shape[0] for k, v in self.data.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"ragged dataset: {lens}")
        self.n = next(iter(lens.values()))
        # axes that jointly shard the batch dim; the expert-parallel path
        # adds 'expert' (tokens are batch-sharded over it too)
        self.batch_axes = tuple(batch_axes or ("data", "fsdp"))
        self.dp = int(np.prod([mesh.shape[a] for a in self.batch_axes]))
        self.batch_size = self.n if full_batch else min(batch_size, self.n)
        self.shuffle = shuffle
        self.seed = seed
        self.remainder = remainder
        self.multi_host = (jax.process_count() > 1 if multi_host is None
                           else multi_host)
        # native (C++) shuffle+gather+prefetch path: batch assembly overlaps
        # device compute on a worker pool (data.native_loader).  Its shuffle
        # permutation differs from the numpy path's, so the backend is
        # pinned per loader instance (resume must not switch backends).
        self._native = None
        if backend in ("native", "auto"):
            from . import native_loader

            if native_loader.available():
                self._native = native_loader.NativeBatcher(
                    self.data, self.batch_size, seed=seed, shuffle=shuffle,
                    drop_remainder=(remainder == "drop"))
            elif backend == "native":
                raise RuntimeError("backend='native' requested but the "
                                   "native loader is unavailable")

    @property
    def steps_per_epoch(self) -> int:
        if self.remainder == "drop":
            return max(self.n // self.batch_size, 1)
        return math.ceil(self.n / self.batch_size)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        order = np.arange(self.n)
        if self.shuffle:
            np.random.default_rng((self.seed, epoch)).shuffle(order)
        return order

    def batch_rows(self, step: int) -> int:
        """Real (unpadded) rows in batch ``step`` of any epoch — for exact
        samples/sec accounting on the final partial batch."""
        bs = self.batch_size
        return min(bs, self.n - step * bs)

    def epoch(self, epoch: int, start_step: int = 0
              ) -> Iterator[Dict[str, jax.Array]]:
        """Yield device-placed global batches for one epoch.  ``start_step``
        skips already-trained batches when resuming mid-epoch (the order is
        deterministic per (seed, epoch), so a resumed run sees the identical
        remaining batches)."""
        if self._native is not None:
            for batch in self._native.epoch(epoch, start_batch=start_step):
                yield self._place(batch)
            return
        order = self._epoch_order(epoch)
        bs = self.batch_size
        for step in range(start_step, self.steps_per_epoch):
            idx = order[step * bs: (step + 1) * bs]
            if self.remainder == "drop" and len(idx) < bs:
                break
            batch = {k: v[idx] for k, v in self.data.items()}
            yield self._place(batch)

    def _place(self, batch: Arrays) -> Dict[str, jax.Array]:
        padded = {}
        pad_mask = None
        for k, v in batch.items():
            pv, pad_mask = shd.pad_to_multiple(v, self.dp)
            padded[k] = pv
        # combine with a caller-provided per-row mask rather than clobber it
        # (the mask contract of ops.losses: 0 rows contribute nothing)
        if "mask" in batch:
            padded["mask"] = padded["mask"].astype(np.float32) * pad_mask
        else:
            padded["mask"] = pad_mask
        if not self.multi_host:
            if self.seq_axis:
                from ..parallel import spmd

                return spmd.place_batch(self.mesh, padded, self.seq_axis)
            return shd.shard_batch(self.mesh, padded, self.batch_axes)
        # multi-host: slice out this process's contiguous row block
        total = padded["mask"].shape[0]
        nproc = jax.process_count()
        start, stop = shd.process_local_slice(total, nproc, jax.process_index())
        local = {k: v[start:stop] for k, v in padded.items()}
        return shd.make_global_batch(self.mesh, local, total, self.batch_axes)
