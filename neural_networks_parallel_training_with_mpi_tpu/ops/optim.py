"""Pure-pytree optimizers.

The reference uses ``torch.optim.SGD(model.parameters(), lr, momentum)``
(dataParallelTraining_NN_MPI.py:91), one instance per rank, replicas kept in
lockstep only because the applied gradient is identical (SURVEY.md C6).  Here
the optimizer is a pure function over pytrees — ``init(params) -> state`` and
``update(grads, state, params) -> (new_params, new_state)`` — so there is one
*logical* optimizer whose state is replicated (or fsdp-sharded) by sharding
annotations, and the lockstep property is by construction.

``sgd`` reproduces torch SGD semantics exactly (dampening=0, no Nesterov):

    buf   <- momentum * buf + grad        (buf = grad on first step)
    param <- param - lr * buf

which is what keeps the parity test (tests/test_parity.py) bit-exact against
the reference algorithm.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp

Pytree = Any
# constant lr or a jax-traceable step -> lr schedule (ops.schedules)
LR = Union[float, Callable[[jax.Array], jax.Array]]


def _lr_at(lr: LR, count: jax.Array) -> jax.Array:
    return lr(count) if callable(lr) else jnp.asarray(lr, jnp.float32)


def global_norm(grads: Pytree) -> jax.Array:
    """L2 norm over every leaf of the gradient pytree (float32 accum)."""
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


def clip_by_global_norm(grads: Pytree, max_norm: float) -> Pytree:
    """Scale the whole pytree so its global L2 norm is <= ``max_norm``.

    Called on *reduced* (post-psum) gradients inside the train step, so the
    norm is the true global-batch gradient norm on every replica — there is
    no per-shard clipping inconsistency.
    """
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


@dataclasses.dataclass(frozen=True)
class Optimizer:
    init: Callable[[Pytree], Pytree]
    update: Callable[[Pytree, Pytree, Pytree], Tuple[Pytree, Pytree]]
    name: str = "optimizer"
    # maps a param PartitionSpec tree -> an opt-state PartitionSpec tree;
    # per-param slots (momentum, mu, nu) inherit the param's sharding so
    # TP/FSDP shard optimizer state exactly like the params they mirror.
    # Signature: state_specs(pspecs, params=None) — optimizers whose state
    # layout depends on leaf SHAPES (adafactor's factored slots) need the
    # param tree; mirror-layout optimizers ignore it, and callers that
    # cannot supply one (the zero1 flat-buffer path) pass None
    state_specs: Optional[Callable[..., Pytree]] = None
    # update(grads, state, params, norm) for wrappers whose decision
    # depends on the global gradient norm (with_skip_guard): a caller that
    # already computed the norm — the telemetry metrics path — hands it in
    # so the step pays ONE norm reduction, not two.  None for optimizers
    # that have no use for it; callers fall back to plain ``update``.
    update_with_norm: Optional[Callable[..., Tuple[Pytree, Pytree]]] = None


class SGDState(NamedTuple):
    count: jax.Array      # optimizer steps taken (drives lr schedules)
    momentum_buf: Pytree  # matches torch's momentum_buffer


def sgd(lr: LR, momentum: float = 0.0, weight_decay: float = 0.0) -> Optimizer:
    """torch-semantics SGD (see module docstring); ``lr`` may be a schedule."""

    def init(params: Pytree) -> SGDState:
        return SGDState(jnp.zeros((), jnp.int32),
                        jax.tree_util.tree_map(jnp.zeros_like, params))

    def update(grads: Pytree, state: SGDState, params: Pytree):
        lr_t = _lr_at(lr, state.count)
        if weight_decay:
            grads = jax.tree_util.tree_map(
                lambda g, p: g + weight_decay * p, grads, params)
        if momentum:
            buf = jax.tree_util.tree_map(
                lambda b, g: momentum * b + g, state.momentum_buf, grads)
            step = buf
        else:
            buf = state.momentum_buf
            step = grads
        # multiply in f32 then cast: lr_t is a strong f32 scalar, and naive
        # promotion would silently upcast bf16 params
        new_params = jax.tree_util.tree_map(
            lambda p, s: (p - (lr_t * s.astype(jnp.float32)).astype(p.dtype)),
            params, step)
        return new_params, SGDState(state.count + 1, buf)

    def state_specs(ps, params=None):
        from jax.sharding import PartitionSpec

        return SGDState(PartitionSpec(), ps)

    return Optimizer(init, update, f"sgd(lr={lr},m={momentum})",
                     state_specs=state_specs)


class AdamState(NamedTuple):
    count: jax.Array
    mu: Pytree
    nu: Pytree


def adam(lr: LR, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
         weight_decay: float = 0.0, decoupled: bool = False) -> Optimizer:
    """Adam / AdamW (``decoupled=True``); ``lr`` may be a schedule."""

    def init(params: Pytree) -> AdamState:
        zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return AdamState(jnp.zeros((), jnp.int32), zeros(), zeros())

    def update(grads: Pytree, state: AdamState, params: Pytree):
        lr_t = _lr_at(lr, state.count)
        if weight_decay and not decoupled:
            grads = jax.tree_util.tree_map(
                lambda g, p: g + weight_decay * p, grads, params)
        count = state.count + 1
        t = count.astype(jnp.float32)
        mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                    state.mu, grads)
        nu = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                                    state.nu, grads)
        mu_hat = jax.tree_util.tree_map(lambda m: m / (1 - b1 ** t), mu)
        nu_hat = jax.tree_util.tree_map(lambda v: v / (1 - b2 ** t), nu)
        def step(p, m, v):
            upd = m / (jnp.sqrt(v) + eps)
            if weight_decay and decoupled:
                upd = upd + weight_decay * p
            return p - (lr_t * upd).astype(p.dtype)
        new_params = jax.tree_util.tree_map(step, params, mu_hat, nu_hat)
        return new_params, AdamState(count, mu, nu)

    def state_specs(ps, params=None):
        from jax.sharding import PartitionSpec

        return AdamState(PartitionSpec(), ps, ps)

    return Optimizer(init, update,
                     f"{'adamw' if decoupled else 'adam'}(lr={lr})",
                     state_specs=state_specs)


def adamw(lr: LR, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
          weight_decay: float = 0.01) -> Optimizer:
    return adam(lr, b1, b2, eps, weight_decay, decoupled=True)


class LionState(NamedTuple):
    count: jax.Array
    momentum: Pytree


def lion(lr: LR, b1: float = 0.9, b2: float = 0.99,
         weight_decay: float = 0.0) -> Optimizer:
    """Lion (EvoLved Sign Momentum, Chen et al. 2023): the update is the
    SIGN of a b1-interpolated momentum, the state a single f32 slot —
    half Adam's optimizer memory, and the sign makes the update magnitude
    uniform across params (weight decay is decoupled, as in the paper).
    TPU-friendly: elementwise sign/interp fuse into the update kernel."""

    def init(params: Pytree) -> LionState:
        return LionState(jnp.zeros((), jnp.int32),
                         jax.tree_util.tree_map(
                             lambda p: jnp.zeros_like(p, jnp.float32),
                             params))

    def update(grads: Pytree, state: LionState, params: Pytree):
        lr_t = _lr_at(lr, state.count)

        def step(p, m, g):
            g32 = g.astype(jnp.float32)
            upd = jnp.sign(b1 * m + (1 - b1) * g32)
            if weight_decay:
                upd = upd + weight_decay * p.astype(jnp.float32)
            return p - (lr_t * upd).astype(p.dtype)

        new_params = jax.tree_util.tree_map(step, params, state.momentum,
                                            grads)
        new_m = jax.tree_util.tree_map(
            lambda m, g: b2 * m + (1 - b2) * g.astype(jnp.float32),
            state.momentum, grads)
        return new_params, LionState(state.count + 1, new_m)

    def state_specs(ps, params=None):
        from jax.sharding import PartitionSpec

        return LionState(PartitionSpec(), ps)

    return Optimizer(init, update, f"lion(lr={lr})",
                     state_specs=state_specs)


class AdafactorState(NamedTuple):
    count: jax.Array
    vr: Pytree  # row factor (shape p.shape[:-1]) for ndim>=2 leaves, else ()
    vc: Pytree  # col factor (shape p.shape[:-2] + (p.shape[-1],)), else ()
    v: Pytree   # full second moment for ndim<2 leaves, else () placeholder
    mu: Pytree  # momentum (b1 > 0) mirroring params, else () placeholder


def adafactor(lr: LR, b1: float = 0.0, decay_pow: float = 0.8,
              eps1: float = 1e-30, eps2: float = 1e-3,
              clip_threshold: float = 1.0, weight_decay: float = 0.0,
              multiply_by_parameter_scale: bool = True) -> Optimizer:
    """Adafactor (Shazeer & Stern 2018) — the TPU-era memory-factored
    optimizer: for matrix-shaped leaves the second moment is stored as a
    rank-1 outer product of row/column exponential averages (O(n+m) state
    instead of O(nm); leading dims of >2-D leaves, e.g. stacked experts or
    conv kernels, are treated as batch).  Increasing decay
    ``b2_t = 1 - t^-decay_pow`` (no bias correction needed), update-RMS
    clipping at ``clip_threshold``, and optional parameter-scale-relative
    steps (``max(eps2, RMS(p)) * lr``).  ``b1 > 0`` adds a full first
    moment applied to the scaled update, as in the paper's momentum
    variant.

    Sharding: factored stats are means over the factored (last two) dims,
    so they are exact under GSPMD global-view layouts and under shard_map
    layouts that replicate every leaf (plain DP).  Layouts that slice
    *inside* matrices (pipeline / seq x tensor / expert x tensor) make the
    factor means shard-local, and even the leading-dim expert slicing is
    not exact: the update-RMS clip and ``multiply_by_parameter_scale``
    RMS(p) are means over the WHOLE leaf, so on an expert-sharded stack
    they cover only the local expert slice (EP-degree-dependent), and a
    2-D expert-stacked bias (E, f) has its column factor averaged over the
    sharded E dim.  The Trainer rejects all of these combinations."""

    def _factored(p) -> bool:
        return jnp.ndim(p) >= 2

    def init(params: Pytree) -> AdafactorState:
        z = lambda: jnp.zeros((), jnp.float32)
        tm = jax.tree_util.tree_map
        return AdafactorState(
            jnp.zeros((), jnp.int32),
            tm(lambda p: jnp.zeros(p.shape[:-1], jnp.float32)
               if _factored(p) else z(), params),
            tm(lambda p: jnp.zeros(p.shape[:-2] + p.shape[-1:], jnp.float32)
               if _factored(p) else z(), params),
            tm(lambda p: z() if _factored(p)
               else jnp.zeros(p.shape, jnp.float32), params),
            tm(lambda p: jnp.zeros(p.shape, jnp.float32) if b1 else z(),
               params),
        )

    def update(grads: Pytree, state: AdafactorState, params: Pytree):
        lr_t = _lr_at(lr, state.count)
        count = state.count + 1
        t = count.astype(jnp.float32)
        b2t = 1.0 - t ** (-decay_pow)

        def one(p, g, r, c, v, m):
            g32 = g.astype(jnp.float32)
            g2 = g32 * g32 + eps1
            if _factored(p):
                r_new = b2t * r + (1 - b2t) * g2.mean(-1)
                c_new = b2t * c + (1 - b2t) * g2.mean(-2)
                # V ~ (R x C) / mean(R): the paper's minimal-KL rank-1
                # reconstruction (mean over rows == mean over cols == the
                # full mean, so either normalizer works)
                vhat = (r_new[..., :, None] * c_new[..., None, :]
                        / jnp.maximum(r_new.mean(-1, keepdims=True),
                                      eps1)[..., None])
                v_new = v
            else:
                v_new = b2t * v + (1 - b2t) * g2
                vhat = v_new
                r_new, c_new = r, c
            # clamp: for never-updated rows (unused vocab/position entries)
            # the rank-1 product r*c ~ eps1 * c underflows f32 subnormals
            # and flushes to zero -> 0/0 NaN; the floor keeps u = 0 there
            u = g32 / jnp.sqrt(jnp.maximum(vhat, eps1))
            rms_u = jnp.sqrt(jnp.mean(jnp.square(u)))
            u = u / jnp.maximum(1.0, rms_u / clip_threshold)
            if multiply_by_parameter_scale:
                scale = jnp.maximum(
                    eps2, jnp.sqrt(jnp.mean(jnp.square(
                        p.astype(jnp.float32)))))
            else:
                scale = jnp.asarray(1.0, jnp.float32)
            step_v = lr_t * scale * u
            if b1:
                m_new = b1 * m + (1 - b1) * step_v
                step_v = m_new
            else:
                m_new = m
            if weight_decay:
                step_v = step_v + (lr_t * weight_decay
                                   * p.astype(jnp.float32))
            return (p - step_v.astype(p.dtype), r_new, c_new, v_new, m_new)

        tm = jax.tree_util.tree_map
        out = tm(one, params, grads, state.vr, state.vc, state.v, state.mu)
        pick = lambda i: tm(lambda _, o: o[i], params, out)
        return pick(0), AdafactorState(count, pick(1), pick(2), pick(3),
                                       pick(4))

    def state_specs(ps, params=None):
        from jax.sharding import PartitionSpec as P

        if params is None:
            raise ValueError(
                "adafactor's state layout depends on param shapes, but this "
                "caller passed no param tree to state_specs (the zero1 flat "
                "buffer and the pipeline spec paths call it one-arg) — use "
                "sgd/adam/adamw/lion on those layouts")
        is_p = lambda x: isinstance(x, P)
        tm = lambda f: jax.tree_util.tree_map(f, ps, params, is_leaf=is_p)

        def pad(s, nd):
            tup = tuple(s)
            return tup + (None,) * (nd - len(tup))

        def strip(tup):  # P(None) == P() semantically; normalize
            while tup and tup[-1] is None:
                tup = tup[:-1]
            return tup

        vr = tm(lambda s, p: P(*strip(pad(s, p.ndim)[:-1])) if p.ndim >= 2
                else P())
        vc = tm(lambda s, p: P(*strip(pad(s, p.ndim)[:-2]
                                      + (pad(s, p.ndim)[-1],)))
                if p.ndim >= 2 else P())
        v = tm(lambda s, p: P() if p.ndim >= 2 else s)
        mu = tm(lambda s, p: s if b1 else P())
        return AdafactorState(P(), vr, vc, v, mu)

    return Optimizer(init, update, f"adafactor(lr={lr},b1={b1})",
                     state_specs=state_specs)


class GuardedState(NamedTuple):
    """Opt state of :func:`with_skip_guard`: the wrapped optimizer's state
    plus a cumulative count of *rejected* updates.  Lives inside the jitted
    step, so the skip decision costs no host round-trip; the host reads
    ``skipped`` only off the hot path (end of training / rollback)."""

    skipped: jax.Array  # int32 scalar — updates rejected so far
    inner: Pytree


def with_skip_guard(opt: Optimizer, skip_threshold: float = 0.0) -> Optimizer:
    """Guard the wrapped update against non-finite (and optionally huge)
    gradients: the update runs under a ``lax.cond`` on a scalar predicate
    computed from the *global* gradient norm, so a bad step is a bitwise
    no-op on params and inner optimizer state on every replica
    identically — and the happy path pays only the norm reduction.

    The predicate is ``isfinite(global_norm(grads))`` and, when
    ``skip_threshold > 0``, additionally ``global_norm <= skip_threshold``
    (measured on the raw reduced gradients, before any ``with_clipping``
    the guard wraps — clipping would mask the anomaly the threshold is
    there to catch).

    Correctness requires the skip PREDICATE to be identical on every
    shard that holds a given parameter.  That holds wherever the update
    runs on fully-reduced (post-psum) or global-view gradients — the
    shard_map DP / DP x SP paths and the GSPMD path — and on the
    sharded-update layouts (zero1's scattered flat shard, the per-leaf
    ``update_sharding='sharded'`` path), which compute the GLOBAL norm
    from psum'd shard squares inside the step and hand it in via
    ``update_with_norm``.  Layouts whose update consumes axis-sharded
    slices without that psum'd norm (pipeline stages, expert/tensor
    slicing) would make the decision shard-local and divergent; the
    Trainer refuses the guard there.

    Semantics on a skipped step: ``TrainState.step`` still advances (it
    counts attempted steps and drives the data order), while the inner
    optimizer's ``count`` — and therefore the lr schedule — does not
    (optimizer steps = applied updates).  ``GuardedState.skipped`` counts
    the rejections.
    """

    def init(params: Pytree) -> GuardedState:
        return GuardedState(jnp.zeros((), jnp.int32), opt.init(params))

    def update_with_norm(grads: Pytree, state: GuardedState, params: Pytree,
                         norm: jax.Array):
        """The guard with a caller-supplied global grad norm (the telemetry
        metrics path computes it anyway — one reduction, shared)."""
        from jax import lax

        ok = jnp.isfinite(norm)
        if skip_threshold > 0:
            ok = ok & (norm <= skip_threshold)

        # lax.cond rather than tree_map(where): the predicate is a traced
        # device scalar (no host divergence), and on the happy path only
        # the apply branch's work runs — a where-select would add a full
        # extra read+write pass over params AND optimizer state every
        # step (measured +24% on a dispatch-bound CPU micro-model; the
        # cond form is noise-level)
        def apply(_):
            new_params, new_inner = opt.update(grads, state.inner, params)
            return new_params, GuardedState(state.skipped, new_inner)

        def skip(_):
            return params, GuardedState(state.skipped + 1, state.inner)

        return lax.cond(ok, apply, skip, None)

    def update(grads: Pytree, state: GuardedState, params: Pytree):
        return update_with_norm(grads, state, params, global_norm(grads))

    def state_specs(ps, params=None):
        from jax.sharding import PartitionSpec

        if opt.state_specs is None:
            raise ValueError(f"{opt.name} lacks state_specs")
        return GuardedState(PartitionSpec(), opt.state_specs(ps, params))

    return Optimizer(init, update,
                     f"guard(thr={skip_threshold}):{opt.name}",
                     state_specs=state_specs,
                     update_with_norm=update_with_norm)


class MasterState(NamedTuple):
    """Opt state of :func:`with_master_weights`: the f32 master copy of
    the parameters plus the wrapped optimizer's state (itself built over
    the master copy, so every slot is f32)."""

    master: Pytree
    inner: Pytree


def with_master_weights(opt: Optimizer) -> Optimizer:
    """Mixed-precision master weights (arXiv 2004.13336 / 2204.06514):
    the visible parameters may live in a storage dtype (bf16), while the
    optimizer updates an f32 MASTER copy kept in its own state; each step
    re-casts the updated master into the storage dtype.  The bf16 params
    never accumulate rounding drift across steps — precision loss is one
    f32->bf16 cast per step, from a master that never loses bits.

    Intended for ``update_sharding='sharded'`` layouts, where the opt
    state (master included) is scattered 1/N per replica — a REPLICATED
    master would duplicate param memory and forfeit the point; the
    Trainer enforces that pairing.  The wrapped update consumes the
    incoming ``params`` only for the output storage dtype.
    """

    def init(params: Pytree) -> MasterState:
        # jnp.array(copy=True), not astype: astype is an identity for
        # params ALREADY f32, which would alias the master to the very
        # param buffers it shadows — a donated train state then donates
        # the same buffer twice and Execute() refuses (latent until an
        # f32-params + master-weights combination actually ran)
        master = jax.tree_util.tree_map(
            lambda p: jnp.array(p, jnp.float32, copy=True), params)
        return MasterState(master, opt.init(master))

    def update(grads: Pytree, state: MasterState, params: Pytree):
        g32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        new_master, new_inner = opt.update(g32, state.inner, state.master)
        new_params = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype), new_master, params)
        return new_params, MasterState(new_master, new_inner)

    def state_specs(ps, params=None):
        if opt.state_specs is None:
            raise ValueError(f"{opt.name} lacks state_specs")
        return MasterState(ps, opt.state_specs(ps, params))

    return Optimizer(init, update, f"master:{opt.name}",
                     state_specs=state_specs)


def with_clipping(opt: Optimizer, max_norm: float) -> Optimizer:
    """Clip gradients by global L2 norm before the wrapped update.

    Intended to wrap the *reduced* gradients (the train steps call
    ``optimizer.update`` after psum), so every replica clips by the same
    global-batch norm.
    """
    if max_norm <= 0:
        return opt

    def update(grads, state, params):
        return opt.update(clip_by_global_norm(grads, max_norm), state, params)

    return Optimizer(opt.init, update, f"clip({max_norm}):{opt.name}",
                     state_specs=opt.state_specs)


def make(name: str, lr: LR, momentum: float = 0.0,
         weight_decay: float = 0.0, grad_clip: float = 0.0) -> Optimizer:
    """Build from config strings (config.TrainConfig.optimizer).  ``lr`` may
    be a constant or a schedule from ``ops.schedules.make``."""
    if name == "sgd":
        opt = sgd(lr, momentum, weight_decay)
    elif name == "adam":
        opt = adam(lr, weight_decay=weight_decay)
    elif name == "adamw":
        opt = adamw(lr, weight_decay=weight_decay or 0.01)
    elif name == "lion":
        opt = lion(lr, weight_decay=weight_decay)
    elif name == "adafactor":
        # classic Adafactor: b1=0, no first moment — inheriting the CLI's
        # --momentum (default 0.9, an SGD knob) would silently allocate a
        # full-size momentum slot and forfeit the factored-memory point;
        # the momentum variant stays available via optim.adafactor(b1=...)
        opt = adafactor(lr, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    return with_clipping(opt, grad_clip)
