"""Pure-pytree optimizers.

The reference uses ``torch.optim.SGD(model.parameters(), lr, momentum)``
(dataParallelTraining_NN_MPI.py:91), one instance per rank, replicas kept in
lockstep only because the applied gradient is identical (SURVEY.md C6).  Here
the optimizer is a pure function over pytrees — ``init(params) -> state`` and
``update(grads, state, params) -> (new_params, new_state)`` — so there is one
*logical* optimizer whose state is replicated (or fsdp-sharded) by sharding
annotations, and the lockstep property is by construction.

``sgd`` reproduces torch SGD semantics exactly (dampening=0, no Nesterov):

    buf   <- momentum * buf + grad        (buf = grad on first step)
    param <- param - lr * buf

which is what keeps the parity test (tests/test_parity.py) bit-exact against
the reference algorithm.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

Pytree = Any


@dataclasses.dataclass(frozen=True)
class Optimizer:
    init: Callable[[Pytree], Pytree]
    update: Callable[[Pytree, Pytree, Pytree], Tuple[Pytree, Pytree]]
    name: str = "optimizer"
    # maps a param PartitionSpec tree -> an opt-state PartitionSpec tree;
    # per-param slots (momentum, mu, nu) inherit the param's sharding so
    # TP/FSDP shard optimizer state exactly like the params they mirror
    state_specs: Optional[Callable[[Pytree], Pytree]] = None


class SGDState(NamedTuple):
    momentum_buf: Pytree  # matches torch's momentum_buffer


def sgd(lr: float, momentum: float = 0.0, weight_decay: float = 0.0) -> Optimizer:
    """torch-semantics SGD (see module docstring)."""

    def init(params: Pytree) -> SGDState:
        return SGDState(jax.tree_util.tree_map(jnp.zeros_like, params))

    def update(grads: Pytree, state: SGDState, params: Pytree):
        if weight_decay:
            grads = jax.tree_util.tree_map(
                lambda g, p: g + weight_decay * p, grads, params)
        if momentum:
            buf = jax.tree_util.tree_map(
                lambda b, g: momentum * b + g, state.momentum_buf, grads)
            step = buf
        else:
            buf = state.momentum_buf
            step = grads
        new_params = jax.tree_util.tree_map(
            lambda p, s: p - lr * s.astype(p.dtype), params, step)
        return new_params, SGDState(buf)

    return Optimizer(init, update, f"sgd(lr={lr},m={momentum})",
                     state_specs=lambda ps: SGDState(ps))


class AdamState(NamedTuple):
    count: jax.Array
    mu: Pytree
    nu: Pytree


def adam(lr: float, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
         weight_decay: float = 0.0, decoupled: bool = False) -> Optimizer:
    """Adam / AdamW (``decoupled=True``)."""

    def init(params: Pytree) -> AdamState:
        zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return AdamState(jnp.zeros((), jnp.int32), zeros(), zeros())

    def update(grads: Pytree, state: AdamState, params: Pytree):
        if weight_decay and not decoupled:
            grads = jax.tree_util.tree_map(
                lambda g, p: g + weight_decay * p, grads, params)
        count = state.count + 1
        t = count.astype(jnp.float32)
        mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                    state.mu, grads)
        nu = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                                    state.nu, grads)
        mu_hat = jax.tree_util.tree_map(lambda m: m / (1 - b1 ** t), mu)
        nu_hat = jax.tree_util.tree_map(lambda v: v / (1 - b2 ** t), nu)
        def step(p, m, v):
            upd = m / (jnp.sqrt(v) + eps)
            if weight_decay and decoupled:
                upd = upd + weight_decay * p
            return p - lr * upd.astype(p.dtype)
        new_params = jax.tree_util.tree_map(step, params, mu_hat, nu_hat)
        return new_params, AdamState(count, mu, nu)

    def state_specs(ps):
        from jax.sharding import PartitionSpec

        return AdamState(PartitionSpec(), ps, ps)

    return Optimizer(init, update,
                     f"{'adamw' if decoupled else 'adam'}(lr={lr})",
                     state_specs=state_specs)


def adamw(lr: float, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
          weight_decay: float = 0.01) -> Optimizer:
    return adam(lr, b1, b2, eps, weight_decay, decoupled=True)


def make(name: str, lr: float, momentum: float = 0.0,
         weight_decay: float = 0.0) -> Optimizer:
    """Build from config strings (config.TrainConfig.optimizer)."""
    if name == "sgd":
        return sgd(lr, momentum, weight_decay)
    if name == "adam":
        return adam(lr, weight_decay=weight_decay)
    if name == "adamw":
        return adamw(lr, weight_decay=weight_decay or 0.01)
    raise ValueError(f"unknown optimizer {name!r}")
