"""Rotary position embeddings (RoPE — Su et al. 2021, RoFormer).

Instead of ADDING a learned position vector to the token embedding (the
reference-era convention this framework's default keeps), RoPE rotates
each query/key head pair-wise by an angle proportional to its absolute
position; the q·k contraction then depends only on the RELATIVE distance
m − n, which is what attention actually wants.  TPU-friendly by
construction: the rotation is a fused elementwise multiply-add on the
(…, head_dim) tile — no gather, no position table streamed from HBM, no
extra parameters (and so nothing for the optimizer/checkpoint to carry).

Applied OUTSIDE the attention kernels, on q/k right after the head
split: every impl (dense, Pallas flash, ring, striped, Ulysses) then
works unchanged, because a token's rotation depends only on its own
global position — under sequence parallelism each shard rotates its
local tokens by their global positions before any collective, and the
already-rotated K travels the ring.  Decode rotates the single new
position and caches the rotated key (standard practice), so cached keys
never need re-rotation.

Half-split convention: the head dim is split as [x1 | x2] and rotated as
(x1·cos − x2·sin, x1·sin + x2·cos) — self-consistent within this
framework (checkpoints trained here decode here; no external-weight
layout to match).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

DEFAULT_THETA = 10000.0


def rope_angles(positions: jax.Array, head_dim: int,
                theta: float = DEFAULT_THETA):
    """(cos, sin) tables for ``positions`` (any shape P) and an even
    ``head_dim`` -> each (*P, head_dim // 2) in f32."""
    if head_dim % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {head_dim}")
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(ang), jnp.sin(ang)


def rope_rotate(x: jax.Array, positions: jax.Array,
                theta: float = DEFAULT_THETA) -> jax.Array:
    """Rotate q or k (..., T, H, D) by per-token ``positions``.

    ``positions`` is (T,) (one sequence of global positions — the
    training path, where sequence-parallel shards pass their own global
    slice) or (B, T) (per-row positions — the decode paths, where every
    batch row sits at its own depth).  Output dtype matches ``x``."""
    cos, sin = rope_angles(positions, x.shape[-1], theta)
    # broadcast over batch (T,) case and insert the heads axis
    if positions.ndim == 1:
        cos, sin = cos[None], sin[None]            # (1, T, half)
    cos, sin = cos[:, :, None, :], sin[:, :, None, :]  # (B|1, T, 1, half)
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos],
        axis=-1).astype(x.dtype)
