"""Loss functions with masked reduction.

The reference uses ``nn.MSELoss()`` (dataParallelTraining_NN_MPI.py:94) —
a plain mean over the local shard.  Here every loss returns ``(sum, count)``
under an optional validity mask so the caller chooses the reduction:

* local mean             ``sum / count``                      (reference :173)
* exact global mean      ``psum(sum) / psum(count)``          (fixes the
  reference's small-shard bias, SURVEY.md §7 "hard parts": averaging unequal
  per-shard means at :190-197 is not the global-batch gradient)

Masking exists because uneven datasets are zero-padded to equal per-device
shapes (parallel.sharding.pad_to_multiple) — padded rows must contribute
nothing to either sum or count.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _masked(per_example: jax.Array, mask: Optional[jax.Array]) -> Tuple[jax.Array, jax.Array]:
    per_example = per_example.astype(jnp.float32)
    if mask is None:
        return per_example.sum(), jnp.asarray(per_example.size, jnp.float32)
    mask = mask.astype(jnp.float32)
    return (per_example * mask).sum(), mask.sum()


def mse(pred: jax.Array, target: jax.Array,
        mask: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Squared-error (sum, count) over examples.  ``pred``/``target`` are
    ``(B, ...)``; per-example error is the mean over trailing dims, matching
    ``nn.MSELoss`` semantics on ``(B, 1)`` outputs (reference :160, :173)."""
    err = (pred.astype(jnp.float32) - target.astype(jnp.float32)) ** 2
    per_example = err.reshape(err.shape[0], -1).mean(axis=-1)
    return _masked(per_example, mask)


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          mask: Optional[jax.Array] = None,
                          label_smoothing: float = 0.0
                          ) -> Tuple[jax.Array, jax.Array]:
    """Cross-entropy (sum, count) with integer labels.  ``logits`` is
    ``(B, C)`` or ``(B, T, C)`` with ``labels`` ``(B,)`` / ``(B, T)``; for the
    sequence case the mask is broadcast over T (all tokens of a padded row are
    masked).  ``label_smoothing`` mixes the one-hot target with the uniform
    distribution: target = (1 - s) * onehot + s / C."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    if label_smoothing > 0.0:
        s = label_smoothing
        # CE against the smoothed target distribution:
        #   logz - [(1 - s) * gold + (s / C) * sum_c logit_c]
        nll = logz - (1.0 - s) * gold - s * logits.mean(axis=-1)
    else:
        nll = logz - gold  # (B,) or (B, T)
    return reduce_token_nll(nll, mask)


def reduce_token_nll(nll: jax.Array, mask: Optional[jax.Array]
                     ) -> Tuple[jax.Array, jax.Array]:
    """Token-level (sum, count) reduction of a per-token loss ``nll``
    ((B,) or (B, T, ...)) with a per-example mask broadcast over the token
    dims — the tail of :func:`softmax_cross_entropy`, shared with the
    vocab-parallel sharded cross-entropy (parallel.megatron) so the two
    cannot disagree on mask semantics."""
    if nll.ndim > 1:
        if mask is not None:
            mask = jnp.broadcast_to(mask.reshape(mask.shape + (1,) * (nll.ndim - 1)),
                                    nll.shape)
        nll = nll.reshape(nll.shape[0], -1)
        mask = None if mask is None else mask.reshape(mask.shape[0], -1)
        per = nll if mask is None else nll * mask
        s = per.sum()
        c = jnp.asarray(nll.size, jnp.float32) if mask is None else mask.sum()
        return s, c
    return _masked(nll, mask)


def accuracy(logits: jax.Array, labels: jax.Array,
             mask: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Correct-prediction (sum, count) — an eval metric, realizing the intent
    of the reference's dead validation code (:213-236)."""
    hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    return reduce_example_hits(hit, mask)


def reduce_example_hits(hit: jax.Array, mask: Optional[jax.Array]
                        ) -> Tuple[jax.Array, jax.Array]:
    """Example-level (sum, count) reduction of a per-token hit tensor
    ((B,) or (B, T, ...)): per-example mean over token dims, then the
    per-example mask — the tail of :func:`accuracy`, shared with the
    vocab-parallel sharded accuracy (parallel.megatron) so the two cannot
    disagree on reduction semantics."""
    hit = hit.reshape(hit.shape[0], -1).mean(axis=-1)
    return _masked(hit, mask)


LOSSES = {"mse": mse, "cross_entropy": softmax_cross_entropy}


def get(name: str):
    """Loss by name.  ``"cross_entropy@0.1"`` selects cross-entropy with
    label smoothing 0.1 — the suffix form lets every step builder stay a
    plain ``loss_name: str`` consumer (the Trainer composes the string
    from ``--label_smoothing``; eval always uses the unsmoothed loss)."""
    if "@" in name:
        base, _, s = name.partition("@")
        if base != "cross_entropy":
            raise ValueError(f"label smoothing only applies to "
                             f"cross_entropy, got {name!r}")
        import functools

        return functools.partial(LOSSES[base], label_smoothing=float(s))
    try:
        return LOSSES[name]
    except KeyError:
        raise ValueError(f"unknown loss {name!r}; have {sorted(LOSSES)}") from None
