"""Weights-only int8 post-training quantization for inference.

The reference has no inference path at all (its validation/test blocks are
dead code, dataParallelTraining_NN_MPI.py:213-236); this module is a
TPU-first extension to the serving side of the framework.  The motivation
is bandwidth, not arithmetic: autoregressive decode at batch sizes below
the MXU's arithmetic-intensity knee is bound by streaming the weight
matrices from HBM once per token, so storing ``W`` as int8 (+ one f32
scale per output channel) halves the bytes per token versus bf16 and
~quarters them versus f32 — a direct tokens/sec lever on v5e's ~819 GB/s
HBM.  The matmul itself stays bf16 on the MXU: ``x @ W_q`` with the int8
weights cast in-register, then the per-output-channel scale folded into
the product.  Per-OUTPUT-channel symmetric scales are chosen exactly
because they commute through the contraction::

    (x @ (W_q * s))[..., o] == (x @ W_q)[..., o] * s[o]

so dequantization is one fused multiply on the (small) output tile, never
a materialized f32 copy of the weights.

Training is deliberately out of scope (straight-through estimators etc.
belong to QAT, not PTQ): :func:`quantize_params` is applied to a trained
(or restored) parameter pytree, and ``models.core.Linear.apply`` consumes
the quantized form transparently — any leaf dict carrying ``w_scale``
multiplies it back in after the matmul, so every decode path built on the
shared modules (models.generate's KV-cache loop, generate_sharded's GSPMD
program) picks it up with zero per-path wiring.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Pytree = Any

# parameter-dict keys that mark a quantizable dense kernel: Linear stores
# {"w": (in, out)[, "b": (out,)]} (models/core.py); LayerNorm stores
# {"scale", "bias"} and Embedding {"table"}, neither of which matches.
_KERNEL_KEY = "w"
_SCALE_KEY = "w_scale"

# Subtrees that look like Linear params but are consumed RAW by their
# module (no Linear.apply, so a w_scale would be silently dropped), or
# whose numerics are too routing-critical to round: the MoE router gate
# ({"w": (d, E)}, models/moe.py::_route does its own f32 matmul).  It is
# O(d*E) — no bandwidth to win — so skipping costs nothing.
_NEVER_QUANTIZE = ("gate",)


def quantize_array(w: jax.Array, axis: int = -2
                   ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8 quantization of a dense kernel.

    ``axis`` is the contraction (input-feature) axis that the scale must
    NOT span — the default -2 matches Linear's ``(in, out)`` layout and,
    unchanged, the scan-stacked ``(n_layers, in, out)`` layout (the layer
    axis keeps per-layer scales, which slice correctly inside the scan).

    Returns ``(q, scale)`` with ``q`` int8 in [-127, 127] (symmetric:
    -128 unused so negation is exact) and ``scale`` f32 shaped like ``w``
    with ``axis`` removed; ``q * scale[..., None-at-axis]`` reconstructs
    ``w`` to within ``scale/2`` per element.
    """
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w.astype(jnp.float32)
                           / jnp.expand_dims(scale, axis)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_array(q: jax.Array, scale: jax.Array,
                     axis: int = -2) -> jax.Array:
    """Inverse of :func:`quantize_array` (f32)."""
    return q.astype(jnp.float32) * jnp.expand_dims(scale, axis)


def _is_linear_params(node: Dict) -> bool:
    w = node.get(_KERNEL_KEY)
    # ndim 2 = plain Linear (in, out); ndim 3 = scan-stacked blocks
    # (n_layers, in, out).  Already-quantized dicts are skipped so the
    # transform is idempotent.
    return (w is not None and getattr(w, "ndim", 0) in (2, 3)
            and _SCALE_KEY not in node
            and jnp.issubdtype(jnp.asarray(w).dtype, jnp.floating))


def _is_expert_params(node: Dict) -> bool:
    # MoEFFN expert kernels {"w_in": (E, d, f), "w_out": (E, f, d), ...}
    # (models/moe.py) — the bulk of an MoE model's parameter bytes, so
    # skipping them would forfeit most of the decode bandwidth win.
    # quantize_array's default contraction axis (-2) gives the needed
    # per-(expert, out-column) scales; models/moe.py::_experts_ffn folds
    # them back in after each einsum.
    w_in, w_out = node.get("w_in"), node.get("w_out")
    return (w_in is not None and w_out is not None
            and getattr(w_in, "ndim", 0) == 3
            and getattr(w_out, "ndim", 0) == 3
            and "w_in_scale" not in node
            and jnp.issubdtype(jnp.asarray(w_in).dtype, jnp.floating))


def quantize_params(params: Pytree,
                    skip: Sequence[str] = ()) -> Pytree:
    """Walk a model parameter pytree and quantize every dense kernel.

    Every dict node shaped like Linear params (``{"w": ndim-2/3 float
    array, ...}``) gains ``w_scale`` and an int8 ``w``; biases,
    LayerNorms, and embedding tables are untouched (they are O(d) —
    no bandwidth to win — and carry the numerics that int8 hurts most).

    ``skip`` names path components to leave in full precision, e.g.
    ``("head",)`` to keep the logit projection exact when perplexity
    parity matters more than the head's (d_model x vocab) bytes.  The
    MoE router gate is always skipped (``_NEVER_QUANTIZE``): its module
    consumes ``w`` raw, so a quantized gate would silently drop its
    scale and saturate the routing softmax.
    """
    skip = tuple(skip) + _NEVER_QUANTIZE

    def walk(node, path):
        if isinstance(node, dict):
            if path and path[-1] in skip:
                return node
            if _is_linear_params(node):
                q, s = quantize_array(node[_KERNEL_KEY])
                out = dict(node)
                out[_KERNEL_KEY] = q
                out[_SCALE_KEY] = s
                return out
            if _is_expert_params(node):
                out = dict(node)
                for key in ("w_in", "w_out", "w_gate"):
                    if key not in node:   # w_gate: SwiGLU experts only
                        continue
                    q, s = quantize_array(node[key])
                    out[key] = q
                    out[key + "_scale"] = s
                return out
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v, path) for v in node]
        if isinstance(node, tuple):
            return tuple(walk(v, path) for v in node)
        return node

    return walk(params, ())


def quantized_bytes(params: Pytree) -> int:
    """Total parameter bytes as stored (int8 kernels count 1 byte/elt) —
    the quantity decode bandwidth actually streams."""
    return sum(int(l.size) * jnp.asarray(l).dtype.itemsize
               for l in jax.tree_util.tree_leaves(params))
