"""Learning-rate schedules.

The reference has a single constant learning rate (`--lr`, default 0.001,
dataParallelTraining_NN_MPI.py:245, consumed by ``torch.optim.SGD`` at :91).
Constant stays the default here; warmup + cosine/linear decay are framework
extensions for the larger BASELINE.json configs (MNIST/CIFAR/LM), where a
flat lr is far from standard practice.

A schedule is a jax-traceable ``step -> lr`` function over the *optimizer*
step count (with gradient accumulation, one accumulated update = one step).
Everything is ``jnp``-level so schedules work inside jitted train steps.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Schedule = Callable[[jax.Array], jax.Array]


def constant(lr: float) -> Schedule:
    def sched(step):
        del step
        return jnp.asarray(lr, jnp.float32)

    return sched


def _warmup(step: jax.Array, lr: float, warmup_steps: int) -> jax.Array:
    """Linear 0 -> lr over ``warmup_steps`` (lr at step >= warmup_steps)."""
    if warmup_steps <= 0:
        return jnp.asarray(lr, jnp.float32)
    frac = (step.astype(jnp.float32) + 1.0) / float(warmup_steps)
    return lr * jnp.minimum(frac, 1.0)


def warmup_cosine(lr: float, total_steps: int, warmup_steps: int = 0,
                  min_lr: float = 0.0) -> Schedule:
    """Linear warmup then cosine decay to ``min_lr`` at ``total_steps``."""
    decay_steps = max(total_steps - warmup_steps, 1)

    def sched(step):
        step = jnp.asarray(step)
        warm = _warmup(step, lr, warmup_steps)
        t = jnp.clip((step.astype(jnp.float32) - warmup_steps) / decay_steps,
                     0.0, 1.0)
        cos = min_lr + 0.5 * (lr - min_lr) * (1.0 + jnp.cos(jnp.pi * t))
        return jnp.where(step < warmup_steps, warm, cos)

    return sched


def warmup_linear(lr: float, total_steps: int, warmup_steps: int = 0,
                  min_lr: float = 0.0) -> Schedule:
    """Linear warmup then linear decay to ``min_lr`` at ``total_steps``."""
    decay_steps = max(total_steps - warmup_steps, 1)

    def sched(step):
        step = jnp.asarray(step)
        warm = _warmup(step, lr, warmup_steps)
        t = jnp.clip((step.astype(jnp.float32) - warmup_steps) / decay_steps,
                     0.0, 1.0)
        lin = lr + (min_lr - lr) * t
        return jnp.where(step < warmup_steps, warm, lin)

    return sched


SCHEDULES = {"constant": constant, "cosine": warmup_cosine,
             "linear": warmup_linear}


def make(name: str, lr: float, total_steps: int = 0, warmup_steps: int = 0,
         min_lr: float = 0.0) -> Schedule:
    """Build from config strings (config.TrainConfig.lr_schedule)."""
    if name == "constant":
        return constant(lr)
    if name not in SCHEDULES:
        raise ValueError(f"unknown schedule {name!r}; have {sorted(SCHEDULES)}")
    if total_steps <= 0:
        raise ValueError(f"schedule {name!r} needs total_steps > 0")
    return SCHEDULES[name](lr, total_steps, warmup_steps, min_lr)
