"""Quantized-matmul seam: low-precision compute for training AND serving.

PR 7 landed the *memory* half of mixed precision (bf16 storage, f32
master weights in the sharded opt state, arXiv 2004.13336); this module
is the *compute* half — ROADMAP item 5.  On TPU the MXU's int8/fp8
throughput is a multiple of bf16 (the arithmetic lever the pjit/TPUv4
training recipe of arXiv 2204.06514 assumes), and on the decode path an
int8 activation x weight dot finishes the job ``ops.quant`` started:
the PTQ path saves HBM *bandwidth* but still casts int8->bf16 in
register, paying bf16 MXU rates.

One seam, two consumers:

* **Training** (``--matmul_dtype {bf16,int8,fp8}`` ->
  ``models.core.Linear``): :func:`qdot` runs the dense contraction in
  the quantized domain with a ``custom_vjp`` so the backward is
  low-precision too.

  - ``int8``: symmetric dynamic quantization — activations per-row over
    the contraction dim, weights per-output-channel — int8 x int8 ->
    int32 via ``lax.dot_general(preferred_element_type=int32)``, both
    scales folded on the (small) output tile.  The backward re-derives
    scales for the transposed contractions (a per-channel scale must
    never span the contraction axis, so the forward scales cannot be
    reused).  Stateless: wiring it into a layout touches nothing but
    the model config.
  - ``fp8``: e4m3 activations/weights, e5m2 gradients (the wider-range
    format — gradients are where fp8 under/overflows first).  Weight
    and gradient scales are exact per-tensor amax computed in-step
    (the tensor is in hand); ACTIVATION scales use **delayed-scaling
    calibration**: a per-tensor-role amax history carried as extra
    state leaves in ``TrainState.qstate`` (see :func:`init_qstate`),
    read at the top of the jitted step and updated at the bottom from
    the step's observed amax — so the cast needs no extra pass over
    the activation before scaling it.  Non-finite observations (a
    skipped/overflowed step) never enter the history.

* **Serving** (:func:`int8_serve_dot`, consumed by ``Linear.apply``
  when the params carry ``ops.quant``'s PTQ ``w_scale`` and the model
  was built with ``matmul_dtype='int8'``): a true int8 activation x
  int8 weight dot with dynamic per-token activation scales — the
  decode matmul itself now runs at int8 MXU rates instead of
  dequant-then-bf16.

Scale granularity: activations per-row (per token) for int8 and
per-ROLE per-tensor for fp8 (one amax history per logical matmul site —
qkv/attn_out/ff_in/ff_gate/ff_out/head — shared across a stack's
layers; under ``scan_layers`` the layers share one program anyway, and
a max over layers is simply a conservative per-tensor bound).  Weights
per-output-channel (int8) / per-tensor (fp8).  Attention's score/value
einsums stay in the compute dtype: they are the numerically hot
contractions and carry none of the parameter-streaming cost.

Dtype support is probed once per process (:func:`fp8_dot_supported`):
where the backend cannot lower an fp8 x fp8 dot, the quantized values
are upcast for the contraction — numerics of fp8 STORAGE preserved
(every cast/clip identical), arithmetic in f32; MXU rate claims stay
TPU-only (DESIGN.md §14).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Pytree = Any

FORMATS = ("bf16", "int8", "fp8")

# finite maxima of the fp8 formats (ml_dtypes): e4m3fn has no inf, max
# 448; e5m2 keeps inf/nan, max finite 57344 — gradients get the range.
E4M3_MAX = 448.0
E5M2_MAX = 57344.0
# floor for amax -> scale so an all-zero tensor maps to scale 1-ish
# instead of dividing by zero (mirrors ops.quant.quantize_array)
_AMAX_TINY = 1e-12

# activation-amax history length for fp8 delayed scaling (TransformerEngine
# convention: scale from the max over the last H steps' amax)
HISTORY = 16


def tensor_amax(x: jax.Array) -> jax.Array:
    """f32 scalar max(|x|) with gradients stopped — the calibration
    observation, never part of the differentiated graph."""
    return lax.stop_gradient(
        jnp.max(jnp.abs(x.astype(jnp.float32))))


# ---------------------------------------------------------------------------
# backend capability
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def fp8_dot_supported() -> bool:
    """Can this backend lower an e4m3 x e4m3 -> f32 dot?  Probed by one
    tiny AOT compile outside any trace (cached per process); False routes
    the contraction through an f32 upcast of the SAME quantized values."""
    try:
        a = jnp.zeros((8, 8), jnp.float8_e4m3fn)
        jax.jit(lambda x, y: lax.dot_general(
            x, y, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)).lower(a, a).compile()
        return True
    except Exception:  # noqa: BLE001 — any lowering failure means "no"
        return False


def _dot_q(a: jax.Array, b: jax.Array, preferred) -> jax.Array:
    """dot_general contracting a's last dim with b's first, in the
    quantized domain where the backend supports it."""
    dims = (((a.ndim - 1,), (0,)), ((), ()))
    if a.dtype == jnp.int8 or fp8_dot_supported():
        return lax.dot_general(a, b, dims, preferred_element_type=preferred)
    return lax.dot_general(a.astype(jnp.float32), b.astype(jnp.float32),
                           dims, preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# int8: dynamic symmetric quantization, both directions
# ---------------------------------------------------------------------------

def _q8_rowwise(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Quantize over the LAST (contraction) dim: int8 codes + f32 scale
    shaped like x with the last dim kept at 1 (per-row / per-token).
    The quantizer itself is ops.quant.quantize_array — ONE definition of
    the symmetric formula and its zero-slice guard."""
    from .quant import quantize_array

    q, s = quantize_array(x, axis=-1)
    return q, s[..., None]


def _q8_colwise(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Quantize a (in, out) kernel over its FIRST (contraction) dim:
    per-output-channel scales, shape (1, out) (same single-source
    quantizer as :func:`_q8_rowwise`)."""
    from .quant import quantize_array

    q, s = quantize_array(w, axis=0)
    return q, s[None, :]


def int8_serve_dot(x: jax.Array, w_q: jax.Array,
                   w_scale: jax.Array) -> jax.Array:
    """Decode-path int8 x int8 dot against ``ops.quant`` PTQ weights:
    ``x`` (..., in) float, ``w_q`` (in, out) int8 with per-output-channel
    ``w_scale`` (out,).  Activations quantize per-token on the fly; both
    scales fold on the output tile.  Returns f32."""
    qx, sx = _q8_rowwise(x)
    y = lax.dot_general(qx, w_q, (((x.ndim - 1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * sx * w_scale.astype(jnp.float32)


@jax.custom_vjp
def _qdot_int8(x: jax.Array, w: jax.Array) -> jax.Array:
    y, _ = _qdot_int8_fwd(x, w)
    return y


def _qdot_int8_fwd(x, w):
    qx, sx = _q8_rowwise(x)
    qw, sw = _q8_colwise(w)
    y = lax.dot_general(qx, qw, (((x.ndim - 1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
    # residuals are the full-precision operands: the backward's transposed
    # contractions need scales over DIFFERENT axes (a per-channel scale
    # must not span the contraction), so the forward codes can't be reused
    return (y.astype(jnp.float32) * sx * sw, (x, w))


def _qdot_int8_bwd(res, dy):
    x, w = res
    x2 = x.reshape(-1, x.shape[-1])          # (N, in)
    dy2 = dy.reshape(-1, dy.shape[-1])       # (N, out)
    # dx = dy @ w.T — contraction over 'out': dy per-row, w per-'in'-row
    # (w's rows span the out dim, so _q8_rowwise gives exactly the
    # (in, 1) scales this contraction needs — one quantizer, both uses)
    qdy_r, sdy_r = _q8_rowwise(dy)
    qw_r, sw_r = _q8_rowwise(w)
    dx = lax.dot_general(qdy_r, qw_r.T,
                         (((dy.ndim - 1,), (0,)), ((), ())),
                         preferred_element_type=jnp.int32)
    dx = (dx.astype(jnp.float32) * sdy_r * sw_r.reshape(1, -1)
          ).reshape(x.shape).astype(x.dtype)
    # dw = x.T @ dy — contraction over rows: both per-COLUMN scales
    qxc, sxc = _q8_colwise(x2)               # scales (1, in)
    qdyc, sdyc = _q8_colwise(dy2)            # scales (1, out)
    dw = lax.dot_general(qxc.T, qdyc, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.int32)
    dw = (dw.astype(jnp.float32) * sxc.T * sdyc).astype(w.dtype)
    return dx, dw


_qdot_int8.defvjp(_qdot_int8_fwd, _qdot_int8_bwd)


# ---------------------------------------------------------------------------
# fp8: e4m3 fwd / e5m2 bwd with delayed activation scaling
# ---------------------------------------------------------------------------

def _cast_fp8(x: jax.Array, amax: jax.Array, fmt_max: float, dtype
              ) -> Tuple[jax.Array, jax.Array]:
    """Scale ``x`` so ``amax`` maps to the format max, saturate, cast.
    Returns (codes, scale) with ``codes / scale`` reconstructing x.
    ``amax <= 0`` means UNCALIBRATED (a fresh delayed-scaling history):
    scale 1.0 — coarser resolution but no saturation, the safe warmup
    until the first real observation lands in the history."""
    amax = amax.astype(jnp.float32)
    scale = jnp.where(amax > _AMAX_TINY, fmt_max / jnp.maximum(
        amax, _AMAX_TINY), 1.0)
    q = jnp.clip(x.astype(jnp.float32) * scale,
                 -fmt_max, fmt_max).astype(dtype)
    return q, scale


@jax.custom_vjp
def _qdot_fp8(x: jax.Array, w: jax.Array, a_amax: jax.Array) -> jax.Array:
    y, _ = _qdot_fp8_fwd(x, w, a_amax)
    return y


def _qdot_fp8_fwd(x, w, a_amax):
    qx, sx = _cast_fp8(x, a_amax, E4M3_MAX, jnp.float8_e4m3fn)
    qw, sw = _cast_fp8(w, tensor_amax(w), E4M3_MAX, jnp.float8_e4m3fn)
    y = _dot_q(qx, qw, jnp.float32) / (sx * sw)
    # keep the fp8 CODES (not x/w): the backward contracts against
    # exactly what the forward multiplied, and they are 1/4 the bytes
    return y, (qx, sx, qw, sw)


def _qdot_fp8_bwd(res, dy):
    qx, sx, qw, sw = res
    qdy, sdy = _cast_fp8(dy, tensor_amax(dy), E5M2_MAX, jnp.float8_e5m2)
    # dx = dy @ w.T, dw = x.T @ dy — both in the quantized domain
    dx = _dot_q(qdy, qw.T, jnp.float32) / (sdy * sw)
    qx2 = qx.reshape(-1, qx.shape[-1])
    qdy2 = qdy.reshape(-1, qdy.shape[-1])
    dw = _dot_q(qx2.T, qdy2, jnp.float32) / (sx * sdy)
    # the delayed amax is calibration state, not a differentiable input
    return dx.reshape(qx.shape).astype(jnp.float32), dw, jnp.zeros(())


_qdot_fp8.defvjp(_qdot_fp8_fwd, _qdot_fp8_bwd)


# ---------------------------------------------------------------------------
# the public seam
# ---------------------------------------------------------------------------

def qdot(x: jax.Array, w: jax.Array, *, fmt: str,
         scales: Optional[jax.Array] = None) -> jax.Array:
    """Low-precision dense contraction ``x @ w`` (w: (in, out)) in format
    ``fmt``, differentiable with a low-precision backward.

    ``scales`` is the fp8 delayed activation amax (f32 scalar from
    :func:`delayed_amax`); None falls back to current scaling (amax of
    ``x`` computed in place — the eval/decode path, where there is no
    calibration state to thread).  int8 is always dynamically scaled.
    Returns f32 (callers fold the compute-dtype cast + bias).  Operands
    enter the quantizers through an f32 cast so the custom_vjp
    cotangents have one well-defined dtype; the cast's own vjp restores
    the caller's param/activation dtype on the way back."""
    x = x.astype(jnp.float32)
    w = w.astype(jnp.float32)
    if fmt == "int8":
        return _qdot_int8(x, w)
    if fmt == "fp8":
        a = scales if scales is not None else tensor_amax(x)
        return _qdot_fp8(x, w, jnp.asarray(a, jnp.float32))
    if fmt == "bf16":
        raise ValueError("qdot is the quantized seam; bf16 is the plain "
                         "jnp.matmul path (models.core.Linear)")
    raise ValueError(f"unknown qdot format {fmt!r}; have {FORMATS}")


# ---------------------------------------------------------------------------
# fp8 delayed-scaling calibration state
# ---------------------------------------------------------------------------

def model_format(model) -> str:
    """The model's matmul format ('bf16' when the seam is off / the
    architecture does not thread it)."""
    cfg = getattr(model, "cfg", None)
    return getattr(cfg, "matmul_dtype", "bf16") or "bf16"


def quant_roles(model) -> Tuple[str, ...]:
    """The model's fp8 tensor roles (one amax history each)."""
    hook = getattr(model, "quant_roles", None)
    return tuple(hook()) if hook is not None else ()


def init_qstate(model, history: int = HISTORY) -> Pytree:
    """Fresh calibration state for an fp8 model: per-role amax history
    vectors, init 0.0 = UNCALIBRATED (qdot's fp8 cast falls back to
    scale 1.0 — safe, unsaturated — until the first observation lands;
    from step 2 the delayed max is real).  () for non-fp8 models, so the
    default ``TrainState.qstate`` stays leaf-free and bf16/int8
    checkpoints are byte-identical to pre-seam ones."""
    if model_format(model) != "fp8":
        return ()
    return {"amax": {r: jnp.zeros((history,), jnp.float32)
                     for r in quant_roles(model)}}


def qstate_specs(model, spec) -> Pytree:
    """A pytree of ``spec`` (e.g. ``P()``) mirroring the model's qstate —
    the shard_map/jit in_specs entry for the calibration leaves (always
    replicated: scalar-ish histories, trivially identical on every
    replica because observations are pmax'd before entering)."""
    if model_format(model) != "fp8":
        return ()
    return {"amax": {r: spec for r in quant_roles(model)}}


def delayed_amax(qstate: Pytree) -> Dict[str, jax.Array]:
    """role -> delayed amax (max over the history window) — the scales
    argument each Linear reads at the top of the step."""
    return {r: jnp.max(h) for r, h in qstate["amax"].items()}


def update_qstate(qstate: Pytree, observed: Dict[str, jax.Array]) -> Pytree:
    """Roll each role's history one slot and record the step's observed
    amax.  Non-finite observations (an overflowed forward — e.g. the step
    the skip guard rejects) are dropped: the slot re-records the current
    delayed amax instead, so one bad step cannot poison the scales."""
    new = {}
    for r, h in qstate["amax"].items():
        obs = jnp.asarray(observed[r], jnp.float32)
        obs = jnp.where(jnp.isfinite(obs), obs, jnp.max(h))
        new[r] = jnp.concatenate([obs[None], h[:-1]])
    return {"amax": new}
