"""Pallas TPU kernels for the hot ops.

The reference's compute path is torch's C++/ATen kernels (SURVEY.md §2.4 —
no in-repo native code); the TPU-native equivalent of "hand-tuned hot op"
is a Pallas kernel lowered through Mosaic onto the MXU/VPU.  This module
provides:

* **flash_attention** — blocked causal attention with online softmax.
  Never materializes the (T, T) score matrix: each q-block streams over
  k/v-blocks in VMEM, carrying running (max, denominator, accumulator) —
  the FlashAttention recurrence.  Causal blocks above the diagonal are
  skipped entirely (the fori_loop upper bound shrinks per q-block), saving
  ~2x FLOPs at long T.  O(T) memory per head instead of O(T^2).
* **fused_layernorm** — single-pass LayerNorm on the VPU; one read of x
  per row instead of XLA's separate mean/var/normalize passes when fusion
  declines.

Both run in interpreter mode on CPU (tests, SURVEY.md §4's fake-device
strategy) and compiled through Mosaic on TPU.  The backward pass of
flash_attention is also Pallas: the forward additionally emits the per-row
logsumexp, and two backward kernels (dq; dk+dv) recompute the probability
blocks from (q, k, lse) in VMEM — the standard FlashAttention-2 backward
split, no (T, T) buffer anywhere.  ``_blocked_attention_reference`` keeps
the same math in plain JAX as the cross-check for tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some non-TPU builds; interpret mode needs pl only
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# Masking modes.  "causal" keeps k_pos <= q_pos (the standard triangle);
# "causal_exclusive" keeps k_pos < q_pos — the striped-ring case
# (parallel.sequence.striped_ring_flash_attention): with tokens laid out
# round-robin over the ring, the block pair (my_rank, src_rank) is EXACTLY
# the inclusive triangle when src <= my and the exclusive one when
# src > my, so every ring step does half work on every device.  Exclusive
# mode can leave a q-row with no attendable key (row 0 of the whole
# shard): such rows exit with output 0 and lse = NEG_INF, which the ring
# merge treats as "no contribution" — the same convention as its
# skip_block.
_MASK_MODES = ("none", "causal", "causal_exclusive")


def _resolve_mask(causal: bool, mask_mode: Optional[str]) -> str:
    mode = mask_mode if mask_mode is not None else (
        "causal" if causal else "none")
    if mode not in _MASK_MODES:
        raise ValueError(f"mask_mode must be one of {_MASK_MODES}, "
                         f"got {mode!r}")
    return mode


# ==========================================================================
# Flash attention
# ==========================================================================

def _k_block_hi(mask: str, qi, block_q: int, block_k: int,
                num_k_blocks: int):
    """Exclusive upper bound on the k-block loop for one q-block: blocks
    entirely above the (inclusive or exclusive) diagonal are never read."""
    if mask == "none":
        return num_k_blocks
    # highest attendable k index: last q row is (qi+1)*Bq - 1; inclusive
    # attends k <= that, exclusive k < that
    last_k = (qi + 1) * block_q - (1 if mask == "causal" else 2)
    return lax.min(num_k_blocks,
                   lax.max(0, lax.div(last_k + block_k, block_k)))


def _mask_scores(mask: str, s, q_pos, k_pos):
    if mask == "none":
        return s
    keep = (k_pos <= q_pos) if mask == "causal" else (k_pos < q_pos)
    return jnp.where(keep, s, NEG_INF)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
                      block_k: int, seq_len: int, mask: str,
                      scale: float):
    """Grid: (batch*heads, T // block_q).  Refs (block-local):
    q (1, block_q, D), k/v (1, T, D), o (1, block_q, D), lse (1, 1, block_q).

    lse rides in a (BH, 1, T) layout: Mosaic requires the last two dims of
    every block shape to be (8, 128)-divisible or equal to the array dims,
    which a (1, block_q) block over (BH, T) violates (the leading 1 is a
    grid dim).  With the singleton axis the block's trailing dims are
    (1, block_q) against array dims (1, T) — legal."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (Bq, D)
    d = q.shape[-1]
    num_k_blocks = seq_len // block_k
    hi = _k_block_hi(mask, qi, block_q, block_k, num_k_blocks)

    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32,
                                                (block_q, block_k), 0)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (Bq, Bk)
        k_pos = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = _mask_scores(mask, s, q_pos, k_pos)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + p.sum(axis=-1, keepdims=True)
        acc_new = corr * acc + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = lax.fori_loop(0, hi, body, (acc0, m0, l0))
    # exclusive mode can leave a row with NO attendable key (its m never
    # left NEG_INF — every seen score was the mask fill, or the loop never
    # ran): emit output 0 / lse NEG_INF, the ring merge's "no
    # contribution" convention.  Inclusive/none modes never hit this.
    empty = m < (NEG_INF * 0.5)
    l_safe = jnp.where(empty, 1.0, l)
    o_ref[0] = jnp.where(empty, 0.0, acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.where(empty, NEG_INF, m + jnp.log(l_safe))[:, 0]


def _heads_major(x: jax.Array) -> jax.Array:
    """(B, T, H, D) -> (B*H, T, D): contiguous per-head rows for kernels."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _heads_minor(x: jax.Array, b: int, h: int) -> jax.Array:
    """(B*H, T, D) -> (B, T, H, D)."""
    _, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _resolve_blocks(t: int, block_q: int, block_k: int):
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"seq_len {t} not divisible by blocks "
                         f"({block_q}, {block_k})")
    return block_q, block_k


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
                   block_q: int, block_k: int,
                   interpret: Optional[bool],
                   mask_mode: Optional[str] = None):
    """q/k/v: (B, T, H, D) -> out (B, T, H, D), lse (B*H, T) float32."""
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    block_q, block_k = _resolve_blocks(t, block_q, block_k)
    if interpret is None:
        interpret = _interpret_default()
    qh, kh, vh = _heads_major(q), _heads_major(k), _heads_major(v)

    kernel = functools.partial(_flash_fwd_kernel, block_q=block_q,
                               block_k=block_k, seq_len=t,
                               mask=_resolve_mask(causal, mask_mode),
                               scale=scale)
    mem = {} if not _HAS_PLTPU else {"memory_space": pltpu.VMEM}
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0), **mem),
            pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0), **mem),
            pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0), **mem),
            pl.BlockSpec((1, 1, block_q), lambda bh, i: (bh, 0, i), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, t), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    return _heads_minor(out, b, h), lse.reshape(b * h, t)


def _blocked_attention_reference(q, k, v, causal: bool, block_k: int):
    """Same math as the kernel in plain JAX (for the VJP): q-rows attend to
    k/v in blocks via lax.scan — O(T * block_k) live memory, XLA-fusable."""
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    q_pos = jnp.arange(t)[:, None]

    num_blocks = t // block_k
    kb = kf.reshape(b, num_blocks, block_k, h, d)
    vb = vf.reshape(b, num_blocks, block_k, h, d)

    def step(carry, inp):
        acc, m, l = carry
        kj, vj, j = inp
        s = jnp.einsum("bthd,bshd->bhts", qf, kj)
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where((k_pos <= q_pos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + p.sum(-1, keepdims=True)
        acc_new = corr[..., 0][..., None] * acc + jnp.einsum(
            "bhts,bshd->bthd", p, vj).transpose(0, 2, 1, 3)
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((b, h, t, d), jnp.float32)
    m0 = jnp.full((b, h, t, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t, 1), jnp.float32)
    (acc, m, l), _ = lax.scan(
        step, (acc0, m0, l0),
        (kb.transpose(1, 0, 2, 3, 4), vb.transpose(1, 0, 2, 3, 4),
         jnp.arange(num_blocks)))
    out = acc / l[..., 0][..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# --------------------------------------------------------------------------
# Backward kernels (FlashAttention-2 split: one kernel accumulates dq over
# k-blocks, one accumulates dk/dv over q-blocks; p is recomputed from
# (q, k, lse), delta = rowsum(do * o) is precomputed outside).
# --------------------------------------------------------------------------

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_q: int, block_k: int, seq_len: int,
                         mask: str, scale: float):
    """Grid: (B*H, T // block_q).  q/do/dq blocks (1, block_q, D); k/v full
    rows (1, T, D); lse/delta blocks (1, 1, block_q) float32 (the singleton
    axis keeps the trailing block dims Mosaic-legal, see _flash_fwd_kernel)."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)[:, None]     # (Bq, 1)
    delta = delta_ref[0, 0].astype(jnp.float32)[:, None]
    d = q.shape[-1]
    num_k_blocks = seq_len // block_k
    hi = _k_block_hi(mask, qi, block_q, block_k, num_k_blocks)
    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32,
                                                (block_q, block_k), 0)
    # exclusive mode marks no-key rows with lse = NEG_INF; exp(s - lse)
    # would blow up there, and their true gradient is 0
    live = lse > (NEG_INF * 0.5)
    lse_safe = jnp.where(live, lse, 0.0)

    def body(j, dq_acc):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = _mask_scores(mask, s, q_pos, k_pos)
        p = jnp.where(live, jnp.exp(s - lse_safe), 0.0)   # (Bq, Bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq_acc + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, hi, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, block_k: int,
                          seq_len: int, mask: str, scale: float):
    """Grid: (B*H, T // block_k).  k/v/dk/dv blocks (1, block_k, D);
    q/do full rows (1, T, D); lse/delta full rows (1, 1, T) float32."""
    kj = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                      # (Bk, D)
    v = v_ref[0].astype(jnp.float32)
    d = k.shape[-1]
    num_q_blocks = seq_len // block_q
    # causal (either diagonal): k-block kj only feeds q rows >= kj*block_k
    # (exclusive needs strictly greater — the shared bound just admits one
    # nearly-masked extra block)
    lo = 0 if mask == "none" else lax.div(kj * block_k, block_q)
    k_pos = kj * block_k + lax.broadcasted_iota(jnp.int32,
                                                (block_q, block_k), 1)

    def body(i, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        # slice from the refs (Mosaic lowers pl.ds ref reads; value-level
        # lax.dynamic_slice has no TPU lowering rule)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)].astype(
            jnp.float32)[:, None]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)].astype(
            jnp.float32)[:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = i * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        s = _mask_scores(mask, s, q_pos, k_pos)
        live = lse > (NEG_INF * 0.5)  # no-key rows: lse = NEG_INF, grad 0
        p = jnp.where(live, jnp.exp(s - jnp.where(live, lse, 0.0)), 0.0)
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                     # (Bq, Bk)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(lo, num_q_blocks, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal: bool, block_q: int,
                    block_k: int, interpret: Optional[bool],
                    g_lse: Optional[jax.Array] = None,
                    mask_mode: Optional[str] = None):
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    block_q, block_k = _resolve_blocks(t, block_q, block_k)
    if interpret is None:
        interpret = _interpret_default()
    qh, kh, vh = _heads_major(q), _heads_major(k), _heads_major(v)
    doh = _heads_major(g)
    # delta_i = sum_j p_ij * dp_ij = rowsum(do * o): one fused elementwise
    # reduce in XLA, shared by both kernels.  lse/delta travel as
    # (BH, 1, T) so every block shape's trailing dims stay Mosaic-legal.
    #
    # A cotangent on the lse OUTPUT (flash_attention_with_lse) folds into
    # the same kernels: d lse_i / d s_ij = p_ij, so
    # ds_ij = p_ij * (dp_ij - delta_i + g_lse_i) — i.e. shift delta by
    # -g_lse and nothing else changes (dv is lse-independent).
    delta = (doh.astype(jnp.float32)
             * _heads_major(out).astype(jnp.float32)).sum(-1)  # (BH, T)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    lse3 = lse.reshape(b * h, 1, t)
    delta3 = delta.reshape(b * h, 1, t)

    mem = {} if not _HAS_PLTPU else {"memory_space": pltpu.VMEM}
    row = dict(block_q=block_q, block_k=block_k, seq_len=t,
               mask=_resolve_mask(causal, mask_mode), scale=scale)
    full = lambda spec_t: pl.BlockSpec((1, spec_t, d),
                                       lambda bh, i: (bh, 0, 0), **mem)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **row),
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0), **mem),
            full(t), full(t),
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0), **mem),
            pl.BlockSpec((1, 1, block_q), lambda bh, i: (bh, 0, i), **mem),
            pl.BlockSpec((1, 1, block_q), lambda bh, i: (bh, 0, i), **mem),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0),
                               **mem),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh, doh, lse3, delta3)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **row),
        grid=(b * h, t // block_k),
        in_specs=[
            full(t),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0), **mem),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0), **mem),
            full(t),
            pl.BlockSpec((1, 1, t), lambda bh, j: (bh, 0, 0), **mem),
            pl.BlockSpec((1, 1, t), lambda bh, j: (bh, 0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0), **mem),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t, d), v.dtype),
        ],
        interpret=interpret,
    )(qh, kh, vh, doh, lse3, delta3)
    return (_heads_minor(dq, b, h), _heads_minor(dk, b, h),
            _heads_minor(dv, b, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Blocked attention, Pallas forward + Pallas backward.
    q/k/v: (B, T, H, D)."""
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k,
                           interpret)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                             causal: bool = True, block_q: int = 128,
                             block_k: int = 128,
                             interpret: Optional[bool] = None,
                             mask_mode: Optional[str] = None
                             ) -> Tuple[jax.Array, jax.Array]:
    """Like :func:`flash_attention` but also returns the per-row logsumexp
    ``lse`` (B*H, T) float32 — the building block for blockwise/ring
    composition (parallel.sequence.ring_flash_attention): partial outputs
    from different K/V blocks merge exactly via their lse weights.  Both
    outputs are differentiable; the lse cotangent rides the same Mosaic
    backward kernels as a ``delta`` shift (see _flash_backward).

    ``mask_mode`` overrides ``causal``: "none" / "causal" /
    "causal_exclusive" (strictly-below-diagonal — the striped-ring block
    case; rows with no attendable key return output 0 / lse NEG_INF)."""
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                          mask_mode)


def _fal_fwd(q, k, v, causal, block_q, block_k, interpret, mask_mode):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                              mask_mode)
    return (out, lse), (q, k, v, out, lse)


def _fal_bwd(causal, block_q, block_k, interpret, mask_mode, res, ct):
    q, k, v, out, lse = res
    g_out, g_lse = ct
    return _flash_backward(q, k, v, out, lse, g_out, causal, block_q,
                           block_k, interpret, g_lse=g_lse,
                           mask_mode=mask_mode)


flash_attention_with_lse.defvjp(_fal_fwd, _fal_bwd)


# ==========================================================================
# Paged attention (serving: decode + chunked prefill over a block pool)
# ==========================================================================

def _paged_attn_kernel(tables_ref, lens_ref, starts_ref, q_ref, k_hbm,
                       v_hbm, *rest, block_size: int, kv_heads: int,
                       groups: int, width: int, scale: float,
                       quant: bool):
    """Grid: (streams,).  Each program walks ITS stream's allocated
    block-table entries — ``ceil(len/block_size)`` of them, a dynamic
    ``fori_loop`` bound — double-buffering pool blocks HBM→VMEM with
    ``make_async_copy`` (block ``j+1``'s DMA is in flight while ``j``
    computes) and carrying the online-softmax (max, denom, acc) in the
    loop.  KV heads are unrolled in-program: one block fetch serves every
    head (a (stream, kv_head) grid would DMA each block ``kv_heads``
    times).

    Refs: ``tables (S, MB)`` / ``lens (S,)`` / ``starts (S,)`` ride
    scalar prefetch (SMEM) — runtime VALUES, not compile-time constants,
    so table churn and length growth re-run the same compiled kernel.
    ``q (1, KV, W·G, hd)`` in VMEM; ``k``/``v`` pools (and int8 scale
    pools when ``quant``) stay UNBLOCKED in ANY/HBM — only the blocks a
    stream actually owns ever cross into VMEM, which is the bandwidth
    half of the win (the FLOPs half is the loop bound).  Scratch: 2-slot
    VMEM landing buffers per pool operand + a (2, n_operands) DMA
    semaphore array.

    Blocks past a stream's true length (and every block of an inactive
    ``len=0`` lane, whose loop never runs) contribute NOTHING.  Within
    the last live block the tail positions ``>= len`` are masked, so the
    sink block's frozen garbage is never attended.  int8 pools
    dequantize ON LOAD (``k·k_scale`` per (position, head) — the same
    per-position scheme the gathered path applies to its logits/probs,
    reassociated).  A ``len=0`` lane exits with output 0, the flash
    kernels' "no contribution" convention."""
    if quant:
        (ks_hbm, vs_hbm, o_ref,
         k_buf, v_buf, ks_buf, vs_buf, sem) = rest
    else:
        o_ref, k_buf, v_buf, sem = rest
    s = pl.program_id(0)
    ln = lens_ref[s]
    nb = lax.div(ln + block_size - 1, block_size)
    rows = width * groups

    def _copies(j):
        slot = lax.rem(j, 2)
        blk = tables_ref[s, j]
        ops = [
            pltpu.make_async_copy(k_hbm.at[blk], k_buf.at[slot],
                                  sem.at[slot, 0]),
            pltpu.make_async_copy(v_hbm.at[blk], v_buf.at[slot],
                                  sem.at[slot, 1]),
        ]
        if quant:
            ops += [
                pltpu.make_async_copy(ks_hbm.at[blk], ks_buf.at[slot],
                                      sem.at[slot, 2]),
                pltpu.make_async_copy(vs_hbm.at[blk], vs_buf.at[slot],
                                      sem.at[slot, 3]),
            ]
        return ops

    # rows are (W, G) flattened: row r is query column r // groups
    k_off = lax.broadcasted_iota(jnp.int32, (rows, block_size), 1)
    q_pos = starts_ref[s] + lax.broadcasted_iota(
        jnp.int32, (rows, block_size), 0) // groups

    def body(j, carry):
        acc, m, l = carry

        @pl.when(j + 1 < nb)
        def _prefetch():
            for c in _copies(j + 1):
                c.start()

        for c in _copies(j):
            c.wait()
        slot = lax.rem(j, 2)
        k = k_buf[slot].astype(jnp.float32)          # (bs, KV, hd)
        v = v_buf[slot].astype(jnp.float32)
        if quant:
            k = k * ks_buf[slot].astype(jnp.float32)[..., None]
            v = v * vs_buf[slot].astype(jnp.float32)[..., None]
        k_pos = j * block_size + k_off
        keep = (k_pos < ln) & (k_pos <= q_pos)       # (rows, bs)
        for h in range(kv_heads):
            q = q_ref[0, h].astype(jnp.float32) * scale    # (rows, hd)
            sc = jax.lax.dot_general(
                q, k[:, h, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # (rows, bs)
            sc = jnp.where(keep, sc, NEG_INF)
            m_new = jnp.maximum(m[h], sc.max(axis=-1, keepdims=True))
            # a row with no attendable key in THIS block keeps its prior
            # max; every live row sees position 0 in block 0, so m is
            # finite before the running exp() can ever see exp(0) garbage
            p = jnp.exp(sc - m_new)
            corr = jnp.exp(m[h] - m_new)
            l = l.at[h].set(corr * l[h] + p.sum(axis=-1, keepdims=True))
            acc = acc.at[h].set(corr * acc[h] + jax.lax.dot_general(
                p, v[:, h, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
            m = m.at[h].set(m_new)
        return acc, m, l

    hd = q_ref.shape[-1]
    acc0 = jnp.zeros((kv_heads, rows, hd), jnp.float32)
    m0 = jnp.full((kv_heads, rows, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((kv_heads, rows, 1), jnp.float32)

    @pl.when(nb == 0)
    def _inactive():
        o_ref[0] = jnp.zeros_like(o_ref[0])

    @pl.when(nb > 0)
    def _walk():
        for c in _copies(0):
            c.start()
        acc, m, l = lax.fori_loop(0, nb, body, (acc0, m0, l0))
        empty = m < (NEG_INF * 0.5)
        l_safe = jnp.where(empty, 1.0, l)
        o_ref[0] = jnp.where(empty, 0.0, acc / l_safe).astype(o_ref.dtype)


def paged_attention(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                    tables: jax.Array, lengths: jax.Array,
                    starts: jax.Array, *,
                    k_scale: Optional[jax.Array] = None,
                    v_scale: Optional[jax.Array] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused paged attention: reads K/V straight from the serving block
    pool through per-stream block tables and reduces over each stream's
    TRUE length instead of the table capacity ``max_blocks·block_size``
    (serve/paged_kv.py's gathered path; ROADMAP 1(b)'s FLOPs win).

    One kernel covers the family: ``width == 1`` is the batched decode
    step (each stream's single query at position ``lengths-1``),
    ``width > 1`` is a chunked-prefill bucket (rows at absolute positions
    ``starts .. starts+width-1``, flash-style causal within the chunk).

    * ``q``: (streams, width, n_heads, head_dim) — GQA folds in-kernel
      (``n_heads`` must be a multiple of the pool's ``kv_heads``).
    * ``k_pool``/``v_pool``: (num_blocks, block_size, kv_heads, head_dim)
      — f32/bf16, or int8 with ``k_scale``/``v_scale``
      (num_blocks, block_size, kv_heads) f32 dequantized on load.
    * ``tables``: (streams, max_blocks) int32 pool indices; unallocated
      entries point at the sink block and are NEVER walked (the block
      loop stops at ``ceil(length/block_size)``).
    * ``lengths``: (streams,) int32 attendable keys per stream (0 = an
      inactive lane: zero blocks walked, zero blocks fetched, output 0).
    * ``starts``: (streams,) int32 absolute position of each stream's
      first query row (decode passes ``lengths - 1``).

    Tables/lengths/starts are traced scalar-prefetch operands: block-table
    churn (admission, growth, eviction) re-runs the SAME compiled kernel
    — pinned by tests/test_paged_attn.py's compile-count test."""
    s_n, width, n_heads, hd = q.shape
    nb, bs, kv_heads, hd_k = k_pool.shape
    if hd_k != hd:
        raise ValueError(f"head_dim mismatch: q {hd} vs pool {hd_k}")
    if n_heads % kv_heads:
        raise ValueError(f"n_heads {n_heads} not a multiple of kv_heads "
                         f"{kv_heads}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("int8 pools need BOTH k_scale and v_scale")
    quant = k_scale is not None
    groups = n_heads // kv_heads
    scale = 1.0 / (hd ** 0.5)
    if interpret is None:
        interpret = _interpret_default()

    if not _HAS_PLTPU:  # pragma: no cover - exercised only on odd builds
        # unlike the flash kernels (plain grids, no DMA), the paged
        # kernel's scalar-prefetch spec, HBM refs and async copies live
        # in pallas.tpu even in interpret mode — no pl-only fallback
        raise RuntimeError("paged_attention needs jax.experimental."
                           "pallas.tpu (scalar prefetch + async DMA)")

    # (S, W, H, hd) -> (S, KV, W·G, hd): per-kv-head query rows contiguous
    qk = q.reshape(s_n, width, kv_heads, groups, hd)
    qk = qk.transpose(0, 2, 1, 3, 4).reshape(s_n, kv_heads,
                                             width * groups, hd)

    row_map = lambda s, tbl, lns, sts: (s, 0, 0, 0)      # noqa: E731
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)      # stays in HBM
    in_specs = [
        pl.BlockSpec((1, kv_heads, width * groups, hd), row_map),
        any_spec, any_spec,
    ]
    operands = [qk, k_pool, v_pool]
    n_dma = 2
    scratch = [
        pltpu.VMEM((2, bs, kv_heads, hd), k_pool.dtype),
        pltpu.VMEM((2, bs, kv_heads, hd), v_pool.dtype),
    ]
    if quant:
        in_specs += [any_spec, any_spec]
        operands += [k_scale, v_scale]
        scratch += [pltpu.VMEM((2, bs, kv_heads), k_scale.dtype),
                    pltpu.VMEM((2, bs, kv_heads), v_scale.dtype)]
        n_dma = 4
    scratch.append(pltpu.SemaphoreType.DMA((2, n_dma)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(s_n,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kv_heads, width * groups, hd), row_map),
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_attn_kernel, block_size=bs, kv_heads=kv_heads,
            groups=groups, width=width, scale=scale, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (s_n, kv_heads, width * groups, hd), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      starts.astype(jnp.int32), *operands)
    # (S, KV, W·G, hd) -> (S, W, H, hd)
    out = out.reshape(s_n, kv_heads, width, groups, hd)
    return out.transpose(0, 2, 1, 3, 4).reshape(s_n, width, n_heads, hd)


# ==========================================================================
# Fused LayerNorm
# ==========================================================================

def _ln_kernel(x_ref, scale_ref, bias_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    mean = x.mean(-1, keepdims=True)
    xc = x - mean
    var = (xc * xc).mean(-1, keepdims=True)
    y = xc * lax.rsqrt(var + eps)
    o_ref[:] = (y * scale_ref[:].astype(jnp.float32)
                + bias_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def fused_layernorm(x: jax.Array, scale: jax.Array, bias: jax.Array,
                    eps: float = 1e-5, block_rows: int = 256,
                    interpret: Optional[bool] = None) -> jax.Array:
    """LayerNorm over the last dim; rows processed in VMEM blocks."""
    if interpret is None:
        interpret = _interpret_default()
    lead = x.shape[:-1]
    d = x.shape[-1]
    rows = 1
    for s in lead:
        rows *= s
    x2 = x.reshape(rows, d)
    block_rows = min(block_rows, rows)
    if rows % block_rows:
        block_rows = 1  # degenerate but correct fallback
    mem = {} if not _HAS_PLTPU else {"memory_space": pltpu.VMEM}
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0), **mem),
            pl.BlockSpec((d,), lambda i: (0,), **mem),
            pl.BlockSpec((d,), lambda i: (0,), **mem),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(x2, scale, bias)
    return out.reshape(*lead, d)
