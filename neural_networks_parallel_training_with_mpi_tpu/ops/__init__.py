"""Numerical ops: losses, optimizers, and Pallas TPU kernels."""

from . import losses, optim
